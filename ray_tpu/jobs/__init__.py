"""Job submission: run driver entrypoints on the cluster, track lifecycle.

Parity target: the reference's job submission stack
(reference: python/ray/job_submission/ JobSubmissionClient/JobStatus,
dashboard/modules/job/job_manager.py JobManager + per-job supervisor
actor), re-designed small: a named JobManager actor owns the job table
(write-through to the head KV, so jobs survive head restarts); each job
runs as a supervisor-actor-owned SUBPROCESS with its runtime env applied,
stdout/stderr captured to a per-job log file and its status reported back.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import subprocess
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu

JOB_MANAGER_NAME = "_rtpu_job_manager"


class JobStatus(str, enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    def is_terminal(self) -> bool:
        return self in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                        JobStatus.STOPPED)


@dataclasses.dataclass
class JobInfo:
    submission_id: str
    entrypoint: str
    status: str
    message: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    log_path: str = ""


class JobSupervisor:
    """One per job: runs the entrypoint subprocess and reports status
    (reference: job supervisor actor, job_manager.py)."""

    def __init__(self, submission_id: str, entrypoint: str,
                 runtime_env: Optional[Dict[str, Any]], log_path: str,
                 head_addr: str):
        self._id = submission_id
        self._entrypoint = entrypoint
        self._env = runtime_env or {}
        self._log_path = log_path
        self._head_addr = head_addr
        self._proc: Optional[subprocess.Popen] = None
        self._status = JobStatus.PENDING.value
        self._message = ""
        self._stopped = False

    def run(self) -> str:
        """Blocking: runs the entrypoint to completion; returns status."""
        from ray_tpu.core.runtime_env import (apply_to_spawn_env,
                                              validate_runtime_env)

        env = dict(os.environ)
        # The job's driver joins THIS cluster.
        env["RTPU_ADDRESS"] = self._head_addr
        cwd = apply_to_spawn_env(validate_runtime_env(self._env), env)
        os.makedirs(os.path.dirname(self._log_path) or ".", exist_ok=True)
        logf = open(self._log_path, "ab", buffering=0)
        self._status = JobStatus.RUNNING.value
        try:
            self._proc = subprocess.Popen(
                self._entrypoint, shell=True, stdout=logf, stderr=logf,
                env=env, cwd=cwd or os.getcwd())
            rc = self._proc.wait()
        except BaseException as e:  # noqa: BLE001
            self._status = JobStatus.FAILED.value
            self._message = repr(e)
            return self._status
        finally:
            logf.close()
        if self._stopped:
            self._status = JobStatus.STOPPED.value
        elif rc == 0:
            self._status = JobStatus.SUCCEEDED.value
        else:
            self._status = JobStatus.FAILED.value
            self._message = f"entrypoint exited rc={rc}"
        return self._status

    def stop(self) -> bool:
        self._stopped = True
        if self._proc is not None and self._proc.poll() is None:
            try:
                self._proc.terminate()
            except Exception:
                pass
            return True
        return False

    def status(self) -> Dict[str, str]:
        return {"status": self._status, "message": self._message}


class JobManager:
    """The named job-table actor (reference: JobManager)."""

    def __init__(self):
        rt = ray_tpu.core.runtime_context.require_runtime()
        self._head_addr = rt.head_addr
        self._jobs: Dict[str, JobInfo] = {}
        self._supervisors: Dict[str, Any] = {}
        self._load()

    # ------------------------------------------------------- persistence

    def _kv_key(self, job_id: str) -> str:
        return f"job/{job_id}"

    def _persist(self, info: JobInfo) -> None:
        import json

        rt = ray_tpu.core.runtime_context.require_runtime()
        rt.head.retrying_call(
            "kv_put", "__jobs__", self._kv_key(info.submission_id).encode(),
            json.dumps(dataclasses.asdict(info)).encode(), True, timeout=10)

    def _load(self) -> None:
        import json

        rt = ray_tpu.core.runtime_context.require_runtime()
        try:
            keys = rt.head.retrying_call("kv_keys", "__jobs__", b"",
                                         timeout=10)
        except Exception:
            return
        for key in keys or ():
            blob = rt.head.retrying_call("kv_get", "__jobs__", key,
                                         timeout=10)
            if blob:
                info = JobInfo(**json.loads(blob))
                # Jobs that were RUNNING when the manager died are lost
                # processes: mark failed rather than lying.
                if not JobStatus(info.status).is_terminal():
                    info.status = JobStatus.FAILED.value
                    info.message = "job manager restarted mid-job"
                self._jobs[info.submission_id] = info

    # --------------------------------------------------------------- API

    def submit(self, entrypoint: str,
               runtime_env: Optional[Dict[str, Any]] = None,
               submission_id: Optional[str] = None) -> str:
        from ray_tpu.core.config import GLOBAL_CONFIG as cfg

        job_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        if job_id in self._jobs and not JobStatus(
                self._jobs[job_id].status).is_terminal():
            raise ValueError(f"job {job_id!r} already running")
        log_path = os.path.join(cfg.log_dir, f"job-{job_id}.log")
        info = JobInfo(job_id, entrypoint, JobStatus.PENDING.value,
                       start_time=time.time(), log_path=log_path)
        self._jobs[job_id] = info
        self._persist(info)
        supervisor_cls = ray_tpu.remote(JobSupervisor)
        sup = supervisor_cls.options(num_cpus=0, max_concurrency=4).remote(
            job_id, entrypoint, runtime_env, log_path, self._head_addr)
        self._supervisors[job_id] = sup
        run_ref = sup.run.remote()
        threading.Thread(target=self._watch, args=(job_id, run_ref),
                         daemon=True).start()
        info.status = JobStatus.RUNNING.value
        self._persist(info)
        return job_id

    def _watch(self, job_id: str, run_ref) -> None:
        info = self._jobs[job_id]
        try:
            status = ray_tpu.get(run_ref, timeout=None)
            sup = self._supervisors.get(job_id)
            if sup is not None:
                st = ray_tpu.get(sup.status.remote(), timeout=30)
                info.message = st.get("message", "")
            info.status = status
        except Exception as e:
            info.status = JobStatus.FAILED.value
            info.message = f"supervisor died: {e}"
        info.end_time = time.time()
        self._persist(info)
        sup = self._supervisors.pop(job_id, None)
        if sup is not None:
            try:
                ray_tpu.kill(sup)
            except Exception:
                pass

    def status(self, job_id: str) -> Optional[Dict[str, Any]]:
        info = self._jobs.get(job_id)
        return dataclasses.asdict(info) if info else None

    def list(self) -> List[Dict[str, Any]]:
        return [dataclasses.asdict(i) for i in self._jobs.values()]

    def stop(self, job_id: str) -> bool:
        sup = self._supervisors.get(job_id)
        if sup is None:
            return False
        return ray_tpu.get(sup.stop.remote(), timeout=30)

    def logs(self, job_id: str, tail_bytes: int = 1 << 20) -> str:
        info = self._jobs.get(job_id)
        if info is None or not os.path.exists(info.log_path):
            return ""
        with open(info.log_path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - tail_bytes))
            return f.read().decode(errors="replace")


def _get_or_start_manager():
    actor_cls = ray_tpu.remote(JobManager)
    return actor_cls.options(name=JOB_MANAGER_NAME, get_if_exists=True,
                             max_concurrency=8, num_cpus=0).remote()


class JobSubmissionClient:
    """Driver-side client (reference: ray.job_submission
    .JobSubmissionClient). Call from a process already attached to the
    cluster (ray_tpu.init)."""

    def __init__(self, address: Optional[str] = None):
        if address is not None:
            ray_tpu.init(address=address, ignore_reinit_error=True)
        self._mgr = _get_or_start_manager()

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   submission_id: Optional[str] = None) -> str:
        return ray_tpu.get(self._mgr.submit.remote(
            entrypoint, runtime_env, submission_id), timeout=120)

    def get_job_status(self, job_id: str) -> JobStatus:
        info = ray_tpu.get(self._mgr.status.remote(job_id), timeout=30)
        if info is None:
            raise KeyError(f"no job {job_id!r}")
        return JobStatus(info["status"])

    def get_job_info(self, job_id: str) -> JobInfo:
        info = ray_tpu.get(self._mgr.status.remote(job_id), timeout=30)
        if info is None:
            raise KeyError(f"no job {job_id!r}")
        return JobInfo(**info)

    def list_jobs(self) -> List[JobInfo]:
        return [JobInfo(**i) for i in
                ray_tpu.get(self._mgr.list.remote(), timeout=30)]

    def stop_job(self, job_id: str) -> bool:
        return ray_tpu.get(self._mgr.stop.remote(job_id), timeout=60)

    def get_job_logs(self, job_id: str) -> str:
        return ray_tpu.get(self._mgr.logs.remote(job_id), timeout=30)

    def wait_until_finish(self, job_id: str, timeout: float = 600.0) -> JobStatus:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            st = self.get_job_status(job_id)
            if st.is_terminal():
                return st
            time.sleep(0.5)
        raise TimeoutError(f"job {job_id} still {st} after {timeout}s")
