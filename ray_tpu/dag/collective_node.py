"""Collective nodes in compiled DAGs: allreduce across actor-method outputs.

Parity target: reference ray.experimental.collective.allreduce
(reference: python/ray/experimental/collective/allreduce.py binding
collective ops into a DAG; python/ray/dag/collective_node.py) — redesigned
for this runtime: the collective executes over the SAME channel substrate
the rest of the compiled DAG uses (shm same-node, push-transfer cross-node),
as a binary-tree reduce+broadcast among the participating actors. No
NCCL-group equivalent is needed host-side; inside one SPMD program
collectives are XLA's job (parallel/spmd.py) — this is the host-tier
cross-actor reduction.

Authoring (mirrors the reference's surface):

    with InputNode() as inp:
        parts = [w.grad.bind(inp) for w in workers]
        reduced = allreduce.bind(parts, op="sum")   # list, one per worker
        outs = [w.apply.bind(r) for w, r in zip(workers, reduced)]
        dag = MultiOutputNode(outs)
"""

from __future__ import annotations

import itertools
from typing import Any, List

from ray_tpu.dag.dag_node import ClassMethodNode, DAGNode

_group_counter = itertools.count()

REDUCE_OPS = ("sum", "prod", "max", "min")


class CollectiveGroupSpec:
    """One collective instance: the participating upstream nodes (one per
    actor) and the reduction op."""

    def __init__(self, upstreams: List[ClassMethodNode], op: str):
        if op not in REDUCE_OPS:
            raise ValueError(f"op must be one of {REDUCE_OPS}, got {op!r}")
        if len(upstreams) < 2:
            raise ValueError("allreduce needs >= 2 participants")
        seen = set()
        for n in upstreams:
            if not isinstance(n, ClassMethodNode):
                raise TypeError(
                    "allreduce participants must be actor-method nodes "
                    f"(got {type(n).__name__})")
            key = n.actor.actor_id.binary()
            if key in seen:
                raise ValueError(
                    "allreduce binds at most one node per actor (the "
                    "reference imposes the same restriction)")
            seen.add(key)
        self.group_id = next(_group_counter)
        self.upstreams = list(upstreams)
        self.op = op
        # Backrefs to every rank's output node, set by bind(): compilation
        # schedules a group ATOMICALLY at its first topo encounter, so it
        # needs all sibling nodes even when only a subset is reachable.
        self.output_nodes: List["CollectiveOutputNode"] = []


class CollectiveOutputNode(DAGNode):
    """Rank r's post-allreduce value: same actor as its upstream, value =
    reduce(op, all upstreams). One per participant."""

    def __init__(self, group: CollectiveGroupSpec, rank: int):
        super().__init__()
        self.group = group
        self.rank = rank
        self.upstream_node = group.upstreams[rank]
        self.actor = self.upstream_node.actor

    def upstream(self) -> List[DAGNode]:
        # Depends on EVERY participant: topo order must place all
        # contributions before any collective output.
        return list(self.group.upstreams)

    def __repr__(self):
        return (f"CollectiveOutputNode(allreduce-{self.group.op} "
                f"rank {self.rank}/{len(self.group.upstreams)})")


class _AllReduce:
    """`allreduce.bind(nodes, op=...)` like the reference module-level API."""

    @staticmethod
    def bind(nodes: List[ClassMethodNode], op: str = "sum"
             ) -> List[CollectiveOutputNode]:
        group = CollectiveGroupSpec(nodes, op)
        group.output_nodes = [CollectiveOutputNode(group, r)
                              for r in range(len(nodes))]
        return list(group.output_nodes)


allreduce = _AllReduce()


def reduce_fn(op: str):
    import numpy as np

    return {
        "sum": np.add, "prod": np.multiply,
        "max": np.maximum, "min": np.minimum,
    }[op]
