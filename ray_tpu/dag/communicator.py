"""Communicator ABC: pluggable tensor transport between DAG actors.

Parity target: reference python/ray/experimental/channel/communicator.py:19
(the backend-pluggable seam the compiled graphs use for NCCL p2p) +
cpu_communicator.py (the test impl). TPU-first stance: INTRA-program tensor
movement belongs to XLA collectives over the mesh (ray_tpu/parallel/) — a
compiled SPMD step never routes tensors through host channels. The
communicator covers the remaining cases: host-side handoff between separate
JAX programs (e.g. pipeline stages owned by different actors on one host)
and CPU-only tests.
"""

from __future__ import annotations

import abc
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.dag.channel import ShmChannel


class Communicator(abc.ABC):
    """Point-to-point send/recv among a fixed group of ranks."""

    @abc.abstractmethod
    def send(self, value: Any, peer_rank: int) -> None: ...

    @abc.abstractmethod
    def recv(self, peer_rank: int) -> Any: ...

    @abc.abstractmethod
    def rank(self) -> int: ...

    @abc.abstractmethod
    def world_size(self) -> int: ...


class CpuCommunicator(Communicator):
    """Shm-channel mesh among n ranks on one node (tests / host handoff).

    Construct ONE spec with `CpuCommunicator.create_group(n)`, pass the
    per-rank communicators to the actors (they serialize by channel ids).
    """

    def __init__(self, my_rank: int, n: int,
                 channels: Dict[tuple, ShmChannel]):
        self._rank = my_rank
        self._n = n
        self._channels = channels
        self._send_seq = {r: 0 for r in range(n)}
        self._recv_seq = {r: 0 for r in range(n)}

    @staticmethod
    def create_group(n: int, capacity: int = 8) -> List["CpuCommunicator"]:
        channels = {
            (src, dst): ShmChannel(uuid.uuid4().bytes, capacity=capacity)
            for src in range(n) for dst in range(n) if src != dst
        }
        return [CpuCommunicator(r, n, channels) for r in range(n)]

    def send(self, value: Any, peer_rank: int) -> None:
        seq = self._send_seq[peer_rank]
        self._send_seq[peer_rank] += 1
        self._channels[(self._rank, peer_rank)].write(value, seq)

    def recv(self, peer_rank: int, timeout: Optional[float] = 60.0) -> Any:
        seq = self._recv_seq[peer_rank]
        self._recv_seq[peer_rank] += 1
        return self._channels[(peer_rank, self._rank)].read(seq, timeout)

    def rank(self) -> int:
        return self._rank

    def world_size(self) -> int:
        return self._n

    def __reduce__(self):
        return (CpuCommunicator, (self._rank, self._n, self._channels))


class JaxHostCommunicator(CpuCommunicator):
    """Same transport, but values that are jax.Arrays are converted to
    numpy for the channel hop and re-placed on the receiver's default
    device — the host-handoff path between separately-compiled JAX programs
    (single-host pipeline stages). Multi-chip tensor traffic inside one
    program should use mesh collectives instead, never this."""

    def send(self, value: Any, peer_rank: int) -> None:
        import jax
        import numpy as np

        if isinstance(value, jax.Array):
            value = np.asarray(value)
        super().send(value, peer_rank)

    def recv(self, peer_rank: int, timeout: Optional[float] = 60.0) -> Any:
        import jax
        import numpy as np

        value = super().recv(peer_rank, timeout)
        if isinstance(value, np.ndarray):
            value = jax.device_put(value)
        return value
