"""Channel error types, shared by every transport.

``ChannelTimeoutError`` carries structured context — which edge, which
seq, how many bytes were in flight, whether the peer was alive at the
time — because the cross-node chaos stress test was de-flaked twice
(PR 8, PR 14) partly on timeouts that were undiagnosable from a bare
"channel read timed out" message.
"""

from __future__ import annotations

from typing import Optional


class ChannelError(RuntimeError):
    pass


class ChannelClosedError(ChannelError):
    """The peer endpoint closed (stop sentinel, teardown, or death)."""


class ChannelTimeoutError(TimeoutError):
    """A channel op exceeded its deadline.

    Attributes (any may be None when the transport cannot know):
      edge            "writer→reader" label of the channel
      seq             the message sequence the op was blocked on
      bytes_in_flight written-but-unconsumed bytes at timeout time
      peer_alive      liveness verdict for the remote endpoint (False =
                      the head's channel registry says it died; the
                      caller should treat the channel as closed)
    """

    def __init__(self, message: str = "channel op timed out", *,
                 edge: Optional[str] = None, seq: Optional[int] = None,
                 bytes_in_flight: Optional[int] = None,
                 peer_alive: Optional[bool] = None):
        self.edge = edge
        self.seq = seq
        self.bytes_in_flight = bytes_in_flight
        self.peer_alive = peer_alive
        parts = [message]
        ctx = []
        if edge is not None:
            ctx.append(f"edge={edge}")
        if seq is not None:
            ctx.append(f"seq={seq}")
        if bytes_in_flight is not None:
            ctx.append(f"bytes_in_flight={bytes_in_flight}")
        if peer_alive is not None:
            ctx.append(f"peer_alive={peer_alive}")
        if ctx:
            parts.append("(" + ", ".join(ctx) + ")")
        super().__init__(" ".join(parts))
