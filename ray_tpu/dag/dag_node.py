"""DAG authoring nodes: `.bind()` graphs over actor methods.

Parity target: reference python/ray/dag/dag_node.py + class_node.py
(ClassMethodNode), input_node.py (InputNode), output_node.py
(MultiOutputNode). Authoring is pure structure — nothing executes until
`experimental_compile` (compiled_dag.py) turns the graph into per-actor
schedules over shm channels.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional

_node_counter = itertools.count()


class DAGNode:
    def __init__(self):
        self._dag_id = next(_node_counter)

    def upstream(self) -> List["DAGNode"]:
        return []

    def experimental_compile(self, **kwargs):
        from ray_tpu.dag.compiled_dag import compile_dag

        return compile_dag(self, **kwargs)

    def execute(self, *args):
        """Convenience: compile on first use, then run (reference allows
        direct .execute on the built dag)."""
        if not hasattr(self, "_compiled"):
            self._compiled = self.experimental_compile()
        return self._compiled.execute(*args)


class InputNode(DAGNode):
    """The driver-supplied per-execution input. Supports context-manager
    syntax mirroring the reference:

        with InputNode() as inp:
            out = actor.fwd.bind(inp)
    """

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc):
        return False


class ClassMethodNode(DAGNode):
    """One bound actor-method call in the graph."""

    def __init__(self, actor_handle, method_name: str, args: tuple,
                 kwargs: Dict[str, Any]):
        super().__init__()
        self.actor = actor_handle
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs

    def upstream(self) -> List[DAGNode]:
        ups = [a for a in self.args if isinstance(a, DAGNode)]
        ups += [v for v in self.kwargs.values() if isinstance(v, DAGNode)]
        return ups

    def __repr__(self):
        return (f"ClassMethodNode({self.method_name} on "
                f"{self.actor.actor_id.hex()[:8]})")


class MultiOutputNode(DAGNode):
    """Fan the DAG out to multiple driver-visible outputs."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__()
        if not outputs:
            raise ValueError("MultiOutputNode needs at least one output")
        self.outputs = list(outputs)

    def upstream(self) -> List[DAGNode]:
        return list(self.outputs)
