"""Cross-node compiled-DAG transport: persistent peer sockets carrying
zero-copy scatter frames.

Parity target: the reference's cross-node mutable-object channels
(RegisterMutableObject/PushMutableObject, node_manager.proto:444-446),
re-designed as a DIRECT peer connection: the reader side runs one
``ChannelEndpoint`` per process (an accept loop on an ephemeral port,
registered once with the head under the channel id), the writer looks
the endpoint up ONCE and then every steady-state send is a single
``sendmsg`` of pickle-5 out-of-band buffers straight onto the socket —
no store put, no node-manager push RPC, no per-message ack object. The
previous design cost 2+ control-plane RPCs and 3 store objects per
message; this costs none of either.

Wire format, writer → reader (one socket per channel edge)::

    hello:  u32 0xC0DE0001 | u32 idlen | channel_id
    data:   u32 size | u8 kind | u64 seq | u64 clock | u32 crc
            | u32 nparts | u32 lens[nparts] | parts...
                                            (size = sum of lens)

``clock``/``crc`` carry the RTPU_DEBUG_CHAN witness's Lamport stamp
and sampled payload checksum (``devtools/chan_debug.py``); both are 0
when the witness is off.

reader → writer (same socket)::

    ack:    u32 0xACACACAC | u64 consumed_seq   (cumulative)

Backpressure is credit-based: the writer admits ``seq`` only while
``seq - acked_through < capacity``; acks are sent when the APPLICATION
consumes a message, not on enqueue, so a stalled reader stalls the
writer by construction. The endpoint enforces per-channel seq
monotonicity on receipt — an inversion or re-delivery is recorded (and
printed as ``RTPU_CHANNEL:``) the same way the RPC witness reports
outbox violations.

Death handling rides the existing report path: the head scrubs channel
registrations when the owning worker dies, so a writer blocked on a
dead reader gets ``peer_alive=False`` context (and
``ChannelClosedError`` once the registry entry is gone) instead of an
opaque timeout.
"""

from __future__ import annotations

import logging
import pickle
import queue
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

from ray_tpu.dag.errors import ChannelClosedError, ChannelTimeoutError
from ray_tpu.dag.ring import KIND_ERR, KIND_OK, KIND_STOP
from ray_tpu.devtools import chan_debug as _chandbg
from ray_tpu.devtools import res_debug as _resdbg
from ray_tpu.devtools.lock_debug import make_lock

_HELLO = 0xC0DE0001
_ACK = 0xACACACAC
_GONE = 0xDEADC0DE  # endpoint -> writer: channel is not served here


def _recv_exact(sock: socket.socket, n: int) -> Optional[memoryview]:
    buf = memoryview(bytearray(n))
    got = 0
    while got < n:
        try:
            r = sock.recv_into(buf[got:])
        except OSError:
            return None
        if not r:
            return None
        got += r
    return buf


class _Inbox:
    """Per-channel receive state on the endpoint."""

    def __init__(self, capacity: int):
        self.q: "queue.Queue" = queue.Queue(maxsize=max(2, capacity) + 2)
        self.conn: Optional[socket.socket] = None
        self.conn_lock = threading.Lock()
        self.last_seq = -1
        self.bytes_received = 0
        self.closed = False


class ChannelEndpoint:
    """Reader-side frame server: one per process, shared by every
    cross-node channel whose reader lives here."""

    chaos_role = "channel"  # fault-injection scope (devtools/chaos.py)

    def __init__(self, host: Optional[str] = None):
        self._inboxes: Dict[bytes, _Inbox] = {}
        self._lock = make_lock("dag.peer.endpoint._lock")
        self._violations: List[dict] = []
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host or "0.0.0.0", 0))
        self._sock.listen(64)
        self._stopped = False
        # The process-wide listener is long-lived BY DESIGN (it serves
        # every channel whose reader lives here): tracked under its own
        # kind, outside LEAK_KINDS — per-channel conns/writer sockets
        # are the leak-audited handles.
        _resdbg.note_acquire("channel_endpoint",
                             key=("endpoint", id(self)), owner=self)
        self._accept_thread = _resdbg.track_thread(threading.Thread(
            target=self._accept_loop, daemon=True,
            name="dag-channel-endpoint"), owner=self)
        self._accept_thread.start()

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def address(self, host: str) -> str:
        return f"{host}:{self.port}"

    def register(self, channel_id: bytes, capacity: int) -> _Inbox:
        with self._lock:
            ib = self._inboxes.get(channel_id)
            if ib is None:
                ib = self._inboxes[channel_id] = _Inbox(capacity)
            return ib

    def unregister(self, channel_id: bytes) -> None:
        with self._lock:
            ib = self._inboxes.pop(channel_id, None)
        if ib is not None:
            ib.closed = True
            with ib.conn_lock:
                conn, ib.conn = ib.conn, None
            if conn is not None:
                _shutdown(conn)

    def violations(self) -> List[dict]:
        with self._lock:
            return list(self._violations)

    def _note_violation(self, rec: dict) -> None:
        import sys

        with self._lock:
            self._violations.append(rec)
        print(f"RTPU_CHANNEL: {rec}", file=sys.stderr, flush=True)

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="dag-channel-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        _resdbg.note_acquire("channel_sock",
                             key=("conn", id(conn)), owner=self)
        try:
            hdr = _recv_exact(conn, 8)
            if hdr is None:
                return
            magic, idlen = struct.unpack("<II", hdr)
            if magic != _HELLO or idlen > 256:
                return
            cid = _recv_exact(conn, idlen)
            if cid is None:
                return
            cid = bytes(cid)
            with self._lock:
                ib = self._inboxes.get(cid)
            if ib is None or ib.closed:
                # Active rejection: a writer dialing a torn-down (or
                # never-served) channel must learn it is GONE — silently
                # closing let buffered sends "succeed" into the void.
                try:
                    conn.sendall(struct.pack("<IQ", _GONE, 0))
                except OSError:
                    pass
                return
            with ib.conn_lock:
                ib.conn = conn
            self._pump(conn, cid, ib)
        finally:
            _shutdown(conn)
            _resdbg.note_release("channel_sock", ("conn", id(conn)))

    def _pump(self, conn: socket.socket, cid: bytes, ib: _Inbox) -> None:
        while not self._stopped and not ib.closed:
            hdr = _recv_exact(conn, 29)
            if hdr is None:
                return
            size, kind, seq, clock, crc = struct.unpack("<IBQQI",
                                                        hdr[:25])
            (nparts,) = struct.unpack("<I", hdr[25:29])
            lens_raw = _recv_exact(conn, 4 * nparts)
            if lens_raw is None:
                return
            lens = struct.unpack("<%dI" % nparts, lens_raw)
            parts = []
            for ln in lens:
                p = _recv_exact(conn, ln)
                if p is None:
                    return
                parts.append(p)
            # Monotonicity witness: SPSC channels deliver seq 0,1,2,...;
            # anything else is a transport bug (re-delivery, inversion).
            if seq <= ib.last_seq:
                self._note_violation({
                    "kind": "channel-seq-inversion",
                    "channel": cid.hex()[:12], "seq": seq,
                    "last": ib.last_seq})
                continue  # drop the duplicate/inverted frame
            if seq != ib.last_seq + 1 and ib.last_seq >= 0:
                self._note_violation({
                    "kind": "channel-seq-gap",
                    "channel": cid.hex()[:12], "seq": seq,
                    "last": ib.last_seq})
            try:
                ib.q.put((kind, seq, clock, crc, parts), timeout=60.0)
            except queue.Full:
                # last_seq NOT advanced: the frame never reached the
                # application, so a retransmit after reconnect must
                # not be dropped as an inversion.
                self._note_violation({
                    "kind": "channel-inbox-overflow",
                    "channel": cid.hex()[:12], "seq": seq})
                return
            ib.last_seq = seq
            ib.bytes_received += size

    def ack(self, ib: _Inbox, seq: int) -> None:
        with ib.conn_lock:
            conn = ib.conn
        if conn is None:
            return
        try:
            conn.sendall(struct.pack("<IQ", _ACK, seq))
        except OSError:
            pass  # writer's liveness probe covers a dead ack path

    def stop(self) -> None:
        self._stopped = True
        with self._lock:
            inboxes = list(self._inboxes.values())
            self._inboxes.clear()
        for ib in inboxes:
            ib.closed = True
            with ib.conn_lock:
                conn, ib.conn = ib.conn, None
            if conn is not None:
                _shutdown(conn)
        _shutdown(self._sock)
        _resdbg.note_release("channel_endpoint", ("endpoint", id(self)))
        if self._accept_thread is not threading.current_thread():
            self._accept_thread.join(timeout=5.0)


def _shutdown(sock: socket.socket) -> None:
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


_endpoint: Optional[ChannelEndpoint] = None
_endpoint_lock = threading.Lock()


def get_endpoint() -> ChannelEndpoint:
    global _endpoint
    with _endpoint_lock:
        if _endpoint is None or _endpoint._stopped:
            _endpoint = ChannelEndpoint()
        return _endpoint


def endpoint_violations() -> List[dict]:
    """Seq-monotonicity / overflow violations this process's endpoint
    observed (the channel analog of the RPC witness's outbox checks)."""
    with _endpoint_lock:
        if _endpoint is None:
            return []
    return _endpoint.violations()


def _local_host() -> str:
    """The host other nodes can dial this process on: the node
    manager's advertised host (worker and node manager share it)."""
    try:
        from ray_tpu.core.runtime_context import get_runtime

        rt = get_runtime()
        for attr in ("node", "head"):
            client = getattr(rt, attr, None)
            addr = getattr(client, "addr", None) or getattr(
                client, "address", None)
            if isinstance(addr, str) and ":" in addr:
                host = addr.rsplit(":", 1)[0]
                if host not in ("0.0.0.0", ""):
                    return host
    except Exception as e:  # noqa: BLE001 — loopback fallback
        logger.debug("channel host resolution failed: %r", e)
    return "127.0.0.1"


def _head_client():
    try:
        from ray_tpu.core.runtime_context import get_runtime

        rt = get_runtime()
        return getattr(rt, "head", None)
    except Exception as e:  # noqa: BLE001 — no-runtime processes
        logger.debug("no head client for channel registry: %r", e)
        return None


def _owner_tag() -> Tuple[str, str]:
    """(owner, node_id) identity the head's death-report scrub keys on:
    the worker's own address when it has one, plus its node."""
    try:
        from ray_tpu.core.runtime_context import get_runtime

        rt = get_runtime()
        return (getattr(rt, "owner_addr", "") or "",
                str(getattr(rt, "node_id", "") or ""))
    except Exception as e:  # noqa: BLE001 — anonymous endpoint
        logger.debug("channel owner identity unavailable: %r", e)
        return "", ""


class CrossNodeChannel:
    """Single-writer single-reader ordered channel ACROSS nodes, over a
    persistent peer socket.

    The reader calls :meth:`prepare_read` (or just ``read``): it
    registers an inbox on this process's ``ChannelEndpoint`` and
    registers the endpoint's address with the head — the ONE-TIME
    negotiation. The writer resolves that address via
    ``channel_lookup`` on first write (or uses an explicit ``addr`` in
    tests/serve negotiation), connects once, and every later send is a
    single scatter ``sendmsg``.
    """

    def __init__(self, channel_id: bytes, writer_node_addr: str = "",
                 reader_node_addr: str = "", capacity: int = 8,
                 edge: str = "", addr: Optional[str] = None):
        self.channel_id = channel_id
        self.writer_node_addr = writer_node_addr
        self.reader_node_addr = reader_node_addr
        self.capacity = capacity
        self.edge = edge or channel_id.hex()[:12]
        self._addr = addr           # explicit endpoint (skips the head)
        self._closed = False
        # writer state
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._ack_cond = threading.Condition()
        self._acked = -1
        self._sent_bytes = 0
        self._acked_bytes = 0
        # seq -> frame size for UNACKED sends (bounded by the credit
        # window); settles into _acked_bytes as acks advance so
        # bytes_in_flight reports what is actually outstanding.
        self._inflight_sizes: Dict[int, int] = {}
        self._sock_dead: Optional[str] = None
        self._peer_gone = False  # endpoint actively rejected the channel
        self._ack_thread = None
        # reader state
        self._inbox: Optional[_Inbox] = None
        self._registered = False

    def _witness_key(self) -> str:
        # Endpoint token, not the bare edge name: a reopened channel
        # restarts seqs at 0 and must not trip the witness's
        # monotonicity checks against the previous incarnation.
        k = getattr(self, "_wkey", None)
        if k is None:
            k = self._wkey = f"{self.edge}@{id(self) & 0xFFFFFF:06x}"
        return k

    # ------------------------------------------------------------- reader

    def prepare_read(self) -> str:
        """Register this process as the channel's reader; returns the
        dialable endpoint address. Idempotent."""
        if self._registered and self._inbox is not None:
            return self._addr or ""
        ep = get_endpoint()
        self._inbox = ep.register(self.channel_id, self.capacity)
        addr = ep.address(_local_host())
        head = _head_client()
        if head is not None:
            owner, node_id = _owner_tag()
            try:
                head.retrying_call("channel_register", self.channel_id,
                                   addr, owner, node_id, timeout=10)
            except Exception as e:  # noqa: BLE001 — writer falls back to
                # its negotiate deadline (and the liveness probe)
                logger.debug("channel_register failed: %r", e)
        self._addr = self._addr or addr
        self._registered = True
        return addr

    def read(self, seq: int, timeout: Optional[float] = None) -> Any:
        from ray_tpu.util import tracing as _tracing

        if self._closed:
            raise ChannelClosedError(f"channel {self.edge} closed locally")
        self.prepare_read()
        ib = self._inbox
        traced = _tracing.enabled()
        t0w = time.time() if traced else 0.0
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            step = 0.5 if deadline is None else max(
                0.0, min(0.5, deadline - time.monotonic()))
            try:
                kind, got_seq, clock, crc, parts = ib.q.get(timeout=step)
                break
            except queue.Empty:
                if self._closed or ib.closed:
                    raise ChannelClosedError(
                        f"channel {self.edge} closed")
                if deadline is not None and time.monotonic() > deadline:
                    raise ChannelTimeoutError(
                        "cross-node channel read timed out",
                        edge=self.edge, seq=seq,
                        bytes_in_flight=ib.bytes_received,
                        peer_alive=None)
        if _chandbg.enabled():
            # Witness BEFORE the mismatch raise: the witness must see
            # the gap/inversion even when the caller turns it into an
            # exception (and record the consume so the ack below is
            # checked against it).
            _chandbg.note_consume(self._witness_key(), got_seq, clock,
                                  crc, *parts)
        if got_seq != seq:
            raise ChannelClosedError(
                f"channel {self.edge}: seq mismatch (got {got_seq}, "
                f"expected {seq})")
        get_endpoint().ack(ib, seq)  # consumption credit -> writer
        if _chandbg.enabled():
            _chandbg.note_ack(self._witness_key(), seq)
        nbytes = sum(len(p) for p in parts)
        if traced:
            _tracing.emit_span(
                "dag.channel.recv", t0w, time.time(),
                attrs={"edge": self.edge, "seq": seq, "bytes": nbytes,
                       "transport": "peer"})
        if kind == KIND_STOP:
            raise ChannelClosedError(f"channel {self.edge} closed")
        value = pickle.loads(bytes(parts[0]),
                             buffers=[bytes(p) for p in parts[1:]])
        if kind == KIND_ERR:
            raise value
        return value[1]

    # ------------------------------------------------------------- writer

    def _resolve_addr(self) -> str:
        if self._addr:
            return self._addr
        from ray_tpu.core.config import GLOBAL_CONFIG as cfg

        head = _head_client()
        if head is None:
            raise ChannelClosedError(
                f"channel {self.edge}: no endpoint address and no head "
                "to negotiate through")
        deadline = time.monotonic() + cfg.dag_negotiate_timeout_s
        while True:
            try:
                ent = head.retrying_call("channel_lookup",
                                         self.channel_id, timeout=10)
            except Exception as e:  # noqa: BLE001 — retried to deadline
                logger.debug("channel_lookup failed: %r", e)
                ent = None
            if ent:
                if not ent.get("alive", True):
                    raise ChannelClosedError(
                        f"channel {self.edge}: reader endpoint died "
                        "before the writer connected")
                self._addr = ent["addr"]
                return self._addr
            if time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    "channel negotiation: reader never registered",
                    edge=self.edge, peer_alive=None)
            time.sleep(0.05)

    def _connect(self) -> socket.socket:
        if self._sock is not None and self._sock_dead is None:
            return self._sock
        addr = self._resolve_addr()
        host, port = addr.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=10)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.sendall(struct.pack("<II", _HELLO, len(self.channel_id))
                         + self.channel_id)
        except BaseException:
            _shutdown(sock)
            raise
        self._sock = sock
        self._sock_dead = None
        _resdbg.note_acquire("channel_sock",
                             key=("writer", id(sock)), owner=self)
        t = _resdbg.track_thread(threading.Thread(
            target=self._ack_loop, args=(sock,), daemon=True,
            name="dag-channel-acks"), owner=self)
        self._ack_thread = t
        t.start()
        return sock

    def _ack_loop(self, sock: socket.socket) -> None:
        try:
            while True:
                frame = _recv_exact(sock, 12)
                if frame is None:
                    return
                magic, seq = struct.unpack("<IQ", frame)
                if magic == _GONE:
                    with self._ack_cond:
                        self._peer_gone = True
                        self._ack_cond.notify_all()
                    return
                if magic != _ACK:
                    return
                with self._ack_cond:
                    if seq > self._acked:
                        self._acked = seq
                        for s in [s for s in self._inflight_sizes
                                  if s <= seq]:
                            self._acked_bytes += \
                                self._inflight_sizes.pop(s)
                    self._ack_cond.notify_all()
        finally:
            with self._ack_cond:
                if self._sock is sock:
                    self._sock_dead = "ack stream ended"
                self._ack_cond.notify_all()
            _resdbg.note_release("channel_sock", ("writer", id(sock)))

    def _peer_alive(self) -> Optional[bool]:
        head = _head_client()
        if head is None:
            return None
        try:
            ent = head.retrying_call("channel_lookup", self.channel_id,
                                     timeout=5)
        except Exception as e:  # noqa: BLE001 — verdict stays unknown
            logger.debug("liveness probe failed: %r", e)
            return None
        if not ent:
            return None
        return bool(ent.get("alive", True))

    def write(self, value: Any, seq: int,
              timeout: Optional[float] = None) -> None:
        self._emit(KIND_OK, ("ok", value), seq, timeout)

    def write_error(self, exc: BaseException, seq: int) -> None:
        self._emit(KIND_ERR, exc, seq, None)

    def write_stop(self, seq: int) -> None:
        self._emit(KIND_STOP, None, seq, None)

    def _emit(self, kind: int, value: Any, seq: int,
              timeout: Optional[float]) -> None:
        from ray_tpu.util import tracing as _tracing

        if self._closed:
            raise ChannelClosedError(f"channel {self.edge} closed locally")
        if self._peer_gone:
            raise ChannelClosedError(
                f"channel {self.edge}: reader endpoint rejected the "
                f"channel (torn down or dead)")
        traced = _tracing.enabled()
        t0w = time.time() if traced else 0.0
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        # pickle-5 out-of-band buffers: large numpy/arrow payloads ride
        # as raw views scatter-gathered onto the socket — never
        # flattened host-side (the PR 4 wire idiom, applied per hop).
        bufs: List[Any] = []
        if kind == KIND_STOP:
            head_bytes = b""
        else:
            head_bytes = pickle.dumps(
                value, protocol=5,
                buffer_callback=lambda b: bufs.append(b.raw()))
        parts = [head_bytes] + [memoryview(b) for b in bufs]
        lens = [len(p) for p in parts]
        size = sum(lens)
        witness = _chandbg.enabled()
        clock = _chandbg.clock_stamp(self._witness_key()) if witness else 0
        crc = _chandbg.payload_crc(seq, *parts) if witness else 0
        hdr = (struct.pack("<IBQQII", size, kind, seq, clock, crc,
                           len(parts))
               + struct.pack("<%dI" % len(parts), *lens))
        from ray_tpu.cluster.protocol import _sendmsg_all

        last_err: Optional[BaseException] = None
        for attempt in range(2):
            if self._peer_gone:
                raise ChannelClosedError(
                    f"channel {self.edge}: reader endpoint rejected the "
                    f"channel (torn down or dead)")
            try:
                # Connect BEFORE the window wait: acks only flow on a
                # live socket, and checking the window with no socket
                # would either deadlock (never connected) or bypass it
                # (just dropped) — the bypass could overrun the
                # reader's bounded inbox.
                with self._send_lock:
                    sock = self._connect()
                # Credit window: at most `capacity` unconsumed messages
                # in flight (acks applied by _ack_loop on this socket).
                with self._ack_cond:
                    while (seq - self._acked > self.capacity
                           and not self._peer_gone
                           and self._sock_dead is None):
                        step = 0.5 if deadline is None else max(
                            0.0, min(0.5, deadline - time.monotonic()))
                        self._ack_cond.wait(step)
                        if (deadline is not None
                                and time.monotonic() > deadline):
                            raise ChannelTimeoutError(
                                "peer write blocked on credit window",
                                edge=self.edge, seq=seq,
                                bytes_in_flight=self._sent_bytes
                                - self._acked_bytes,
                                peer_alive=self._peer_alive())
                with self._send_lock:
                    if self._sock is not sock:
                        raise OSError("socket superseded mid-emit")
                    _sendmsg_all(sock, [memoryview(hdr)] + parts)
                with self._ack_cond:
                    self._sent_bytes += size
                    self._inflight_sizes[seq] = size
                    floor = self._acked
                if witness:
                    _chandbg.note_send(self._witness_key(), seq, size,
                                       window=(floor, self.capacity))
                if traced:
                    _tracing.emit_span(
                        "dag.channel.send", t0w, time.time(),
                        attrs={"edge": self.edge, "seq": seq,
                               "bytes": size, "transport": "peer"})
                return
            except (ChannelClosedError, ChannelTimeoutError):
                raise
            except OSError as e:
                last_err = e
                self._drop_sock()
                alive = self._peer_alive()
                if alive is False:
                    break
                time.sleep(0.1)
        raise ChannelClosedError(
            f"channel {self.edge}: send to reader failed (seq={seq}, "
            f"peer_alive={self._peer_alive()}): {last_err!r}")

    def _drop_sock(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            _shutdown(sock)

    # ------------------------------------------------------------ teardown

    def wait_consumed(self, seq: int, timeout: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout
        with self._ack_cond:
            while self._acked < seq:
                if (self._sock_dead is not None
                        or time.monotonic() > deadline):
                    return self._acked >= seq
                self._ack_cond.wait(0.1)
        return True

    def drain(self, from_seq: int, span: int = 0) -> None:
        self.close()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._drop_sock()
        if self._registered:
            get_endpoint().unregister(self.channel_id)
            head = _head_client()
            if head is not None:
                try:
                    head.notify("channel_unregister", self.channel_id)
                except Exception as e:  # noqa: BLE001 — the register cap
                    # and death scrub bound a missed unregister
                    logger.debug("channel_unregister failed: %r", e)

    def __reduce__(self):
        return (CrossNodeChannel,
                (self.channel_id, self.writer_node_addr,
                 self.reader_node_addr, self.capacity, self.edge,
                 self._addr))
