"""DAG compilation: bound graphs -> per-actor channel-driven schedules.

Parity target: reference python/ray/dag/compiled_dag_node.py:767
(_get_or_compile: topo-sort, channel allocation, per-actor executables)
+ dag_node_operation.py (per-actor op schedules). TPU-first reshape: the
compiled DAG is the host-side repeated-step executor — ONE compile hands
each actor its op list; each `execute()` costs channel writes/reads (shm +
condvar), bypassing scheduler, leases, and per-call RPC entirely. This is
the substrate pipeline-parallel training steps run on (parallel/pipeline.py
shards the model; this layer moves the microbatch activations).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.dag.channel import ChannelClosedError, ShmChannel
from ray_tpu.dag.errors import ChannelError
from ray_tpu.dag.collective_node import CollectiveOutputNode, reduce_fn
from ray_tpu.dag.dag_node import (ClassMethodNode, DAGNode, InputNode,
                                  MultiOutputNode)

_DAG_LOOP_METHOD = "__rtpu_dag_loop__"


def _topo_order(root: DAGNode) -> List[DAGNode]:
    seen: Dict[int, DAGNode] = {}
    order: List[DAGNode] = []

    def visit(n: DAGNode):
        if n._dag_id in seen:
            return
        seen[n._dag_id] = n
        for up in n.upstream():
            visit(up)
        order.append(n)

    visit(root)
    return order


class CompiledDAGRef:
    """Future for one execute() round's outputs."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._got = False
        self._value = None

    def get(self, timeout: Optional[float] = 60.0):
        if not self._got:
            outs, first_err = [], None
            # Consume EVERY output channel for this seq even when one
            # carries an error — an unconsumed sibling slot would stall
            # its producer at seq+capacity forever.
            for ch in self._dag._output_channels:
                try:
                    outs.append(ch.read(self._seq, timeout))
                except BaseException as e:  # noqa: BLE001
                    if first_err is None:
                        first_err = e
            self._got = True
            if first_err is not None:
                self._value = ("__err__", first_err)
                raise first_err
            self._value = outs[0] if len(outs) == 1 else outs
        if isinstance(self._value, tuple) and len(self._value) == 2 \
                and self._value[0] == "__err__":
            raise self._value[1]
        return self._value


class CompiledDAG:
    def __init__(self, root: DAGNode, capacity: Optional[int] = None):
        from ray_tpu.core.config import GLOBAL_CONFIG as _cfg

        self._capacity = (capacity if capacity is not None
                          else _cfg.dag_channel_capacity)
        self._seq = 0
        self._torn_down = False
        self._lock = threading.Lock()
        self._build(root)

    # ------------------------------------------------------------ build

    def _chan(self) -> ShmChannel:
        return ShmChannel(uuid.uuid4().bytes, capacity=self._capacity)

    def _build(self, root: DAGNode) -> None:
        order = _topo_order(root)
        multi = order[-1] if isinstance(order[-1], MultiOutputNode) else None
        output_nodes = multi.outputs if multi else [root]
        for n in order:
            if isinstance(n, MultiOutputNode) and n is not multi:
                raise ValueError("MultiOutputNode must be the DAG root")

        inputs = [n for n in order if isinstance(n, InputNode)]
        if len(inputs) > 1:
            raise ValueError("a DAG takes exactly one InputNode")

        # One channel per ARGUMENT SLOT (not per producer/consumer pair —
        # binding the same upstream to two args needs two SPSC channels),
        # plus one per driver-visible output. producer_outputs collects
        # every channel a node must write. Each channel records its
        # (writer, reader) endpoints — "driver" or an actor key — so the
        # kind can be chosen AFTER placement resolves: same-node pairs
        # ride shm, cross-node pairs ride the push transfer.
        self._input_channels: List[Any] = []
        producer_outputs: Dict[int, List[Any]] = {}
        chan_ends: Dict[int, list] = {}  # id(ch) -> [writer, reader]
        current_consumer: List[Any] = ["driver"]

        def argspec(v):
            if isinstance(v, InputNode):
                ch = self._chan()
                self._input_channels.append(ch)
                chan_ends[id(ch)] = [ch, "driver", current_consumer[0]]
                return ("chan", ch)
            if isinstance(v, DAGNode):
                ch = self._chan()
                producer_outputs.setdefault(v._dag_id, []).append(ch)
                chan_ends[id(ch)] = [ch, None, current_consumer[0]]
                return ("chan", ch)
            return ("const", v)

        per_actor: Dict[bytes, List[Dict[str, Any]]] = {}
        self._actors: Dict[bytes, Any] = {}
        # First pass: ops + arg channels, in global topo order (preserves
        # intra-actor dependency order; the reference's dag_node_operation
        # applies the same per-actor restriction). Collective groups are
        # laid out per-actor from the SAME global order, so every actor
        # enters concurrent groups in a consistent order (no cross-group
        # deadlock by construction).
        ops_by_node: Dict[int, Dict[str, Any]] = {}
        node_actor_key: Dict[int, bytes] = {}
        group_tree: Dict[int, Tuple[list, list]] = {}  # gid -> (up, down)
        for n in order:
            if isinstance(n, CollectiveOutputNode):
                group = n.group
                if group.group_id in group_tree:
                    continue  # whole group scheduled at first encounter
                # Schedule EVERY rank's op NOW, atomically. Two
                # guarantees hang off this: (a) all actors append
                # concurrent groups in the same relative order (first
                # topo encounter is a global order), so two groups can
                # never interleave into a cross-group deadlock; (b) a
                # rank whose output node is unreachable from the DAG
                # root still runs its op (its peers' tree reads would
                # otherwise block forever). Topo order has already
                # visited every contribution (upstream() returns all
                # participants), so dependencies hold for all ranks.
                k = len(group.upstreams)
                # Binary-tree edges rank i <-> parent (i-1)//2, one
                # up + one down channel per non-root rank (reference
                # analog: collective nodes lower onto a communicator;
                # here the communicator IS the DAG's channel substrate).
                ups: list = [None] * k
                downs: list = [None] * k
                for i in range(1, k):
                    pkey = group.upstreams[(i - 1) // 2].actor \
                        .actor_id.binary()
                    ikey = group.upstreams[i].actor.actor_id.binary()
                    up = self._chan()
                    down = self._chan()
                    chan_ends[id(up)] = [up, ikey, pkey]
                    chan_ends[id(down)] = [down, pkey, ikey]
                    ups[i], downs[i] = up, down
                group_tree[group.group_id] = (ups, downs)
                for sib in (group.output_nodes or [n]):
                    key = sib.actor.actor_id.binary()
                    self._actors[key] = sib.actor
                    node_actor_key[sib._dag_id] = key
                    r = sib.rank
                    children = [c for c in (2 * r + 1, 2 * r + 2)
                                if c < k]
                    current_consumer[0] = key
                    op = {
                        "kind": "allreduce",
                        "op": group.op,
                        "method": f"allreduce-{group.op}",
                        "args": [argspec(sib.upstream_node)],
                        "kwargs": {},
                        "up_parent": ups[r] if r else None,
                        "down_parent": downs[r] if r else None,
                        "up_children": [ups[c] for c in children],
                        "down_children": [downs[c] for c in children],
                        "outputs": [],
                    }
                    ops_by_node[sib._dag_id] = op
                    per_actor.setdefault(key, []).append(op)
                continue
            if not isinstance(n, ClassMethodNode):
                continue
            key = n.actor.actor_id.binary()
            self._actors[key] = n.actor
            node_actor_key[n._dag_id] = key
            current_consumer[0] = key
            op = {
                "method": n.method_name,
                "args": [argspec(a) for a in n.args],
                "kwargs": {k: argspec(v) for k, v in n.kwargs.items()},
                "outputs": [],
            }
            ops_by_node[n._dag_id] = op
            per_actor.setdefault(key, []).append(op)
        current_consumer[0] = "driver"
        self._output_channels = []
        for out in output_nodes:
            if not isinstance(out, (ClassMethodNode, CollectiveOutputNode)):
                raise ValueError("DAG outputs must be actor-method or "
                                 "collective nodes")
            ch = self._chan()
            self._output_channels.append(ch)
            chan_ends[id(ch)] = [ch, None, "driver"]
            producer_outputs.setdefault(out._dag_id, []).append(ch)
        # Second pass: attach collected output channels + writer endpoints.
        for node_id, op in ops_by_node.items():
            op["outputs"] = producer_outputs.get(node_id, [])
            for ch in op["outputs"]:
                chan_ends[id(ch)][1] = node_actor_key[node_id]

        replacements = self._resolve_channel_kinds(chan_ends)
        if replacements:
            self._rewrite_channels(per_actor, replacements)
        # Pre-negotiate the driver's READER ends now (cross-node output
        # channels register their endpoint with the head before any
        # actor writer looks them up) and label every edge for error
        # context.
        def _label(ep) -> str:
            return "driver" if ep == "driver" else ep.hex()[:8]

        for ends in chan_ends.values():
            ch = replacements.get(id(ends[0]), ends[0])
            ch.edge = f"{_label(ends[1])}->{_label(ends[2])}"
        for ch in self._output_channels:
            prep = getattr(ch, "prepare_read", None)
            if prep is not None:
                prep()

        # Ship each actor its schedule; the worker runs a dedicated loop
        # thread (special method intercepted in worker_main).
        import ray_tpu

        ray_tpu.get([
            handle._actor_method_call(
                _DAG_LOOP_METHOD, (per_actor[key],), {}, 1)
            for key, handle in self._actors.items()
        ], timeout=60)

    def _resolve_channel_kinds(self, chan_ends: Dict[int, list]
                               ) -> Dict[int, Any]:
        """Placement-aware channel selection: endpoints on one node keep
        the shm channel; endpoints on DIFFERENT nodes get a
        CrossNodeChannel over the push transfer (reference analog:
        shared-memory channels vs cross-node mutable-object push,
        node_manager.proto:444). Returns {id(old_ch): replacement}.

        Resolution failures RAISE: compile is the one place an error is
        cheap, and guessing shm for an actor that is actually remote is a
        silent hang on first execute."""
        from ray_tpu.core.runtime_context import require_runtime
        from ray_tpu.dag.channel import CrossNodeChannel

        rt = require_runtime()
        my_node = getattr(rt, "node_id", None)
        lister = getattr(rt, "list_actors", None)
        nodes_fn = getattr(rt, "nodes", None)
        if my_node is None or lister is None or nodes_fn is None:
            return {}  # single-process runtime: shm always works

        # Actors may still be PENDING placement (node_id None until the
        # head schedules them): wait placement out rather than guessing.
        actor_keys = {ep for ends in chan_ends.values()
                      for ep in ends[1:] if ep != "driver"}
        deadline = time.monotonic() + 60.0
        while True:
            table = {a["actor_id"]: a for a in lister()}
            unplaced = [k for k in actor_keys
                        if (table.get(k.hex()) or {}).get("node_id")
                        is None]
            if not unplaced:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"DAG compile: {len(unplaced)} actor(s) not placed "
                    f"within 60s (first: "
                    f"{unplaced[0].hex()[:12]})")
            time.sleep(0.1)
        node_addr = {n["node_id"]: n["address"] for n in nodes_fn()}

        def endpoint_node(ep) -> str:
            if ep == "driver":
                return my_node
            return table[ep.hex()]["node_id"]

        replacements: Dict[int, Any] = {}
        for _ch_id, (ch, writer, reader) in chan_ends.items():
            wn, rn = endpoint_node(writer), endpoint_node(reader)
            if wn == rn:
                continue
            wa, ra = node_addr.get(wn), node_addr.get(rn)
            if wa is None or ra is None:
                raise ValueError(
                    f"cannot resolve node addresses for cross-node DAG "
                    f"channel ({wn!r} -> {rn!r})")
            replacements[id(ch)] = CrossNodeChannel(
                ch.channel_id, wa, ra, capacity=self._capacity)
        return replacements

    def _rewrite_channels(self, per_actor: Dict[bytes, list],
                          replacements: Dict[int, Any]) -> None:
        def swap(ch):
            return replacements.get(id(ch), ch)

        self._input_channels = [swap(c) for c in self._input_channels]
        self._output_channels = [swap(c) for c in self._output_channels]
        for ops in per_actor.values():
            for op in ops:
                op["args"] = [(k, swap(v) if k == "chan" else v)
                              for k, v in op["args"]]
                op["kwargs"] = {key: (k, swap(v) if k == "chan" else v)
                                for key, (k, v) in op["kwargs"].items()}
                op["outputs"] = [swap(c) for c in op["outputs"]]
                if op.get("kind") == "allreduce":
                    for f in ("up_parent", "down_parent"):
                        if op[f] is not None:
                            op[f] = swap(op[f])
                    op["up_children"] = [swap(c) for c in op["up_children"]]
                    op["down_children"] = [swap(c)
                                           for c in op["down_children"]]

    # ------------------------------------------------------------ execute

    def execute(self, *args) -> CompiledDAGRef:
        """One round: write the input to every input channel, return a ref
        for the outputs. Rounds pipeline up to the channel capacity."""
        with self._lock:
            if self._torn_down:
                raise RuntimeError("compiled DAG was torn down")
            seq = self._seq
            self._seq += 1
        value = args[0] if len(args) == 1 else args
        for ch in self._input_channels:
            ch.write(value, seq)
        return CompiledDAGRef(self, seq)

    def teardown(self) -> None:
        with self._lock:
            if self._torn_down:
                return
            self._torn_down = True
            seq = self._seq
            self._seq += 1
        for ch in self._input_channels:
            try:
                ch.write_stop(seq)
            except Exception:
                pass
        # Handshake, not a sleep: wait for each loop to CONSUME its stop
        # sentinel (deleting it mid-flight would leave the loop blocked on
        # a message that will never exist), then clean leftover slots.
        from ray_tpu.core.config import GLOBAL_CONFIG as _cfg

        for ch in self._input_channels:
            ch.wait_consumed(seq, timeout=_cfg.dag_teardown_timeout_s)
        for ch in self._input_channels + self._output_channels:
            ch.drain(seq + 1)

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass


def compile_dag(root: DAGNode, **kwargs) -> CompiledDAG:
    return CompiledDAG(root, **kwargs)


# ---------------------------------------------------------------- worker side

def _execute_allreduce(op: Dict[str, Any], arg_state: tuple, seq: int,
                       emit, read_fn) -> tuple:
    """Tree allreduce for one seq. arg_state is ("ok", v) | ("err", e) |
    ("stop",). Guarantees exactly one write to every channel this rank
    writes (up_parent + down_children) and one consume of every channel it
    reads (up_children + down_parent) in ALL outcomes — a skipped slot
    would stall the peer at seq+capacity forever. Returns the same
    state-tuple shape for the rank's reduced output."""
    written: set = set()
    consumed: set = set()
    up_p, down_p = op["up_parent"], op["down_parent"]
    read_list = list(op["up_children"]) + ([down_p] if down_p is not None
                                           else [])
    stop = arg_state[0] == "stop"
    err = arg_state[1] if arg_state[0] == "err" else None
    result = None
    if not stop and err is None:
        value = arg_state[1]
        fn = reduce_fn(op["op"])
        current = [None]

        def tracked_read(ch):
            current[0] = ch
            try:
                return read_fn(ch, seq)
            finally:
                # stop sentinels and error payloads consume the slot on
                # raise; only a hard timeout (actor dying) does not, and
                # then the loop is exiting anyway.
                consumed.add(id(ch))
                current[0] = None

        try:
            for ch in op["up_children"]:
                value = fn(value, tracked_read(ch))
            if up_p is not None:
                emit("w", up_p, value, seq)
                written.add(id(up_p))
                result = tracked_read(down_p)
            else:
                result = value
            for ch in op["down_children"]:
                emit("w", ch, result, seq)
                written.add(id(ch))
        except ChannelClosedError:
            stop = True
        except BaseException as e:  # noqa: BLE001 — propagated to peers
            err = e
    if stop or err is not None:
        mode = "s" if stop else "e"
        payload = None if stop else err
        if up_p is not None and id(up_p) not in written:
            emit(mode, up_p, payload, seq)
        for ch in op["down_children"]:
            if id(ch) not in written:
                emit(mode, ch, payload, seq)
        for ch in read_list:
            if id(ch) not in consumed:
                try:
                    ch.read(seq, timeout=5.0)
                except Exception:
                    pass
        return ("stop",) if stop else ("err", err)
    return ("ok", result)


def _drain_op_for_stop(op: Dict[str, Any], seq: int, emit) -> None:
    """Teardown-path unblocking for an op whose seq round is being
    abandoned: consume its input slots, emit stop on its outputs, and for
    collectives do the same for the tree channels."""
    for kind, v in list(op["args"]) + list(op["kwargs"].values()):
        if kind != "chan":
            continue
        try:
            v.read(seq, timeout=0.5)
        except Exception:
            pass
    if op.get("kind") == "allreduce":
        if op["up_parent"] is not None:
            try:
                emit("s", op["up_parent"], None, seq)
            except Exception:
                pass
        for ch in op["down_children"]:
            try:
                emit("s", ch, None, seq)
            except Exception:
                pass
        reads = list(op["up_children"]) + (
            [op["down_parent"]] if op["down_parent"] is not None else [])
        for ch in reads:
            try:
                ch.read(seq, timeout=0.5)
            except Exception:
                pass
    for out in op["outputs"]:
        try:
            emit("s", out, None, seq)
        except Exception:
            pass


def _read_interruptible(ch, seq: int, stop_event: threading.Event):
    """Channel read that honors the kill switch: blocking in the store's
    condvar with timeout=None would strand the loop thread past actor
    death (ray_tpu.kill sets the event but cannot wake a condvar wait)."""
    from ray_tpu.dag.channel import ChannelTimeoutError

    while True:
        try:
            return ch.read(seq, timeout=0.5)
        except ChannelTimeoutError:
            if stop_event.is_set():
                raise ChannelClosedError("actor stopping")


def run_actor_dag_loop(instance, schedule: List[Dict[str, Any]],
                       stop_event: threading.Event) -> None:
    """Executed on a dedicated thread inside the hosting worker: one
    iteration per seq — read op inputs, call the method on the actor
    instance, write outputs. Errors are forwarded downstream (the driver
    raises them from the output channel); a stop sentinel propagates and
    ends the loop.

    COMM OVERLAP (reference: dag_node_operation.py:506-539's
    READ/COMPUTE/WRITE schedule with overlapped communication, toggled by
    DAGContext.overlap_gpu_communication): output writes run on a
    dedicated per-loop SENDER thread, so compute for seq+1 overlaps the
    channel send of seq — on cross-node channels (a push RPC per message)
    that send is the hop's whole latency. Order is preserved: one sender
    drains the queue FIFO, and every channel stays single-writer."""
    import queue as _q

    from ray_tpu.core.config import GLOBAL_CONFIG as _cfg

    overlap = bool(getattr(_cfg, "dag_overlap_comm", False))
    send_q: "_q.Queue" = _q.Queue(maxsize=32)
    send_failed: List[BaseException] = []

    def _sched_channels():
        """Every channel object in the schedule, tagged by this actor's
        role on it ("r" = this loop reads it, "w" = writes)."""
        for op in schedule:
            for kind, v in list(op["args"]) + list(op["kwargs"].values()):
                if kind == "chan":
                    yield "r", v
            for out in op["outputs"]:
                yield "w", out
            if op.get("kind") == "allreduce":
                for ch in op["up_children"]:
                    yield "r", ch
                if op["down_parent"] is not None:
                    yield "r", op["down_parent"]
                if op["up_parent"] is not None:
                    yield "w", op["up_parent"]
                for ch in op["down_children"]:
                    yield "w", ch

    # One-time negotiation, BEFORE the first execute round: reader ends
    # register their endpoint (cross-node writers look it up through
    # the head exactly once); steady-state hops then never touch the
    # head again.
    for role, ch in _sched_channels():
        if role == "r":
            prep = getattr(ch, "prepare_read", None)
            if prep is not None:
                prep()

    def _close_channels():
        for role, ch in _sched_channels():
            close = getattr(ch, "close", None)
            if close is None:
                continue
            try:
                if role == "r":
                    try:
                        close(unlink=True)
                    except TypeError:
                        close()
                else:
                    close()
            except Exception:  # noqa: BLE001 — teardown is best-effort:
                # every peer also closes its own ends, and the ring/sock
                # res witness reports anything that truly leaked
                continue

    def _sender():
        while True:
            item = send_q.get()
            if item is None:
                return
            mode, ch, payload, s = item
            try:
                if mode == "w":
                    ch.write(payload, s)
                elif mode == "e":
                    ch.write_error(payload, s)
                else:
                    ch.write_stop(s)
            except BaseException as e:  # noqa: BLE001 — surfaced to loop
                send_failed.append(e)

    sender_thread = None
    if overlap:
        sender_thread = threading.Thread(
            target=_sender, daemon=True, name="dag-sender")
        sender_thread.start()

    def emit(mode, ch, payload, s):
        # Once a sender exists it stays the ONLY writer (switching to
        # direct writes mid-flight would race its queued writes and
        # reorder seqs on a channel).
        if overlap:
            send_q.put((mode, ch, payload, s))
            return
        if mode == "w":
            ch.write(payload, s)
        elif mode == "e":
            ch.write_error(payload, s)
        else:
            ch.write_stop(s)

    def finish():
        if sender_thread is not None:
            send_q.put(None)
            sender_thread.join(timeout=30)
        _close_channels()

    seq = 0
    while not stop_event.is_set():
        stopped = False
        for op in schedule:
            # Consume EVERY arg channel for this seq — skipping siblings
            # after the first error/stop would leave unread slots that
            # stall their producers at seq+capacity forever.
            args, kwargs = [], {}
            first_err, saw_stop = None, False
            for kind, v in op["args"]:
                if kind != "chan":
                    args.append(v)
                    continue
                try:
                    args.append(_read_interruptible(v, seq, stop_event))
                except ChannelClosedError:
                    saw_stop = True
                    args.append(None)
                except BaseException as e:  # noqa: BLE001
                    first_err = first_err or e
                    args.append(None)
            for k, (kind, v) in op["kwargs"].items():
                if kind != "chan":
                    kwargs[k] = v
                    continue
                try:
                    kwargs[k] = _read_interruptible(v, seq, stop_event)
                except ChannelClosedError:
                    saw_stop = True
                    kwargs[k] = None
                except BaseException as e:  # noqa: BLE001
                    first_err = first_err or e
                    kwargs[k] = None
            if op.get("kind") == "allreduce":
                # Collective op: the tree protocol handles stop/error
                # propagation to PEERS itself (every tree channel is
                # written/consumed exactly once per seq in all outcomes).
                if saw_stop:
                    arg_state: tuple = ("stop",)
                elif first_err is not None:
                    arg_state = ("err", first_err)
                else:
                    arg_state = ("ok", args[0])
                state = _execute_allreduce(
                    op, arg_state, seq, emit,
                    lambda ch, s: _read_interruptible(ch, s, stop_event))
                if state[0] == "ok":
                    for out in op["outputs"]:
                        emit("w", out, state[1], seq)
                    continue
                if state[0] == "err":
                    for out in op["outputs"]:
                        emit("e", out, state[1], seq)
                    continue
                saw_stop = True  # fall through to the stop path below
            if saw_stop:
                for out in op["outputs"]:
                    try:
                        emit("s", out, None, seq)
                    except Exception:
                        pass
                # Consume the REMAINING ops' input sentinels too — each
                # input channel got its own stop, and teardown's
                # wait_consumed handshake blocks until all are read.
                idx = schedule.index(op)
                for later in schedule[idx + 1:]:
                    _drain_op_for_stop(later, seq, emit)
                stopped = True
                break
            if first_err is not None:
                # An upstream error rode the channel in: forward it.
                for out in op["outputs"]:
                    emit("e", out, first_err, seq)
                continue
            try:
                result = getattr(instance, op["method"])(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — forwarded, not fatal
                for out in op["outputs"]:
                    emit("e", out, e, seq)
                continue
            for out in op["outputs"]:
                emit("w", out, result, seq)
        if stopped:
            finish()
            return
        if send_failed:
            # A channel write failed on the sender: the pipeline is
            # broken — say so LOUDLY (the sync path would have printed a
            # thread traceback) and stop rather than compute into a dead
            # channel; the driver surfaces as a channel timeout.
            import sys as _sys

            print(f"compiled-DAG sender write failed; stopping loop: "
                  f"{send_failed[0]!r}", file=_sys.stderr, flush=True)
            finish()
            return
        seq += 1
    finish()
