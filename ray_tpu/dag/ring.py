"""Same-node compiled-DAG transport: an SPSC shm ring buffer.

Parity target: the reference's shared-memory compiled-graph channels
(python/ray/experimental/channel/shared_memory_channel.py) re-designed
as a classic single-producer single-consumer byte ring over one mmap'd
file in /dev/shm: a steady-state hop is a memcpy into the ring plus one
8-byte position publish — no store RPC, no scheduler, no head. The
previous design (one immutable store object per message) cost a store
put + directory notify + delete per hop; the ring costs none of that
and is what lets a compiled-DAG hop undercut a task-RPC round trip by
an order of magnitude (bench.py --dag).

Layout (offsets in bytes)::

    0   magic   u32  (creator writes this LAST: attachers spin on it)
    4   version u32
    8   capacity u64   data bytes
    16  write_pos u64  monotonic byte cursor (writer-owned)
    24  read_pos  u64  monotonic byte cursor (reader-owned)
    32  read_seq  u64  messages consumed (reader-owned; backpressure +
                       wait_consumed read this)
    40  writer_closed u8 / reader_closed u8
    64  data[capacity]

Records never wrap:
``[u32 size | u32 kind | u64 seq | u64 clock | u32 crc | payload]``
padded to 8 bytes; when the contiguous tail is too small the writer
stamps a wrap marker (size = 0xFFFFFFFF) and continues at offset 0.
``clock``/``crc`` carry the RTPU_DEBUG_CHAN witness's Lamport stamp
and sampled payload checksum (``devtools/chan_debug.py``) and are 0
when the witness is off; layout v3 bumped the version so a stale
attacher fails loudly instead of misparsing records.
Position publishes happen AFTER the payload memcpy, so the reader only
ever observes complete records (aligned 8-byte stores are atomic on
the platforms this runtime targets).

Rendezvous needs no coordination service: both endpoints derive the
ring path from the channel id and race ``O_CREAT|O_EXCL`` — the loser
attaches. Payloads larger than ``dag_ring_spill_bytes`` spill to a
side file the ring references; the writer pins each spill (RTPU_DEBUG_RES
kind ``channel_spill``) until it observes consumption and reclaims
unconsumed spills at close, so a dead reader cannot leak them.
"""

from __future__ import annotations

import mmap
import os
import pickle
import struct
import tempfile
import time
from typing import Any, List, Optional, Tuple

from ray_tpu.dag.errors import ChannelClosedError, ChannelTimeoutError
from ray_tpu.devtools import chan_debug as _chandbg
from ray_tpu.devtools import res_debug as _resdbg

_MAGIC = 0x52545543  # "RTUC"
_VERSION = 3
_HDR = 64
_REC_HDR = 32  # <IIQQI = 28 bytes of header, padded to 8-alignment
_WRAP = 0xFFFFFFFF

# Record kinds (mirrored by the cross-node transport in peer.py).
KIND_OK = 0       # pickled ("ok", value)
KIND_ERR = 1      # pickled exception
KIND_STOP = 2     # stop sentinel (no payload)
KIND_SPILL = 8    # payload = utf-8 side-file name carrying a KIND_OK body
KIND_SPILL_ERR = 9  # side file carries a KIND_ERR body

_O_MAGIC = 0
_O_VERSION = 4
_O_CAP = 8
_O_WPOS = 16
_O_RPOS = 24
_O_RSEQ = 32
_O_WCLOSED = 40
_O_RCLOSED = 41


def channel_dir() -> str:
    """The node-local rendezvous directory for rings and spill files."""
    from ray_tpu.core.config import GLOBAL_CONFIG as cfg

    d = cfg.dag_channel_dir
    if d:
        return d
    if os.path.isdir("/dev/shm"):
        return "/dev/shm"
    return tempfile.gettempdir()


def _align8(n: int) -> int:
    return (n + 7) & ~7


class _Waiter:
    """Latency-tiered wait for the ring's poll loops: pure spin for the
    first ~200 probes (a hop lands in tens of µs when the peer is
    active), then ``sleep(0)`` yields (stay runnable, surrender the
    core), then exponential timed sleeps (this kernel's minimum timed
    sleep is ~0.5 ms — sleeping FIRST put half a millisecond on every
    hop)."""

    __slots__ = ("spins", "pause")

    def __init__(self):
        self.spins = 0
        self.pause = 0.0002

    def wait(self) -> None:
        self.spins += 1
        if self.spins <= 200:
            return
        if self.spins <= 1200:
            time.sleep(0)
            return
        time.sleep(self.pause)
        self.pause = min(self.pause * 2, 0.005)


class RingChannel:
    """Single-writer single-reader ordered channel over one shm ring.

    Both endpoints construct it from the (serializable) ``channel_id``;
    whichever process touches the ring first creates the file, the
    other attaches. ``capacity`` bounds in-flight MESSAGES (the old
    channel-slot semantics the compiled DAG pipelines against) and
    ``cfg.dag_ring_bytes`` bounds in-flight BYTES.
    """

    def __init__(self, channel_id: bytes, capacity: int = 8,
                 ring_bytes: Optional[int] = None, edge: str = ""):
        self.channel_id = channel_id
        self.capacity = capacity
        self.edge = edge or channel_id.hex()[:12]
        self._ring_bytes = ring_bytes
        self._mm: Optional[mmap.mmap] = None
        self._path: Optional[str] = None
        self._closed = False
        self._role: Optional[str] = None  # "w" | "r", set on first op
        self._read_seq = 0               # next seq this end expects
        # Writer-side spill ledger: (record_end_pos, path) pending
        # consumption; settled (released) when read_pos passes end_pos,
        # reclaimed (unlinked) at close if the reader never got there.
        self._spills: List[Tuple[int, str]] = []

    # ------------------------------------------------------------- mapping

    def _ring_path(self) -> str:
        return os.path.join(channel_dir(),
                            f"rtpu-ring-{self.channel_id.hex()}.ch")

    def _ensure(self) -> mmap.mmap:
        if self._closed:
            raise ChannelClosedError(f"channel {self.edge} closed locally")
        if self._mm is not None:
            return self._mm
        from ray_tpu.core.config import GLOBAL_CONFIG as cfg

        cap = self._ring_bytes or cfg.dag_ring_bytes
        path = self._ring_path()
        size = _HDR + cap
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
            creator = True
        except FileExistsError:
            fd = os.open(path, os.O_RDWR)
            creator = False
        try:
            if creator:
                os.ftruncate(fd, size)
                mm = mmap.mmap(fd, size)
                struct.pack_into("<I", mm, _O_VERSION, _VERSION)
                struct.pack_into("<Q", mm, _O_CAP, cap)
                # Magic last: attachers spin on it below, so a half-
                # initialized header is never observable.
                struct.pack_into("<I", mm, _O_MAGIC, _MAGIC)
            else:
                deadline = time.monotonic() + cfg.dag_negotiate_timeout_s
                while os.fstat(fd).st_size < _HDR:
                    if time.monotonic() > deadline:
                        raise ChannelTimeoutError(
                            "ring rendezvous: creator never sized "
                            f"{path}", edge=self.edge)
                    time.sleep(0.001)
                mm = mmap.mmap(fd, os.fstat(fd).st_size)
                while struct.unpack_from("<I", mm, _O_MAGIC)[0] != _MAGIC:
                    if time.monotonic() > deadline:
                        raise ChannelTimeoutError(
                            "ring rendezvous: header never initialized",
                            edge=self.edge)
                    time.sleep(0.001)
                ver = struct.unpack_from("<I", mm, _O_VERSION)[0]
                if ver != _VERSION:
                    mm.close()
                    raise ChannelClosedError(
                        f"channel {self.edge}: ring layout v{ver} != "
                        f"v{_VERSION} — both endpoints must run the "
                        "same build (record headers are incompatible)")
        finally:
            os.close(fd)
        self._mm = mm
        self._path = path
        self._cap = struct.unpack_from("<Q", mm, _O_CAP)[0]
        # Keyed by ENDPOINT identity, not path: both ends of a
        # same-process channel map the same file and must balance
        # independently.
        _resdbg.note_acquire("channel_ring",
                             key=(os.getpid(), id(self)), owner=self)
        return mm

    def _witness_key(self) -> str:
        """RTPU_DEBUG_CHAN endpoint token: edge + object identity, so a
        reopened channel under the same edge name starts a fresh
        stream in the witness registry."""
        k = getattr(self, "_wkey", None)
        if k is None:
            k = self._wkey = f"{self.edge}@{id(self) & 0xFFFFFF:06x}"
        return k

    # ------------------------------------------------------------- cursors

    def _u64(self, off: int) -> int:
        return struct.unpack_from("<Q", self._mm, off)[0]

    def _set_u64(self, off: int, v: int) -> None:
        struct.pack_into("<Q", self._mm, off, v)

    def _peer_closed(self, role: str) -> bool:
        off = _O_RCLOSED if role == "w" else _O_WCLOSED
        return self._mm[off] != 0

    def bytes_in_flight(self) -> int:
        if self._mm is None:
            return 0
        return self._u64(_O_WPOS) - self._u64(_O_RPOS)

    # -------------------------------------------------------------- writer

    def write(self, value: Any, seq: int,
              timeout: Optional[float] = None) -> None:
        self._emit(KIND_OK, pickle.dumps(("ok", value), protocol=5),
                   seq, timeout)

    def write_error(self, exc: BaseException, seq: int) -> None:
        self._emit(KIND_ERR, pickle.dumps(exc, protocol=5), seq, None)

    def write_stop(self, seq: int) -> None:
        self._emit(KIND_STOP, b"", seq, None)

    def _emit(self, kind: int, payload: bytes, seq: int,
              timeout: Optional[float]) -> None:
        from ray_tpu.core.config import GLOBAL_CONFIG as cfg
        from ray_tpu.util import tracing as _tracing

        mm = self._ensure()
        self._role = "w"
        traced = _tracing.enabled()
        t0w = time.time() if traced else 0.0
        witness = _chandbg.enabled()
        clock = crc = 0
        if witness:
            clock = _chandbg.clock_stamp(self._witness_key())
            # crc over the ORIGINAL payload, before any spill-out: the
            # reader recomputes after spill resolution, so a side file
            # mutated between send and consume is caught too.
            crc = _chandbg.payload_crc(seq, payload)
        if len(payload) > cfg.dag_ring_spill_bytes:
            payload = self._spill_out(payload, seq)
            kind = KIND_SPILL if kind == KIND_OK else KIND_SPILL_ERR
        size = len(payload)
        rec = _REC_HDR + _align8(size)
        if rec > self._cap:
            raise ValueError(
                f"channel {self.edge}: {size}-byte record exceeds the "
                f"{self._cap}-byte ring (raise dag_ring_bytes)")
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        waiter = _Waiter()
        while True:
            wpos = self._u64(_O_WPOS)
            rpos = self._u64(_O_RPOS)
            off = wpos % self._cap
            tail = self._cap - off
            need = rec if tail >= rec else tail + rec
            window_ok = seq - self._u64(_O_RSEQ) < self.capacity
            if self._cap - (wpos - rpos) >= need and window_ok:
                break
            if self._peer_closed("w"):
                raise ChannelClosedError(
                    f"channel {self.edge}: reader closed "
                    f"(seq={seq}, {wpos - rpos} bytes unconsumed)")
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"ring write blocked on backpressure",
                    edge=self.edge, seq=seq,
                    bytes_in_flight=wpos - rpos, peer_alive=True)
            self._settle_spills(rpos)
            waiter.wait()
        if tail < rec:
            if tail >= 4:
                struct.pack_into("<I", mm, _HDR + off, _WRAP)
            wpos += tail
            off = 0
        struct.pack_into("<IIQQI", mm, _HDR + off, size, kind, seq,
                         clock, crc)
        mm[_HDR + off + _REC_HDR:_HDR + off + _REC_HDR + size] = payload
        # Publish AFTER the payload memcpy: the reader never sees a
        # partial record.
        self._set_u64(_O_WPOS, wpos + rec)
        if kind in (KIND_SPILL, KIND_SPILL_ERR):
            self._spills.append((wpos + rec, self._last_spill_path))
            if witness:
                _chandbg.note_spill_pin(self._witness_key(),
                                        self._last_spill_path,
                                        wpos + rec)
        if witness:
            _chandbg.note_cursor(self._witness_key(), "wpos", wpos + rec)
            _chandbg.note_send(self._witness_key(), seq, size,
                               window=(self._u64(_O_RSEQ),
                                       self.capacity))
        self._settle_spills(self._u64(_O_RPOS))
        if traced:
            _tracing.emit_span(
                "dag.channel.send", t0w, time.time(),
                attrs={"edge": self.edge, "seq": seq, "bytes": size,
                       "transport": "ring"})

    def _spill_out(self, payload: bytes, seq: int) -> bytes:
        name = f"rtpu-spill-{self.channel_id.hex()}-{seq}.sp"
        path = os.path.join(channel_dir(), name)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
        _resdbg.note_acquire("channel_spill",
                             key=(os.getpid(), path), owner=self)
        self._last_spill_path = path
        return name.encode()

    def _settle_spills(self, rpos: int) -> None:
        while self._spills and self._spills[0][0] <= rpos:
            _end, path = self._spills.pop(0)
            _resdbg.note_release("channel_spill", (os.getpid(), path))
            _chandbg.note_spill_release(self._witness_key(), path)

    # -------------------------------------------------------------- reader

    def read(self, seq: int, timeout: Optional[float] = None) -> Any:
        """Blocking ordered read; the record's seq must match ``seq``
        (SPSC streams are strictly ordered — a mismatch is a protocol
        violation, not a wait). Raises carried errors; a stop sentinel
        raises ChannelClosedError."""
        from ray_tpu.util import tracing as _tracing

        self._ensure()
        self._role = "r"
        traced = _tracing.enabled()
        t0w = time.time() if traced else 0.0
        kind, got_seq, payload = self._next_record(timeout)
        if got_seq != seq:
            raise ChannelClosedError(
                f"channel {self.edge}: seq inversion (got {got_seq}, "
                f"expected {seq})")
        if traced:
            _tracing.emit_span(
                "dag.channel.recv", t0w, time.time(),
                attrs={"edge": self.edge, "seq": seq,
                       "bytes": len(payload), "transport": "ring"})
        if kind == KIND_STOP:
            raise ChannelClosedError(f"channel {self.edge} closed")
        if kind == KIND_ERR:
            raise pickle.loads(payload)
        return pickle.loads(payload)[1]

    def _spill_in(self, kind: int, name_b: bytes):
        path = os.path.join(channel_dir(), name_b.decode())
        # CLAIM the side file by atomic rename before touching its
        # contents: the writer's close() reclaims spills it believes
        # unconsumed once its grace window expires, and a plain open()
        # here raced that unlink (the bench.py --dag flake — the reader
        # had dequeued the ring record but not yet opened the file).
        # rename vs unlink is atomic either way: if we win, the writer's
        # unlink of the original ENOENTs harmlessly; if the writer won,
        # the rename fails and the stream is truthfully reported closed.
        claimed = path + ".rd"
        try:
            os.rename(path, claimed)
        except FileNotFoundError:
            raise ChannelClosedError(
                f"channel {self.edge}: spill {os.path.basename(path)} "
                "reclaimed by writer close before the reader consumed "
                "it") from None
        with open(claimed, "rb") as f:
            payload = f.read()
        try:
            os.unlink(claimed)
        except OSError:
            pass
        return (KIND_OK if kind == KIND_SPILL else KIND_ERR), payload

    def _next_record(self, timeout: Optional[float]):
        mm = self._mm
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        waiter = _Waiter()
        while True:
            rpos = self._u64(_O_RPOS)
            wpos = self._u64(_O_WPOS)
            if wpos > rpos:
                off = rpos % self._cap
                tail = self._cap - off
                if tail < _REC_HDR:
                    self._set_u64(_O_RPOS, rpos + tail)
                    continue
                size, kind, seq, clock, crc = struct.unpack_from(
                    "<IIQQI", mm, _HDR + off)
                if size == _WRAP:
                    self._set_u64(_O_RPOS, rpos + tail)
                    continue
                payload = bytes(mm[_HDR + off + _REC_HDR:
                                   _HDR + off + _REC_HDR + size])
                if kind in (KIND_SPILL, KIND_SPILL_ERR):
                    # Resolve the side file BEFORE publishing the
                    # cursor: the writer settles its spill ledger on
                    # cursor advance, so advancing first would let a
                    # reader crash in the window strand the file with
                    # the witness showing it released.
                    kind, payload = self._spill_in(kind, payload)
                new_rpos = rpos + _REC_HDR + _align8(size)
                self._set_u64(_O_RPOS, new_rpos)
                self._set_u64(_O_RSEQ, seq + 1)
                self._read_seq = seq + 1
                if _chandbg.enabled():
                    _chandbg.note_cursor(self._witness_key(), "rpos",
                                         new_rpos)
                    _chandbg.note_consume(self._witness_key(), seq,
                                          clock, crc, payload)
                return kind, seq, payload
            if self._peer_closed("r"):
                raise ChannelClosedError(
                    f"channel {self.edge}: writer closed with no "
                    "pending record")
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    "ring read timed out",
                    edge=self.edge, seq=self._read_seq,
                    bytes_in_flight=wpos - rpos,
                    peer_alive=not self._peer_closed("r"))
            waiter.wait()

    # ------------------------------------------------------------ teardown

    def wait_consumed(self, seq: int, timeout: float = 10.0) -> bool:
        """Writer-side handshake: block until the reader consumed
        message ``seq`` (its read_seq cursor passed it)."""
        self._ensure()
        deadline = time.monotonic() + timeout
        pause = 0.001
        while self._u64(_O_RSEQ) <= seq:
            if self._peer_closed("w") or time.monotonic() > deadline:
                return self._u64(_O_RSEQ) > seq
            time.sleep(pause)
            pause = min(pause * 2, 0.02)
        return True

    def drain(self, from_seq: int, span: int = 0) -> None:
        """Teardown cleanup: discard whatever is left and close."""
        if self._mm is not None and not self._closed:
            try:
                if self._role != "w":
                    self._set_u64(_O_RPOS, self._u64(_O_WPOS))
            except (ValueError, OSError):
                pass
        self.close(unlink=True)

    def close(self, unlink: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        if self._mm is None:
            # Endpoint never mapped the ring: still honor unlink (the
            # PEER may have created the file).
            if unlink:
                try:
                    os.unlink(self._ring_path())
                except OSError:
                    pass
            return
        try:
            off = _O_WCLOSED if self._role == "w" else _O_RCLOSED
            if self._role is not None:
                self._mm[off] = 1
            elif unlink:
                # Endpoint that never transferred: mark both sides so a
                # blocked peer wakes either way.
                self._mm[_O_WCLOSED] = 1
        except (ValueError, OSError):
            pass
        # Reclaim spills the reader never consumed (reader death must
        # not strand multi-MB side files: the res-lint
        # acquire-without-release shape, settled here). But a spill
        # whose ring record the reader ALREADY dequeued may be opened by
        # _spill_in any instant now — an immediate unlink raced that
        # open and killed the reader with FileNotFoundError (the
        # bench.py --dag flake). Observe consumption first: poll rpos
        # until the ledger settles, the reader declares itself closed,
        # or the grace window expires — only what is still unconsumed
        # THEN is treated as stranded and reclaimed.
        if self._spills and self._role == "w":
            from ray_tpu.core.config import GLOBAL_CONFIG as cfg

            deadline = time.monotonic() + cfg.dag_spill_reclaim_grace_s
            pause = 0.0005
            while self._spills:
                try:
                    self._settle_spills(self._u64(_O_RPOS))
                    if (not self._spills or self._mm[_O_RCLOSED]
                            or time.monotonic() > deadline):
                        break
                except (ValueError, OSError):
                    break
                time.sleep(pause)
                pause = min(pause * 2, 0.02)
        if self._spills and self._role == "w" and _chandbg.enabled():
            # A pin whose record the reader already dequeued but that
            # never settled is the PR 19 reclaim race: reclaiming it
            # below would unlink a file _spill_in may open any instant.
            try:
                _chandbg.note_close(self._witness_key(),
                                    self._u64(_O_RPOS))
            except (ValueError, OSError):
                pass
        for _end, path in self._spills:
            try:
                os.unlink(path)
            except OSError:
                pass
            _resdbg.note_release("channel_spill", (os.getpid(), path))
            _chandbg.note_spill_release(self._witness_key(), path)
        self._spills = []
        path, mm, self._mm = self._path, self._mm, None
        try:
            mm.close()
        except (ValueError, OSError):
            pass
        _resdbg.note_release("channel_ring", (os.getpid(), id(self)))
        if unlink and path:
            try:
                os.unlink(path)
            except OSError:
                pass

    # The compiled DAG ships channel objects inside actor schedules.
    def __reduce__(self):
        return (RingChannel, (self.channel_id, self.capacity,
                              self._ring_bytes, self.edge))
