"""Compiled-DAG channels: the data plane the compiled graph runs on.

Two transports, selected at compile time once actor placement is known
(``compiled_dag._resolve_channel_kinds``):

- :class:`ShmChannel` (``ring.RingChannel``) — same-node edges ride an
  SPSC shm ring buffer (one mmap in /dev/shm per edge): a hop is a
  memcpy + an 8-byte cursor publish. See ``ring.py``.
- :class:`CrossNodeChannel` (``peer.CrossNodeChannel``) — cross-node
  edges ride a persistent peer socket carrying pickle-5 scatter frames
  with credit-based backpressure, negotiated ONCE through the head's
  channel registry. See ``peer.py``.

Both implement the same surface the compiled DAG drives::

    write(value, seq) / write_error(exc, seq) / write_stop(seq)
    read(seq, timeout)            # ordered; consumption is the ack
    wait_consumed(seq, timeout)   # teardown handshake
    drain(from_seq) / close()

``ChannelWriter`` / ``ChannelReader`` wrap an endpoint with a running
seq counter for long-lived streams (the disaggregated-serving KV mesh)
where callers want ``send()``/``recv()`` instead of explicit seqs.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ray_tpu.dag.errors import (ChannelClosedError, ChannelError,
                                ChannelTimeoutError)
from ray_tpu.dag.peer import (ChannelEndpoint, CrossNodeChannel,
                              endpoint_violations, get_endpoint)
from ray_tpu.dag.ring import RingChannel, channel_dir

#: Same-node transport under its historical name (the compiled DAG and
#: its tests type-check channel kinds by these two class names).
ShmChannel = RingChannel

__all__ = [
    "ChannelClosedError", "ChannelEndpoint", "ChannelError",
    "ChannelReader", "ChannelTimeoutError", "ChannelWriter",
    "CrossNodeChannel", "RingChannel", "ShmChannel", "channel_dir",
    "endpoint_violations", "get_endpoint", "open_edge",
]


def open_edge(channel_id: bytes, *, writer_node: Optional[str],
              reader_node: Optional[str],
              writer_addr: Optional[str] = None,
              reader_addr: Optional[str] = None,
              capacity: int = 8, ring_bytes: Optional[int] = None,
              edge: str = ""):
    """Placement-aware channel construction for data-plane edges OUTSIDE
    compiled DAGs (the streaming Dataset executor, exchange meshes): the
    same ring-vs-peer decision ``compiled_dag._resolve_channel_kinds``
    makes at compile time, packaged for callers that already know both
    endpoints' nodes. Same node (or unknown placement, e.g. a
    single-process runtime) -> shm SPSC ring; different nodes -> peer
    socket with credit backpressure (both node ADDRESSES required)."""
    if (writer_node is None or reader_node is None
            or writer_node == reader_node):
        return RingChannel(channel_id, capacity=capacity,
                           ring_bytes=ring_bytes, edge=edge)
    if not writer_addr or not reader_addr:
        raise ValueError(
            f"cross-node edge {edge or channel_id.hex()[:8]} needs both "
            f"node addresses ({writer_node!r} -> {reader_node!r})")
    ch = CrossNodeChannel(channel_id, writer_addr, reader_addr,
                          capacity=capacity)
    ch.edge = edge
    return ch


class ChannelWriter:
    """Thread-safe auto-seq facade over a channel's writer end: many
    producer threads, ONE ordered stream (the channel stays
    single-writer — the lock serializes, the counter orders)."""

    def __init__(self, channel):
        self.channel = channel
        self._seq = 0
        self._lock = threading.Lock()

    def send(self, value: Any, timeout: Optional[float] = None) -> int:
        with self._lock:
            seq = self._seq
            self._seq += 1
            self.channel.write(value, seq, timeout=timeout)
            return seq

    def send_stop(self) -> None:
        with self._lock:
            try:
                self.channel.write_stop(self._seq)
                self._seq += 1
            except (ChannelError, ChannelTimeoutError, OSError):
                pass

    def close(self) -> None:
        close = getattr(self.channel, "close", None)
        if close is not None:
            close()


class ChannelReader:
    """Auto-seq facade over a channel's reader end (single consumer)."""

    def __init__(self, channel):
        self.channel = channel
        self._seq = 0

    def prepare(self) -> None:
        prep = getattr(self.channel, "prepare_read", None)
        if prep is not None:
            prep()

    def recv(self, timeout: Optional[float] = None) -> Any:
        value = self.channel.read(self._seq, timeout=timeout)
        self._seq += 1
        return value

    def close(self) -> None:
        close = getattr(self.channel, "close", None)
        if close is not None:
            try:
                close(unlink=True)
            except TypeError:
                close()
