"""Shm-backed channels: the compiled DAG's data plane.

Parity target: reference python/ray/experimental/channel/
shared_memory_channel.py:151 (Channel over mutable plasma objects).
Re-designed over this runtime's object plane: each (channel, seq) message
is one immutable store object with a DETERMINISTIC id
(sha224(channel_id || seq) — exactly the store's 28-byte key size), so
writer and reader processes rendezvous with no coordination service.
Consumption is deletion (the ack), and backpressure is the writer waiting
for the message `capacity` slots back to be consumed. Wakeups ride the
store's process-shared seal condvar — a compiled-DAG hop costs a shm write
+ condvar broadcast, not an RPC through the scheduler.
"""

from __future__ import annotations

import hashlib
import pickle
import time
from typing import Any, Optional, Tuple

from ray_tpu.core.ids import ObjectID


class ChannelTimeoutError(TimeoutError):
    pass


class ChannelClosedError(RuntimeError):
    pass


_STOP = b"\x00__rtpu_channel_stop__"


def _msg_oid(channel_id: bytes, seq: int) -> ObjectID:
    return ObjectID(hashlib.sha224(
        channel_id + seq.to_bytes(8, "little")).digest())


class ShmChannel:
    """Single-writer single-reader ordered message channel.

    Both ends construct it from the (serializable) channel_id; the store
    handle comes from the hosting process's runtime. Same-node only — the
    compiled DAG scheduler co-locates or falls back to the RPC path.
    """

    def __init__(self, channel_id: bytes, capacity: int = 8):
        self.channel_id = channel_id
        self.capacity = capacity
        self._store = None

    def _ensure_store(self):
        if self._store is None:
            from ray_tpu.core.runtime_context import require_runtime

            self._store = require_runtime().store
        return self._store

    # ------------------------------------------------------------ writer

    def write(self, value: Any, seq: int, timeout: Optional[float] = None,
              _raw: Optional[bytes] = None) -> None:
        store = self._ensure_store()
        payload = _raw if _raw is not None else pickle.dumps(
            ("ok", value), protocol=5)
        # Backpressure: the slot `capacity` behind must have been consumed.
        # Exponential backoff (0.5ms -> 10ms): contains() may stat the
        # spill dir, and a tight poll would be a syscall storm per stalled
        # writer.
        if seq >= self.capacity:
            old = _msg_oid(self.channel_id, seq - self.capacity)
            deadline = None if timeout is None else time.monotonic() + timeout
            pause = 0.0005
            while store.contains(old):
                if deadline is not None and time.monotonic() > deadline:
                    raise ChannelTimeoutError(
                        f"reader {self.capacity} messages behind")
                time.sleep(pause)
                pause = min(pause * 2, 0.01)
        store.put_bytes(_msg_oid(self.channel_id, seq), payload)

    def write_error(self, exc: BaseException, seq: int) -> None:
        self.write(None, seq, _raw=pickle.dumps(("err", exc), protocol=5))

    def write_stop(self, seq: int) -> None:
        self.write(None, seq, _raw=pickle.dumps(("stop", None), protocol=5))

    # ------------------------------------------------------------ reader

    def read(self, seq: int, timeout: Optional[float] = None) -> Any:
        """Blocking read of message `seq`; consumed (deleted) on return.
        Raises the carried exception for error messages and
        ChannelClosedError for stop sentinels."""
        store = self._ensure_store()
        oid = _msg_oid(self.channel_id, seq)
        ms = -1 if timeout is None else max(1, int(timeout * 1000))
        buf = store.get(oid, timeout_ms=ms)
        if buf is None:
            raise ChannelTimeoutError(
                f"channel read timed out (seq={seq})")
        try:
            kind, value = pickle.loads(bytes(buf.buffer))
        finally:
            buf.release()
        store.delete(oid)  # consumption ack: frees the writer's slot
        if kind == "err":
            raise value
        if kind == "stop":
            raise ChannelClosedError("channel closed")
        return value

    def wait_consumed(self, seq: int, timeout: float = 10.0) -> bool:
        """Block until message `seq` has been consumed (teardown
        handshake). True if consumed within the timeout."""
        store = self._ensure_store()
        oid = _msg_oid(self.channel_id, seq)
        deadline = time.monotonic() + timeout
        pause = 0.001
        while store.contains(oid):
            if time.monotonic() > deadline:
                return False
            time.sleep(pause)
            pause = min(pause * 2, 0.05)
        return True

    def drain(self, from_seq: int, span: int = 64) -> None:
        """Best-effort cleanup of unconsumed messages (teardown)."""
        store = self._ensure_store()
        for seq in range(max(0, from_seq - span), from_seq + span):
            try:
                store.delete(_msg_oid(self.channel_id, seq))
            except Exception:
                pass

    def __reduce__(self):
        return (ShmChannel, (self.channel_id, self.capacity))


class CrossNodeChannel:
    """Single-writer single-reader ordered channel ACROSS nodes.

    Parity target: the reference's cross-node mutable-object channels
    (reference: RegisterMutableObject/PushMutableObject,
    node_manager.proto:444-446) re-designed over this runtime's push
    transfer: the writer seals each message into its LOCAL store and
    pushes it to the reader's node (rpc_push_object — receiver-driven
    chunk protocol); the reader consumes from its local store and pushes
    a tiny ACK object back. Backpressure: the writer admits seq only
    after ack(seq - capacity) arrived (then deletes it), so at most
    `capacity` messages are in flight node-to-node."""

    def __init__(self, channel_id: bytes, writer_node_addr: str,
                 reader_node_addr: str, capacity: int = 8):
        self.channel_id = channel_id
        self.writer_node_addr = writer_node_addr
        self.reader_node_addr = reader_node_addr
        self.capacity = capacity
        self._rt = None
        self._acked_through = -1  # writer-side cumulative consumption mark

    def _runtime(self):
        if self._rt is None:
            from ray_tpu.core.runtime_context import require_runtime

            self._rt = require_runtime()
        return self._rt

    def _ack_oid(self, seq: int) -> ObjectID:
        return _msg_oid(self.channel_id + b"#ack", seq)

    def _delete_unregistered(self, store, oid: ObjectID) -> None:
        """Delete + drop the head's directory entry: pushed copies were
        registered object_added on arrival, and a raw store delete would
        leak one directory row per message forever. The removal rides the
        runtime's BATCHED notify outbox — a direct head.notify here could
        overtake a same-process put's still-queued object_added and leave
        the head holding a permanently stale add."""
        store.delete(oid)
        rt = self._runtime()
        try:
            rt._queue_object_notify("rm", oid.binary())
        except Exception:
            pass

    # ------------------------------------------------------------ writer

    def _observe_acks(self, store, upto_seq: int) -> None:
        """Advance the cumulative consumption mark: the reader consumes IN
        ORDER, so ack(m) present implies everything <= m was consumed —
        one LOST ack therefore costs nothing once a later one lands
        (per-seq waits would deadlock on a single dropped ack push)."""
        for s in range(self._acked_through + 1, upto_seq + 1):
            ack = self._ack_oid(s)
            if store.contains(ack):
                self._acked_through = max(self._acked_through, s)
        # Ring-clean observed acks (including ghosts re-pushed by retries).
        for s in range(max(0, self._acked_through - 2 * self.capacity),
                       self._acked_through + 1):
            try:
                self._delete_unregistered(store, self._ack_oid(s))
            except Exception:
                pass

    def write(self, value: Any, seq: int, timeout: Optional[float] = None,
              _raw: Optional[bytes] = None) -> None:
        rt = self._runtime()
        store = rt.store
        payload = _raw if _raw is not None else pickle.dumps(
            ("ok", value), protocol=5)
        if seq >= self.capacity:
            needed = seq - self.capacity
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            pause = 0.0005
            while self._acked_through < needed:
                self._observe_acks(store, seq - 1)
                if self._acked_through >= needed:
                    break
                if deadline is not None and time.monotonic() > deadline:
                    raise ChannelTimeoutError(
                        f"reader {self.capacity} messages behind")
                time.sleep(pause)
                pause = min(pause * 2, 0.01)
        oid = _msg_oid(self.channel_id, seq)
        store.put_bytes(oid, payload)
        # A False reply may be one dropped inner transfer RPC (chaos, a
        # transient peer hiccup), not a dead reader: retry before
        # declaring the channel closed. Double-pushes are safe — the
        # reader consumes each seq once and ring-cleans ghosts. The outer
        # per-try window EXCEEDS the handler's internal wait
        # (timeout_ms/1000 + 5) so slow-but-succeeding transfers are not
        # spuriously retried; transport exceptions become the same
        # ChannelClosedError as exhausted retries, and the local copy is
        # dropped on EVERY exit (leaks otherwise).
        ok = False
        try:
            for attempt in range(3):
                try:
                    ok = rt.node.retrying_call(
                        "push_object", oid.binary(),
                        self.reader_node_addr, 10000, timeout=18)
                except Exception:
                    ok = False
                if ok:
                    break
                if attempt < 2:
                    time.sleep(0.2 * (attempt + 1))
        finally:
            # Local copy served its purpose once pushed; drop it so
            # channels never accumulate in the writer's store.
            store.delete(oid)
        if not ok:
            raise ChannelClosedError(
                f"push to {self.reader_node_addr} failed (seq={seq})")

    def write_error(self, exc: BaseException, seq: int) -> None:
        self.write(None, seq, _raw=pickle.dumps(("err", exc), protocol=5))

    def write_stop(self, seq: int) -> None:
        self.write(None, seq, _raw=pickle.dumps(("stop", None), protocol=5))

    # ------------------------------------------------------------ reader

    def read(self, seq: int, timeout: Optional[float] = None) -> Any:
        rt = self._runtime()
        store = rt.store
        oid = _msg_oid(self.channel_id, seq)
        ms = -1 if timeout is None else max(1, int(timeout * 1000))
        buf = store.get(oid, timeout_ms=ms)
        if buf is None:
            raise ChannelTimeoutError(
                f"cross-node channel read timed out (seq={seq})")
        try:
            kind, value = pickle.loads(bytes(buf.buffer))
        finally:
            buf.release()
        self._delete_unregistered(store, oid)
        # Ring-clean a long-consumed slot: a retried push may have
        # RESURRECTED an already-consumed message (push is not
        # idempotent); nothing else would ever delete the ghost.
        if seq >= 2 * self.capacity:
            try:
                self._delete_unregistered(
                    store, _msg_oid(self.channel_id,
                                    seq - 2 * self.capacity))
            except Exception:
                pass
        # Ack: a 1-byte object pushed back to the writer's node. Lost acks
        # are tolerated — the writer's consumption mark advances on ANY
        # later ack (ordered consumption implies the earlier ones).
        ack = self._ack_oid(seq)
        try:
            store.put_bytes(ack, b"\x01")
            rt.node.retrying_call("push_object", ack.binary(),
                                  self.writer_node_addr, 5000, timeout=12)
            store.delete(ack)
        except Exception:
            pass
        if kind == "err":
            raise value
        if kind == "stop":
            raise ChannelClosedError("channel closed")
        return value

    def wait_consumed(self, seq: int, timeout: float = 10.0) -> bool:
        """Writer-side teardown handshake: consumed == its ack arrived
        (or the cumulative mark already passed it)."""
        rt = self._runtime()
        store = rt.store
        ack = self._ack_oid(seq)
        deadline = time.monotonic() + timeout
        pause = 0.001
        while self._acked_through < seq and not store.contains(ack):
            if time.monotonic() > deadline:
                return False
            time.sleep(pause)
            pause = min(pause * 2, 0.05)
        return True

    def drain(self, from_seq: int, span: int = 64) -> None:
        rt = self._runtime()
        store = rt.store
        for seq in range(max(0, from_seq - span), from_seq + span):
            for oid in (_msg_oid(self.channel_id, seq),
                        self._ack_oid(seq)):
                try:
                    store.delete(oid)
                except Exception:
                    pass

    def __reduce__(self):
        return (CrossNodeChannel,
                (self.channel_id, self.writer_node_addr,
                 self.reader_node_addr, self.capacity))
