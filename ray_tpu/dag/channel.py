"""Shm-backed channels: the compiled DAG's data plane.

Parity target: reference python/ray/experimental/channel/
shared_memory_channel.py:151 (Channel over mutable plasma objects).
Re-designed over this runtime's object plane: each (channel, seq) message
is one immutable store object with a DETERMINISTIC id
(sha224(channel_id || seq) — exactly the store's 28-byte key size), so
writer and reader processes rendezvous with no coordination service.
Consumption is deletion (the ack), and backpressure is the writer waiting
for the message `capacity` slots back to be consumed. Wakeups ride the
store's process-shared seal condvar — a compiled-DAG hop costs a shm write
+ condvar broadcast, not an RPC through the scheduler.
"""

from __future__ import annotations

import hashlib
import pickle
import time
from typing import Any, Optional, Tuple

from ray_tpu.core.ids import ObjectID


class ChannelTimeoutError(TimeoutError):
    pass


class ChannelClosedError(RuntimeError):
    pass


_STOP = b"\x00__rtpu_channel_stop__"


def _msg_oid(channel_id: bytes, seq: int) -> ObjectID:
    return ObjectID(hashlib.sha224(
        channel_id + seq.to_bytes(8, "little")).digest())


class ShmChannel:
    """Single-writer single-reader ordered message channel.

    Both ends construct it from the (serializable) channel_id; the store
    handle comes from the hosting process's runtime. Same-node only — the
    compiled DAG scheduler co-locates or falls back to the RPC path.
    """

    def __init__(self, channel_id: bytes, capacity: int = 8):
        self.channel_id = channel_id
        self.capacity = capacity
        self._store = None

    def _ensure_store(self):
        if self._store is None:
            from ray_tpu.core.runtime_context import require_runtime

            self._store = require_runtime().store
        return self._store

    # ------------------------------------------------------------ writer

    def write(self, value: Any, seq: int, timeout: Optional[float] = None,
              _raw: Optional[bytes] = None) -> None:
        store = self._ensure_store()
        payload = _raw if _raw is not None else pickle.dumps(
            ("ok", value), protocol=5)
        # Backpressure: the slot `capacity` behind must have been consumed.
        # Exponential backoff (0.5ms -> 10ms): contains() may stat the
        # spill dir, and a tight poll would be a syscall storm per stalled
        # writer.
        if seq >= self.capacity:
            old = _msg_oid(self.channel_id, seq - self.capacity)
            deadline = None if timeout is None else time.monotonic() + timeout
            pause = 0.0005
            while store.contains(old):
                if deadline is not None and time.monotonic() > deadline:
                    raise ChannelTimeoutError(
                        f"reader {self.capacity} messages behind")
                time.sleep(pause)
                pause = min(pause * 2, 0.01)
        store.put_bytes(_msg_oid(self.channel_id, seq), payload)

    def write_error(self, exc: BaseException, seq: int) -> None:
        self.write(None, seq, _raw=pickle.dumps(("err", exc), protocol=5))

    def write_stop(self, seq: int) -> None:
        self.write(None, seq, _raw=pickle.dumps(("stop", None), protocol=5))

    # ------------------------------------------------------------ reader

    def read(self, seq: int, timeout: Optional[float] = None) -> Any:
        """Blocking read of message `seq`; consumed (deleted) on return.
        Raises the carried exception for error messages and
        ChannelClosedError for stop sentinels."""
        store = self._ensure_store()
        oid = _msg_oid(self.channel_id, seq)
        ms = -1 if timeout is None else max(1, int(timeout * 1000))
        buf = store.get(oid, timeout_ms=ms)
        if buf is None:
            raise ChannelTimeoutError(
                f"channel read timed out (seq={seq})")
        try:
            kind, value = pickle.loads(bytes(buf.buffer))
        finally:
            buf.release()
        store.delete(oid)  # consumption ack: frees the writer's slot
        if kind == "err":
            raise value
        if kind == "stop":
            raise ChannelClosedError("channel closed")
        return value

    def wait_consumed(self, seq: int, timeout: float = 10.0) -> bool:
        """Block until message `seq` has been consumed (teardown
        handshake). True if consumed within the timeout."""
        store = self._ensure_store()
        oid = _msg_oid(self.channel_id, seq)
        deadline = time.monotonic() + timeout
        pause = 0.001
        while store.contains(oid):
            if time.monotonic() > deadline:
                return False
            time.sleep(pause)
            pause = min(pause * 2, 0.05)
        return True

    def drain(self, from_seq: int, span: int = 64) -> None:
        """Best-effort cleanup of unconsumed messages (teardown)."""
        store = self._ensure_store()
        for seq in range(max(0, from_seq - span), from_seq + span):
            try:
                store.delete(_msg_oid(self.channel_id, seq))
            except Exception:
                pass

    def __reduce__(self):
        return (ShmChannel, (self.channel_id, self.capacity))
