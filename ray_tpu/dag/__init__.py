"""ray_tpu.dag: compiled multi-actor execution graphs (aDAG equivalent).

Parity target: the reference's Compiled Graphs surface (python/ray/dag —
InputNode/MultiOutputNode/.bind()/experimental_compile) re-designed for
this runtime: compile turns the bound graph into per-actor schedules
over PRE-NEGOTIATED per-edge channels — shm ring buffers for same-node
edges (ring.py), persistent peer sockets carrying scatter frames for
cross-node edges (peer.py) — so a steady-state hop never touches the
head, the scheduler, or a lease. The disaggregated prefill/decode
serving tier (serve/llm.py) streams KV pages over the same channels.

Runtime witness: ``RTPU_DEBUG_CHAN=1`` (zero overhead off) makes every
ring/peer endpoint check its own frame protocol online — per-edge seq
monotonicity, credit windows, ack-after-consume, cursor ordering, a
Lamport clock carried in frame headers, a sampled payload checksum
(every 16th frame, send vs. consume — catches torn reads and
mutate-after-send), and spill side-file pin/reclaim pairing.
Violations print ``RTPU_CHAN:`` lines, are queryable via
``devtools.chan_debug.violations()``, and ride flight-recorder dumps
under the ``"chan_debug"`` key; the static half is the rtpu-lint
``chan`` rule family (``devtools/chanlint.py``).
"""

from ray_tpu.dag.channel import (ChannelClosedError, ChannelEndpoint,
                                 ChannelError, ChannelReader,
                                 ChannelTimeoutError, ChannelWriter,
                                 CrossNodeChannel, RingChannel, ShmChannel,
                                 endpoint_violations)
from ray_tpu.dag.collective_node import (CollectiveOutputNode, allreduce)
from ray_tpu.dag.communicator import (Communicator, CpuCommunicator,
                                      JaxHostCommunicator)
from ray_tpu.dag.compiled_dag import CompiledDAG, CompiledDAGRef
from ray_tpu.dag.dag_node import (ClassMethodNode, DAGNode, InputNode,
                                  MultiOutputNode)

__all__ = [
    "ChannelClosedError", "ChannelEndpoint", "ChannelError",
    "ChannelReader", "ChannelTimeoutError", "ChannelWriter",
    "ClassMethodNode", "CollectiveOutputNode", "Communicator",
    "CompiledDAG", "CompiledDAGRef", "CpuCommunicator", "CrossNodeChannel",
    "DAGNode", "InputNode", "JaxHostCommunicator", "MultiOutputNode",
    "RingChannel", "ShmChannel", "allreduce", "endpoint_violations",
]
