"""ray_tpu.dag: compiled multi-actor execution graphs (aDAG equivalent).

Parity target: the reference's Compiled Graphs surface (python/ray/dag/ —
InputNode/MultiOutputNode/.bind()/experimental_compile) re-designed for
this runtime: schedules execute over shm channels with condvar wakeups
instead of per-call RPC (see compiled_dag.py).
"""

from ray_tpu.dag.channel import (ChannelClosedError, ChannelTimeoutError,
                                 ShmChannel)
from ray_tpu.dag.collective_node import (CollectiveOutputNode, allreduce)
from ray_tpu.dag.communicator import (Communicator, CpuCommunicator,
                                      JaxHostCommunicator)
from ray_tpu.dag.compiled_dag import CompiledDAG, CompiledDAGRef
from ray_tpu.dag.dag_node import (ClassMethodNode, DAGNode, InputNode,
                                  MultiOutputNode)

__all__ = [
    "ChannelClosedError", "ChannelTimeoutError", "ClassMethodNode",
    "CollectiveOutputNode", "Communicator", "CompiledDAG", "CompiledDAGRef",
    "CpuCommunicator", "DAGNode", "InputNode", "JaxHostCommunicator",
    "MultiOutputNode", "ShmChannel", "allreduce",
]
