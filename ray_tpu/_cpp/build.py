"""Build the native components (g++ -O2 -shared) into ray_tpu/_cpp/*.so.

Run directly (`python ray_tpu/_cpp/build.py`) or let
`ray_tpu.core.shm_store.ensure_built()` invoke it lazily on first use.

NOTE: shm_store.cc layout v2 (sharded arena) changed the mapped segment
format AND the library ABI (rtpu_store_create gained n_shards,
rtpu_obj_create gained pref_shard). Any previously built .so — including
one an RTPU_SHM_STORE_SO override points at — must be rebuilt from the
current source; the Python client checks rtpu_lib_layout_version() at
load and refuses stale builds with a clear error. On containers whose
glibc rejects the checked-in binary, build OUT of tree and point
RTPU_SHM_STORE_SO at the result (see .claude/skills/verify/SKILL.md).
"""

from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

TARGETS = [
    ("shm_store.cc", "libshm_store.so", ["-lpthread", "-lrt"]),
]


def build(verbose: bool = True, force: bool = False) -> list[str]:
    built = []
    for src, out, libs in TARGETS:
        src_p = os.path.join(HERE, src)
        out_p = os.path.join(HERE, out)
        if (not force and os.path.exists(out_p)
                and os.path.getmtime(out_p) >= os.path.getmtime(src_p)):
            built.append(out_p)
            continue
        cmd = ["g++", "-O2", "-g", "-std=c++17", "-shared", "-fPIC",
               "-o", out_p, src_p] + libs
        if verbose:
            print("+", " ".join(cmd), file=sys.stderr)
        subprocess.run(cmd, check=True)
        built.append(out_p)
    return built


if __name__ == "__main__":
    build()
