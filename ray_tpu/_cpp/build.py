"""Build the native components (g++ -O2 -shared) into ray_tpu/_cpp/*.so.

Run directly (`python ray_tpu/_cpp/build.py`) or let
`ray_tpu.core.shm_store.ensure_built()` invoke it lazily on first use.

NOTE: shm_store.cc layout v2 (sharded arena) changed the mapped segment
format AND the library ABI (rtpu_store_create gained n_shards,
rtpu_obj_create gained pref_shard). Any previously built .so — including
one an RTPU_SHM_STORE_SO override points at — must be rebuilt from the
current source; the Python client checks rtpu_lib_layout_version() at
load and refuses stale builds with a clear error. On containers whose
glibc rejects the checked-in binary, build OUT of tree and point
RTPU_SHM_STORE_SO at the result (see .claude/skills/verify/SKILL.md).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

TARGETS = [
    ("shm_store.cc", "libshm_store.so", ["-lpthread", "-lrt"]),
]

#: --sanitize flag -> extra g++ flags. Sanitized builds are for hunting
#: races/overflows in shm_store.cc under the dataplane tests; they are
#: slower and must NEVER overwrite the checked-in .so — they build
#: out-of-tree and are loaded via RTPU_SHM_STORE_SO.
SANITIZERS = {
    "address": ["-fsanitize=address", "-fno-omit-frame-pointer"],
    "thread": ["-fsanitize=thread", "-fno-omit-frame-pointer"],
}


def build(verbose: bool = True, force: bool = False,
          sanitize: str | None = None,
          out_dir: str | None = None) -> list[str]:
    extra: list[str] = []
    if sanitize is not None:
        extra = SANITIZERS[sanitize]
        if out_dir is None:
            # Default the sanitized artifact out of tree: an in-tree
            # sanitized .so would both dirty the checked-in binary and
            # drag libasan/libtsan into every normal cluster boot.
            out_dir = os.path.join("/tmp", f"rtpu_native_{sanitize}")
        force = True  # flags changed: mtime shortcut would lie
    dest = out_dir or HERE
    os.makedirs(dest, exist_ok=True)
    built = []
    for src, out, libs in TARGETS:
        src_p = os.path.join(HERE, src)
        out_p = os.path.join(dest, out)
        if (not force and os.path.exists(out_p)
                and os.path.getmtime(out_p) >= os.path.getmtime(src_p)):
            built.append(out_p)
            continue
        cmd = (["g++", "-O2", "-g", "-std=c++17", "-shared", "-fPIC"]
               + extra + ["-o", out_p, src_p] + libs)
        if verbose:
            print("+", " ".join(cmd), file=sys.stderr)
        subprocess.run(cmd, check=True)
        built.append(out_p)
    if sanitize is not None and verbose:
        # dlopen-ing a sanitized .so into a plain python process aborts
        # ("runtime does not come first in initial library list") unless
        # the sanitizer runtime is preloaded.
        rt_lib = {"address": "libasan.so", "thread": "libtsan.so"}[sanitize]
        preload = subprocess.run(
            ["g++", f"-print-file-name={rt_lib}"],
            capture_output=True, text=True).stdout.strip()
        print(f"sanitized ({sanitize}) build is out-of-tree; run the "
              f"cluster against it with:\n"
              f"  export RTPU_SHM_STORE_SO={built[0]}\n"
              f"  export LD_PRELOAD={preload or rt_lib}",
              file=sys.stderr)
    return built


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--sanitize", choices=sorted(SANITIZERS),
                   help="build with AddressSanitizer/ThreadSanitizer "
                        "(out-of-tree; load via RTPU_SHM_STORE_SO)")
    p.add_argument("--out-dir",
                   help="directory for the built .so (default: in-tree, "
                        "or /tmp/rtpu_native_<sanitizer> when "
                        "--sanitize is given)")
    p.add_argument("--force", action="store_true",
                   help="rebuild even if the output is newer than the "
                        "source")
    args = p.parse_args()
    build(force=args.force, sanitize=args.sanitize, out_dir=args.out_dir)


if __name__ == "__main__":
    main()
