// rtpu shm object store: the per-node object plane (plasma-equivalent).
//
// Design parity with the reference's plasma store
// (reference: src/ray/object_manager/plasma/store.h:55,
//  object_lifecycle_manager.h:101, eviction_policy.h:105), re-architected
// for the TPU era instead of ported: plasma is a *server process* speaking a
// flatbuffer protocol over a unix socket (reference plasma/plasma.fbs), which
// costs a socket round-trip per create/get/seal. Here the store is a plain
// POSIX shm segment that every worker process on the node maps directly;
// operations take a process-shared robust mutex and touch the header table
// in-place. Zero RPCs, zero copies on the hot path — get() returns an
// offset into the same mapping the creator wrote through. Host RAM is the
// staging area for TPU HBM, so the store doubles as the iter_batches
// device-prefetch source.
//
// Layout:  [StoreHeader | slot table | data arena]
//   - slot table: open-addressed (linear probe) on the 28-byte ObjectID
//   - arena: first-fit free list with boundary-tag coalescing
//   - eviction: LRU over sealed refcount-0 objects (clock via header tick)
//   - crash safety: PTHREAD_MUTEX_ROBUST — a worker dying mid-section marks
//     the mutex inconsistent; the next locker repairs and continues.
//
// Built by ray_tpu/_cpp/build.py (g++ -O2 -shared), consumed via ctypes from
// ray_tpu/core/shm_store.py.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055534852ULL;  // "RTPUSHR"
constexpr int kKeySize = 28;
constexpr uint8_t kEmpty = 0;
constexpr uint8_t kCreated = 1;
constexpr uint8_t kSealed = 2;
constexpr uint8_t kTombstone = 3;  // slot freed; probe chains continue past

// Arena block header (boundary tags for O(1) coalescing).
struct BlockHeader {
  uint64_t size;       // payload size (bytes, 64-aligned)
  uint64_t prev_size;  // payload size of physically-previous block (0 = first)
  uint32_t free_;      // 1 if on free list
  uint32_t pad_;
  uint64_t next_free;  // offset of next free block (0 = end)
  uint64_t prev_free;  // offset of prev free block (0 = head)
};
constexpr uint64_t kBlockHdr = sizeof(BlockHeader);

struct Slot {
  uint8_t key[kKeySize];
  uint8_t state;
  uint8_t doomed;      // delete() hit a pinned object: dies at last release
  uint8_t pad[2];
  int32_t refcount;
  uint64_t offset;     // data offset within segment (to payload)
  uint64_t data_size;  // user-visible size
  uint64_t lru_tick;
};

struct StoreHeader {
  uint64_t magic;
  uint64_t segment_size;
  uint64_t n_slots;
  uint64_t slot_table_off;
  uint64_t arena_off;
  uint64_t arena_size;
  uint64_t used_bytes;
  uint64_t n_objects;
  uint64_t lru_clock;
  uint64_t free_head;  // offset of first free block (0 = none)
  uint64_t n_evictions;
  uint64_t create_waiters;
  // 1 (default): create may destructively evict LRU sealed objects.
  // 0: create fails with OOM instead — the client layer spills victims to
  // disk first (node-wide policy: the flag lives in the shared header).
  uint64_t auto_evict;
  pthread_mutex_t mutex;
  pthread_cond_t seal_cond;
};

struct Handle {
  uint8_t* base;
  uint64_t size;
  StoreHeader* hdr;
};

inline Slot* slot_table(Handle* h) {
  return reinterpret_cast<Slot*>(h->base + h->hdr->slot_table_off);
}

inline uint64_t align64(uint64_t n) { return (n + 63) & ~uint64_t(63); }

uint64_t fnv1a(const uint8_t* key) {
  uint64_t hsh = 1469598103934665603ULL;
  for (int i = 0; i < kKeySize; i++) {
    hsh ^= key[i];
    hsh *= 1099511628211ULL;
  }
  return hsh;
}

class Locker {
 public:
  explicit Locker(Handle* h) : h_(h) {
    int rc = pthread_mutex_lock(&h_->hdr->mutex);
    if (rc == EOWNERDEAD) {
      // Previous owner died inside a critical section. Repair: the header
      // table is always left structurally valid between individual field
      // writes (see ordering notes in create/seal), so consistent-mark is
      // safe.
      pthread_mutex_consistent(&h_->hdr->mutex);
    }
  }
  ~Locker() { pthread_mutex_unlock(&h_->hdr->mutex); }

 private:
  Handle* h_;
};

// -------- arena allocator (first-fit free list, boundary-tag coalesce) ----

inline BlockHeader* block_at(Handle* h, uint64_t payload_off) {
  return reinterpret_cast<BlockHeader*>(h->base + payload_off - kBlockHdr);
}

inline uint64_t next_payload_off(Handle* h, uint64_t payload_off) {
  BlockHeader* b = block_at(h, payload_off);
  uint64_t next = payload_off + b->size + kBlockHdr;
  if (next >= h->hdr->arena_off + h->hdr->arena_size) return 0;
  return next;
}

inline uint64_t prev_payload_off(Handle* h, uint64_t payload_off) {
  BlockHeader* b = block_at(h, payload_off);
  if (b->prev_size == 0 && payload_off == h->hdr->arena_off + kBlockHdr)
    return 0;
  return payload_off - kBlockHdr - b->prev_size;
}

void freelist_remove(Handle* h, uint64_t off) {
  BlockHeader* b = block_at(h, off);
  if (b->prev_free)
    block_at(h, b->prev_free)->next_free = b->next_free;
  else
    h->hdr->free_head = b->next_free;
  if (b->next_free) block_at(h, b->next_free)->prev_free = b->prev_free;
  b->next_free = b->prev_free = 0;
  b->free_ = 0;
}

void freelist_push(Handle* h, uint64_t off) {
  BlockHeader* b = block_at(h, off);
  b->free_ = 1;
  b->prev_free = 0;
  b->next_free = h->hdr->free_head;
  if (h->hdr->free_head) block_at(h, h->hdr->free_head)->prev_free = off;
  h->hdr->free_head = off;
}

// Split block at `off` so its payload is exactly `want` (aligned); push
// remainder to the free list.
void split_block(Handle* h, uint64_t off, uint64_t want) {
  BlockHeader* b = block_at(h, off);
  uint64_t spare = b->size - want;
  if (spare < kBlockHdr + 64) return;  // too small to split
  uint64_t rem_off = off + want + kBlockHdr;
  BlockHeader* rem = block_at(h, rem_off);
  rem->size = spare - kBlockHdr;
  rem->prev_size = want;
  rem->free_ = 0;
  rem->next_free = rem->prev_free = 0;
  b->size = want;
  uint64_t after = next_payload_off(h, rem_off);
  if (after) block_at(h, after)->prev_size = rem->size;
  freelist_push(h, rem_off);
}

// Returns payload offset or 0.
uint64_t arena_alloc(Handle* h, uint64_t want) {
  want = align64(want ? want : 1);
  uint64_t off = h->hdr->free_head;
  while (off) {
    BlockHeader* b = block_at(h, off);
    if (b->size >= want) {
      freelist_remove(h, off);
      split_block(h, off, want);
      h->hdr->used_bytes += block_at(h, off)->size + kBlockHdr;
      return off;
    }
    off = b->next_free;
  }
  return 0;
}

void arena_free(Handle* h, uint64_t off) {
  BlockHeader* b = block_at(h, off);
  h->hdr->used_bytes -= b->size + kBlockHdr;
  // Coalesce with next.
  uint64_t next = next_payload_off(h, off);
  if (next && block_at(h, next)->free_) {
    freelist_remove(h, next);
    b->size += block_at(h, next)->size + kBlockHdr;
    uint64_t after = next_payload_off(h, off);
    if (after) block_at(h, after)->prev_size = b->size;
  }
  // Coalesce with prev.
  uint64_t prev = prev_payload_off(h, off);
  if (prev && block_at(h, prev)->free_) {
    BlockHeader* pb = block_at(h, prev);
    freelist_remove(h, prev);
    pb->size += b->size + kBlockHdr;
    uint64_t after = next_payload_off(h, prev);
    if (after) block_at(h, after)->prev_size = pb->size;
    off = prev;
  }
  freelist_push(h, off);
}

// -------- slot table ------------------------------------------------------

Slot* find_slot(Handle* h, const uint8_t* key) {
  Slot* table = slot_table(h);
  uint64_t n = h->hdr->n_slots;
  uint64_t i = fnv1a(key) % n;
  for (uint64_t probes = 0; probes < n; probes++) {
    Slot* s = &table[i];
    if (s->state == kEmpty) return nullptr;
    if (s->state != kTombstone && memcmp(s->key, key, kKeySize) == 0) return s;
    i = (i + 1) % n;
  }
  return nullptr;
}

Slot* find_insert_slot(Handle* h, const uint8_t* key) {
  Slot* table = slot_table(h);
  uint64_t n = h->hdr->n_slots;
  uint64_t i = fnv1a(key) % n;
  Slot* first_tomb = nullptr;
  for (uint64_t probes = 0; probes < n; probes++) {
    Slot* s = &table[i];
    if (s->state == kEmpty) return first_tomb ? first_tomb : s;
    if (s->state == kTombstone) {
      if (!first_tomb) first_tomb = s;
    } else if (memcmp(s->key, key, kKeySize) == 0) {
      return nullptr;  // exists
    }
    i = (i + 1) % n;
  }
  return first_tomb;  // table full of live+tombstones; may still reuse tomb
}

// Evict LRU sealed refcount-0 objects until at least `need` bytes could be
// allocated (or nothing evictable remains). Returns 1 if anything evicted.
int evict_for(Handle* h, uint64_t need) {
  int evicted_any = 0;
  for (;;) {
    // Find LRU candidate.
    Slot* table = slot_table(h);
    Slot* lru = nullptr;
    for (uint64_t i = 0; i < h->hdr->n_slots; i++) {
      Slot* s = &table[i];
      if (s->state == kSealed && s->refcount == 0) {
        if (!lru || s->lru_tick < lru->lru_tick) lru = s;
      }
    }
    if (!lru) return evicted_any;
    arena_free(h, lru->offset);
    lru->state = kTombstone;
    h->hdr->n_objects--;
    h->hdr->n_evictions++;
    evicted_any = 1;
    // Enough contiguous room now?
    uint64_t off = arena_alloc(h, need);
    if (off) {
      arena_free(h, off);
      return 1;
    }
  }
}

}  // namespace

extern "C" {

// Create + initialize a store segment. Fails if it already exists unless
// unlink_existing. Returns handle or null.
void* rtpu_store_create(const char* name, uint64_t segment_size,
                        uint64_t n_slots, int unlink_existing, int populate) {
  if (unlink_existing) shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)segment_size) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  // Optional MAP_POPULATE prefaults the segment at creation so first-touch
  // page faults never throttle the put path (cold: ~0.05 GB/s, prefaulted:
  // memcpy-bound ~4 GB/s) — but costs seconds/GB up front, so the Python
  // side defaults to a background prefault thread instead.
  int flags = MAP_SHARED | (populate ? MAP_POPULATE : 0);
  void* base =
      mmap(nullptr, segment_size, PROT_READ | PROT_WRITE, flags, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;

  auto* hdr = reinterpret_cast<StoreHeader*>(base);
  memset(hdr, 0, sizeof(StoreHeader));
  hdr->segment_size = segment_size;
  hdr->n_slots = n_slots;
  hdr->slot_table_off = align64(sizeof(StoreHeader));
  uint64_t table_bytes = align64(n_slots * sizeof(Slot));
  hdr->arena_off = hdr->slot_table_off + table_bytes;
  hdr->arena_size = segment_size - hdr->arena_off;
  memset(reinterpret_cast<uint8_t*>(base) + hdr->slot_table_off, 0,
         table_bytes);
  hdr->auto_evict = 1;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mutex, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
  pthread_cond_init(&hdr->seal_cond, &ca);

  auto* h = new Handle{reinterpret_cast<uint8_t*>(base), segment_size, hdr};
  // One giant free block spanning the arena.
  uint64_t first = hdr->arena_off + kBlockHdr;
  BlockHeader* b = block_at(h, first);
  b->size = hdr->arena_size - kBlockHdr;
  b->prev_size = 0;
  b->free_ = 0;
  b->next_free = b->prev_free = 0;
  freelist_push(h, first);
  hdr->magic = kMagic;  // last: marks init complete for openers
  return h;
}

void* rtpu_store_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  auto* hdr = reinterpret_cast<StoreHeader*>(base);
  if (hdr->magic != kMagic) {
    munmap(base, st.st_size);
    return nullptr;
  }
  return new Handle{reinterpret_cast<uint8_t*>(base), (uint64_t)st.st_size,
                    hdr};
}

void rtpu_store_close(void* hp) {
  auto* h = reinterpret_cast<Handle*>(hp);
  munmap(h->base, h->size);
  delete h;
}

void rtpu_store_unlink(const char* name) { shm_unlink(name); }

// Node-wide eviction policy switch (lives in the shared header so every
// mapping process obeys it). 0 = fail-with-OOM so the client layer can
// spill to disk instead of destroying data.
void rtpu_store_set_auto_evict(void* hp, int on) {
  auto* h = reinterpret_cast<Handle*>(hp);
  Locker lock(h);
  h->hdr->auto_evict = on ? 1 : 0;
}

// Select LRU sealed refcount-0 victims whose sizes sum to >= need (or until
// none remain / max_keys reached). Copies their keys into keys_out
// (kKeySize bytes each) WITHOUT removing them — the caller reads each out
// to disk, then deletes it. Returns the number of keys written.
int rtpu_store_spill_victims(void* hp, uint64_t need, uint8_t* keys_out,
                             int max_keys) {
  auto* h = reinterpret_cast<Handle*>(hp);
  Locker lock(h);
  if (max_keys > 256) max_keys = 256;
  uint64_t chosen[256];
  int count = 0;
  uint64_t acc = 0;
  Slot* table = slot_table(h);
  while (count < max_keys && acc < need) {
    Slot* best = nullptr;
    uint64_t best_i = 0;
    for (uint64_t i = 0; i < h->hdr->n_slots; i++) {
      Slot* s = &table[i];
      if (s->state != kSealed || s->refcount != 0) continue;
      bool taken = false;
      for (int j = 0; j < count; j++) {
        if (chosen[j] == i) { taken = true; break; }
      }
      if (taken) continue;
      if (!best || s->lru_tick < best->lru_tick) { best = s; best_i = i; }
    }
    if (!best) break;
    chosen[count] = best_i;
    memcpy(keys_out + (uint64_t)count * kKeySize, best->key, kKeySize);
    acc += best->data_size;
    count++;
  }
  return count;
}

uint8_t* rtpu_store_base(void* hp) {
  return reinterpret_cast<Handle*>(hp)->base;
}

// Reserve space for an object. Returns payload offset, or 0 on:
//   errno_out = 1 (already exists), 2 (out of memory even after eviction),
//               3 (slot table full).
uint64_t rtpu_obj_create(void* hp, const uint8_t* key, uint64_t data_size,
                         int* errno_out) {
  auto* h = reinterpret_cast<Handle*>(hp);
  Locker lock(h);
  *errno_out = 0;
  if (find_slot(h, key)) {
    *errno_out = 1;
    return 0;
  }
  uint64_t off = arena_alloc(h, data_size);
  if (!off) {
    if (h->hdr->auto_evict) {
      evict_for(h, align64(data_size ? data_size : 1));
      off = arena_alloc(h, data_size);
    }
    if (!off) {
      *errno_out = 2;
      return 0;
    }
  }
  Slot* s = find_insert_slot(h, key);
  if (!s) {
    arena_free(h, off);
    *errno_out = 3;
    return 0;
  }
  memcpy(s->key, key, kKeySize);
  s->refcount = 0;
  s->offset = off;
  s->data_size = data_size;
  s->lru_tick = ++h->hdr->lru_clock;
  s->state = kCreated;  // last: slot visible only when fully written
  h->hdr->n_objects++;
  return off;
}

int rtpu_obj_seal(void* hp, const uint8_t* key) {
  auto* h = reinterpret_cast<Handle*>(hp);
  Locker lock(h);
  Slot* s = find_slot(h, key);
  if (!s || s->state != kCreated) return -1;
  s->state = kSealed;
  pthread_cond_broadcast(&h->hdr->seal_cond);
  return 0;
}

// Blocking get: waits up to timeout_ms (-1 = forever, 0 = nonblocking) for
// the object to be sealed. On success pins (refcount++) and fills
// offset/size. Returns 0 ok, -1 timeout/missing.
int rtpu_obj_get(void* hp, const uint8_t* key, int64_t timeout_ms,
                 uint64_t* offset, uint64_t* size) {
  auto* h = reinterpret_cast<Handle*>(hp);
  Locker lock(h);
  struct timespec deadline;
  if (timeout_ms > 0) {
    clock_gettime(CLOCK_MONOTONIC, &deadline);
    deadline.tv_sec += timeout_ms / 1000;
    deadline.tv_nsec += (timeout_ms % 1000) * 1000000L;
    if (deadline.tv_nsec >= 1000000000L) {
      deadline.tv_sec++;
      deadline.tv_nsec -= 1000000000L;
    }
  }
  for (;;) {
    Slot* s = find_slot(h, key);
    if (s && s->state == kSealed && !s->doomed) {
      s->refcount++;
      s->lru_tick = ++h->hdr->lru_clock;
      *offset = s->offset;
      *size = s->data_size;
      return 0;
    }
    if (timeout_ms == 0) return -1;
    int rc;
    if (timeout_ms < 0) {
      rc = pthread_cond_wait(&h->hdr->seal_cond, &h->hdr->mutex);
    } else {
      rc = pthread_cond_timedwait(&h->hdr->seal_cond, &h->hdr->mutex,
                                  &deadline);
    }
    if (rc == ETIMEDOUT) return -1;
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&h->hdr->mutex);
  }
}

// Returns 0 on plain release, 2 when this was the LAST pin of a doomed
// object (now freed) — the caller must treat the object as deleted.
int rtpu_obj_release(void* hp, const uint8_t* key) {
  auto* h = reinterpret_cast<Handle*>(hp);
  Locker lock(h);
  Slot* s = find_slot(h, key);
  if (!s || s->refcount <= 0) return -1;
  s->refcount--;
  if (s->refcount == 0 && s->doomed) {
    arena_free(h, s->offset);
    s->state = kTombstone;
    s->doomed = 0;
    h->hdr->n_objects--;
    return 2;
  }
  return 0;
}

// Delete: free immediately if unpinned; pinned objects are freed on the
// last release... by design we simply refuse (caller retries/abandons —
// the distributed refcounter only deletes when it believes refs are gone).
// Delete semantics with pins outstanding: the object is DOOMED — it reads
// as absent immediately (get/contains miss it) and its memory is freed by
// the LAST release. This closes the spill/consume race: a concurrent
// spiller's pin cannot make a consumer's delete silently fail (the
// spiller's release returns 2 so it can discard the spill file it wrote).
int rtpu_obj_delete(void* hp, const uint8_t* key) {
  auto* h = reinterpret_cast<Handle*>(hp);
  Locker lock(h);
  Slot* s = find_slot(h, key);
  if (!s) return -1;
  if (s->refcount > 0) {
    s->doomed = 1;
    return 0;
  }
  arena_free(h, s->offset);
  s->state = kTombstone;
  s->doomed = 0;
  h->hdr->n_objects--;
  return 0;
}

int rtpu_obj_contains(void* hp, const uint8_t* key) {
  auto* h = reinterpret_cast<Handle*>(hp);
  Locker lock(h);
  Slot* s = find_slot(h, key);
  return (s && s->state == kSealed && !s->doomed) ? 1 : 0;
}

// Abort an in-progress create (creator failed before seal).
int rtpu_obj_abort(void* hp, const uint8_t* key) {
  auto* h = reinterpret_cast<Handle*>(hp);
  Locker lock(h);
  Slot* s = find_slot(h, key);
  if (!s || s->state != kCreated) return -1;
  arena_free(h, s->offset);
  s->state = kTombstone;
  h->hdr->n_objects--;
  return 0;
}

uint64_t rtpu_store_size(void* hp) {
  return reinterpret_cast<Handle*>(hp)->size;
}

// Fault the whole segment in without touching contents (safe concurrently
// with writers — pages are populated, not modified). Called from a
// background thread by the creator so puts never pay first-touch faults.
int rtpu_store_prefault(void* hp) {
#ifdef MADV_POPULATE_WRITE
  auto* h = reinterpret_cast<Handle*>(hp);
  return madvise(h->base, h->size, MADV_POPULATE_WRITE);
#else
  return -1;
#endif
}

void rtpu_store_stats(void* hp, uint64_t* used, uint64_t* capacity,
                      uint64_t* n_objects, uint64_t* n_evictions) {
  auto* h = reinterpret_cast<Handle*>(hp);
  Locker lock(h);
  *used = h->hdr->used_bytes;
  *capacity = h->hdr->arena_size;
  *n_objects = h->hdr->n_objects;
  *n_evictions = h->hdr->n_evictions;
}

}  // extern "C"
