// rtpu shm object store: the per-node object plane (plasma-equivalent).
//
// Design parity with the reference's plasma store
// (reference: src/ray/object_manager/plasma/store.h:55,
//  object_lifecycle_manager.h:101, eviction_policy.h:105), re-architected
// for the TPU era instead of ported: plasma is a *server process* speaking a
// flatbuffer protocol over a unix socket (reference plasma/plasma.fbs), which
// costs a socket round-trip per create/get/seal. Here the store is a plain
// POSIX shm segment that every worker process on the node maps directly;
// operations take a process-shared robust mutex and touch the header table
// in-place. Zero RPCs, zero copies on the hot path — get() returns an
// offset into the same mapping the creator wrote through. Host RAM is the
// staging area for TPU HBM, so the store doubles as the iter_batches
// device-prefetch source.
//
// Layout v2 — SHARDED for multi-writer scaling: the single arena + one
// process-shared mutex serialized every concurrent create/seal/get/release
// (aggregate put bandwidth *fell* when writers were added). Now:
//
//   [StoreHeader | ShardHeader[n_shards] | slot stripes | sub-arenas]
//
//   - an object's *home shard* is fnv1a(key) % n_shards: its slot lives in
//     that shard's stripe, so lookups (create-exists, get, seal, release,
//     delete, contains) take exactly ONE shard mutex.
//   - each shard owns a sub-arena with its own first-fit free list
//     (boundary-tag coalescing). create() allocates from the home shard's
//     arena and FALLS THROUGH to the other shards when it is full; the
//     slot records arena_shard so frees return the block to its owner.
//   - no operation ever holds two shard mutexes: create inserts a PENDING
//     placeholder slot (excludes duplicate creates), allocates under the
//     arena-owner's lock only, then fills the slot under the home lock.
//     Frees capture (offset, arena_shard) under the home lock, tombstone,
//     and free under the arena-owner's lock afterwards.
//   - eviction stays globally-LRU-correct across shards: the LRU clock is
//     a lock-free atomic in the store header, and evict scans every stripe
//     (one lock at a time) for the oldest sealed refcount-0 object whose
//     block lives in the pressured shard.
//   - crash safety: PTHREAD_MUTEX_ROBUST per shard — a worker dying
//     mid-section marks that shard's mutex inconsistent; the next locker
//     repairs and continues. The two-phase ops narrow the v1 guarantee:
//     a process dying BETWEEN a free's tombstone section and its
//     arena_free section leaks that one block until the store is
//     recreated (the offset lived only in the dead process), and one
//     dying between create's placeholder and fill leaves a PENDING slot
//     that rtpu_obj_reclaim_pending (driven by the Python put path's
//     takeover timer) clears. Both windows are microseconds of C code
//     with no syscalls besides the mutexes.
//   - kLayoutVersion is stamped into the mapped header and exported from
//     the library (rtpu_lib_layout_version) so a stale prebuilt .so — or a
//     stale RTPU_SHM_STORE_SO override — fails fast at attach instead of
//     silently corrupting the arena. Rebuild: python ray_tpu/_cpp/build.py
//   - spill_files: lock-free counter of live spill files for this store;
//     the Python layer checks it before paying unlink/stat syscalls on the
//     (overwhelmingly common) spill-less delete path.
//
// Built by ray_tpu/_cpp/build.py (g++ -O2 -shared), consumed via ctypes from
// ray_tpu/core/shm_store.py.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x325253485550'5452ULL;  // layout-v2 magic
constexpr uint64_t kLayoutVersion = 2;
constexpr int kKeySize = 28;
constexpr uint8_t kEmpty = 0;
constexpr uint8_t kCreated = 1;
constexpr uint8_t kSealed = 2;
constexpr uint8_t kTombstone = 3;  // slot freed; probe chains continue past
constexpr uint8_t kPendingShard = 0xff;  // create() allocation in flight

// Arena block header (boundary tags for O(1) coalescing).
struct BlockHeader {
  uint64_t size;       // payload size (bytes, 64-aligned)
  uint64_t prev_size;  // payload size of physically-previous block (0 = first)
  uint32_t free_;      // 1 if on free list
  uint32_t pad_;
  uint64_t next_free;  // offset of next free block (0 = end)
  uint64_t prev_free;  // offset of prev free block (0 = head)
};
constexpr uint64_t kBlockHdr = sizeof(BlockHeader);

struct Slot {
  uint8_t key[kKeySize];
  uint8_t state;
  uint8_t doomed;       // delete() hit a pinned object: dies at last release
  uint8_t arena_shard;  // which shard's sub-arena holds the payload
  uint8_t pad;
  int32_t refcount;
  uint64_t offset;     // data offset within segment (to payload)
  uint64_t data_size;  // user-visible size
  uint64_t lru_tick;
};

struct ShardHeader {
  pthread_mutex_t mutex;   // guards this shard's slot stripe + sub-arena
  pthread_cond_t seal_cond;
  uint64_t slot_off;       // absolute offset of this shard's slot stripe
  uint64_t n_slots;
  uint64_t arena_off;      // absolute offset of this shard's sub-arena
  uint64_t arena_size;
  uint64_t used_bytes;
  uint64_t free_head;      // absolute payload offset of first free block
  uint64_t n_objects;      // live objects whose HOME is this shard
  uint64_t n_evictions;
};

struct StoreHeader {
  uint64_t magic;
  uint64_t layout_version;
  uint64_t segment_size;
  uint64_t n_shards;
  uint64_t n_slots_total;
  uint64_t lru_clock;    // global LRU clock, advanced with atomics
  uint64_t auto_evict;   // 1 (default): create may destructively evict LRU
                         // sealed objects. 0: create fails with OOM and the
                         // client layer spills victims to disk first.
  uint64_t spill_files;  // live spill files for this store (atomic, approx)
  uint64_t shards_off;   // absolute offset of the ShardHeader array
};

struct Handle {
  uint8_t* base;
  uint64_t size;
  StoreHeader* hdr;
  ShardHeader* shards;
};

inline ShardHeader* shard(Handle* h, uint64_t i) { return &h->shards[i]; }

inline Slot* stripe(Handle* h, ShardHeader* sh) {
  return reinterpret_cast<Slot*>(h->base + sh->slot_off);
}

inline uint64_t align64(uint64_t n) { return (n + 63) & ~uint64_t(63); }

uint64_t fnv1a(const uint8_t* key) {
  uint64_t hsh = 1469598103934665603ULL;
  for (int i = 0; i < kKeySize; i++) {
    hsh ^= key[i];
    hsh *= 1099511628211ULL;
  }
  return hsh;
}

inline uint64_t home_of(Handle* h, const uint8_t* key) {
  // Mix the top bits in: the low bits also pick the probe start inside the
  // stripe, and reusing the same bits for both would cluster probes.
  uint64_t hsh = fnv1a(key);
  return (hsh >> 32) % h->hdr->n_shards;
}

inline uint64_t clock_tick(Handle* h) {
  return __atomic_add_fetch(&h->hdr->lru_clock, 1, __ATOMIC_RELAXED);
}

class Locker {
 public:
  explicit Locker(ShardHeader* sh) : sh_(sh) {
    int rc = pthread_mutex_lock(&sh_->mutex);
    if (rc == EOWNERDEAD) {
      // Previous owner died inside a critical section. Repair: the header
      // table is always left structurally valid between individual field
      // writes (see ordering notes in create/seal), so consistent-mark is
      // safe.
      pthread_mutex_consistent(&sh_->mutex);
    }
  }
  ~Locker() { pthread_mutex_unlock(&sh_->mutex); }

 private:
  ShardHeader* sh_;
};

// -------- arena allocator (per-shard first-fit free list, boundary-tag
// coalesce; caller holds the owning shard's mutex) ------------------------

inline BlockHeader* block_at(Handle* h, uint64_t payload_off) {
  return reinterpret_cast<BlockHeader*>(h->base + payload_off - kBlockHdr);
}

inline uint64_t next_payload_off(Handle* h, ShardHeader* sh,
                                 uint64_t payload_off) {
  BlockHeader* b = block_at(h, payload_off);
  uint64_t next = payload_off + b->size + kBlockHdr;
  if (next >= sh->arena_off + sh->arena_size) return 0;
  return next;
}

inline uint64_t prev_payload_off(Handle* h, ShardHeader* sh,
                                 uint64_t payload_off) {
  BlockHeader* b = block_at(h, payload_off);
  if (b->prev_size == 0 && payload_off == sh->arena_off + kBlockHdr)
    return 0;
  return payload_off - kBlockHdr - b->prev_size;
}

void freelist_remove(Handle* h, ShardHeader* sh, uint64_t off) {
  BlockHeader* b = block_at(h, off);
  if (b->prev_free)
    block_at(h, b->prev_free)->next_free = b->next_free;
  else
    sh->free_head = b->next_free;
  if (b->next_free) block_at(h, b->next_free)->prev_free = b->prev_free;
  b->next_free = b->prev_free = 0;
  b->free_ = 0;
}

void freelist_push(Handle* h, ShardHeader* sh, uint64_t off) {
  BlockHeader* b = block_at(h, off);
  b->free_ = 1;
  b->prev_free = 0;
  b->next_free = sh->free_head;
  if (sh->free_head) block_at(h, sh->free_head)->prev_free = off;
  sh->free_head = off;
}

// Split block at `off` so its payload is exactly `want` (aligned); push
// remainder to the free list.
void split_block(Handle* h, ShardHeader* sh, uint64_t off, uint64_t want) {
  BlockHeader* b = block_at(h, off);
  uint64_t spare = b->size - want;
  if (spare < kBlockHdr + 64) return;  // too small to split
  uint64_t rem_off = off + want + kBlockHdr;
  BlockHeader* rem = block_at(h, rem_off);
  rem->size = spare - kBlockHdr;
  rem->prev_size = want;
  rem->free_ = 0;
  rem->next_free = rem->prev_free = 0;
  b->size = want;
  uint64_t after = next_payload_off(h, sh, rem_off);
  if (after) block_at(h, after)->prev_size = rem->size;
  freelist_push(h, sh, rem_off);
}

// Returns payload offset or 0.
uint64_t arena_alloc(Handle* h, ShardHeader* sh, uint64_t want) {
  want = align64(want ? want : 1);
  uint64_t off = sh->free_head;
  while (off) {
    BlockHeader* b = block_at(h, off);
    if (b->size >= want) {
      freelist_remove(h, sh, off);
      split_block(h, sh, off, want);
      sh->used_bytes += block_at(h, off)->size + kBlockHdr;
      return off;
    }
    off = b->next_free;
  }
  return 0;
}

void arena_free(Handle* h, ShardHeader* sh, uint64_t off) {
  BlockHeader* b = block_at(h, off);
  sh->used_bytes -= b->size + kBlockHdr;
  // Coalesce with next.
  uint64_t next = next_payload_off(h, sh, off);
  if (next && block_at(h, next)->free_) {
    freelist_remove(h, sh, next);
    b->size += block_at(h, next)->size + kBlockHdr;
    uint64_t after = next_payload_off(h, sh, off);
    if (after) block_at(h, after)->prev_size = b->size;
  }
  // Coalesce with prev.
  uint64_t prev = prev_payload_off(h, sh, off);
  if (prev && block_at(h, prev)->free_) {
    BlockHeader* pb = block_at(h, prev);
    freelist_remove(h, sh, prev);
    pb->size += b->size + kBlockHdr;
    uint64_t after = next_payload_off(h, sh, prev);
    if (after) block_at(h, after)->prev_size = pb->size;
    off = prev;
  }
  freelist_push(h, sh, off);
}

// Free a payload block owned by shard `si`, taking that shard's lock.
void free_block_in(Handle* h, uint64_t si, uint64_t off) {
  ShardHeader* as = shard(h, si);
  Locker lock(as);
  arena_free(h, as, off);
}

// -------- slot stripes (caller holds the stripe's shard mutex) -----------

Slot* find_slot_in(Handle* h, ShardHeader* sh, const uint8_t* key) {
  Slot* table = stripe(h, sh);
  uint64_t n = sh->n_slots;
  uint64_t i = fnv1a(key) % n;
  for (uint64_t probes = 0; probes < n; probes++) {
    Slot* s = &table[i];
    if (s->state == kEmpty) return nullptr;
    if (s->state != kTombstone && memcmp(s->key, key, kKeySize) == 0) return s;
    i = (i + 1) % n;
  }
  return nullptr;
}

Slot* find_insert_slot_in(Handle* h, ShardHeader* sh, const uint8_t* key) {
  Slot* table = stripe(h, sh);
  uint64_t n = sh->n_slots;
  uint64_t i = fnv1a(key) % n;
  Slot* first_tomb = nullptr;
  for (uint64_t probes = 0; probes < n; probes++) {
    Slot* s = &table[i];
    if (s->state == kEmpty) return first_tomb ? first_tomb : s;
    if (s->state == kTombstone) {
      if (!first_tomb) first_tomb = s;
    } else if (memcmp(s->key, key, kKeySize) == 0) {
      return nullptr;  // exists
    }
    i = (i + 1) % n;
  }
  return first_tomb;  // table full of live+tombstones; may still reuse tomb
}

// Evict globally-LRU sealed refcount-0 objects whose payload lives in shard
// `target` until at least `need` contiguous bytes could be allocated there
// (or nothing evictable remains). Never holds two locks: each scan round
// takes one stripe lock at a time, then re-verifies the victim under its
// home lock before tombstoning. Returns 1 if enough room was made.
int evict_in_shard(Handle* h, uint64_t target, uint64_t need) {
  uint64_t n = h->hdr->n_shards;
  for (;;) {
    uint8_t vkey[kKeySize];
    uint64_t vtick = 0;
    int found = 0;
    for (uint64_t si = 0; si < n; si++) {
      ShardHeader* sh = shard(h, si);
      Locker lock(sh);
      Slot* table = stripe(h, sh);
      for (uint64_t i = 0; i < sh->n_slots; i++) {
        Slot* s = &table[i];
        if (s->state != kSealed || s->refcount != 0 || s->doomed ||
            s->arena_shard != target)
          continue;
        if (!found || s->lru_tick < vtick) {
          memcpy(vkey, s->key, kKeySize);
          vtick = s->lru_tick;
          found = 1;
        }
      }
    }
    if (!found) return 0;
    // Delete the victim (it may have been pinned/removed since the scan).
    uint64_t home = home_of(h, vkey);
    ShardHeader* hs = shard(h, home);
    uint64_t free_off = 0;
    {
      Locker lock(hs);
      Slot* s = find_slot_in(h, hs, vkey);
      if (s && s->state == kSealed && s->refcount == 0 && !s->doomed &&
          s->arena_shard == target && s->lru_tick == vtick) {
        free_off = s->offset;
        s->state = kTombstone;
        hs->n_objects--;
        hs->n_evictions++;
      }
    }
    ShardHeader* as = shard(h, target);
    {
      Locker lock(as);
      if (free_off) arena_free(h, as, free_off);
      // Enough contiguous room now?
      uint64_t off = arena_alloc(h, as, need);
      if (off) {
        arena_free(h, as, off);
        return 1;
      }
    }
  }
}

}  // namespace

extern "C" {

// Compile-time layout version of THIS library build; the Python client
// refuses to run against a library whose version it does not expect.
uint64_t rtpu_lib_layout_version() { return kLayoutVersion; }

// Layout version stamped into a mapped segment's header.
uint64_t rtpu_store_layout_version(void* hp) {
  return reinterpret_cast<Handle*>(hp)->hdr->layout_version;
}

uint64_t rtpu_store_n_shards(void* hp) {
  return reinterpret_cast<Handle*>(hp)->hdr->n_shards;
}

// Largest single allocation any sub-arena could ever satisfy (an object
// cannot span sub-arenas) — the client fails oversized creates fast with
// a clear error instead of spinning through futile spill/evict laps.
uint64_t rtpu_store_max_object_bytes(void* hp) {
  auto* h = reinterpret_cast<Handle*>(hp);
  uint64_t arena = shard(h, 0)->arena_size;
  return arena > 2 * kBlockHdr ? arena - 2 * kBlockHdr : 0;
}

// Create + initialize a store segment. Fails if it already exists unless
// unlink_existing. Returns handle or null.
void* rtpu_store_create(const char* name, uint64_t segment_size,
                        uint64_t n_slots, uint64_t n_shards,
                        int unlink_existing, int populate) {
  if (unlink_existing) shm_unlink(name);
  if (n_shards < 1) n_shards = 1;
  if (n_shards > 64) n_shards = 64;
  if (n_slots < n_shards * 8) n_slots = n_shards * 8;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)segment_size) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  // Optional MAP_POPULATE prefaults the segment at creation so first-touch
  // page faults never throttle the put path (cold: ~0.05 GB/s, prefaulted:
  // memcpy-bound ~4 GB/s) — but costs seconds/GB up front, so the Python
  // side defaults to a background prefault thread instead.
  int flags = MAP_SHARED | (populate ? MAP_POPULATE : 0);
  void* base =
      mmap(nullptr, segment_size, PROT_READ | PROT_WRITE, flags, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;

  auto* hdr = reinterpret_cast<StoreHeader*>(base);
  memset(hdr, 0, sizeof(StoreHeader));
  hdr->segment_size = segment_size;
  hdr->layout_version = kLayoutVersion;

  // Shrink the shard count until every sub-arena is usefully large: a
  // single object can never span sub-arenas, so small (test) stores
  // collapse to fewer shards rather than making every big object
  // unallocatable. 64 MB minimum keeps the default 2 GB store at 8 shards
  // while a 64 MB store stays monolithic.
  constexpr uint64_t kMinSubArena = 64ULL << 20;
  uint64_t shards_off = align64(sizeof(StoreHeader));
  uint64_t n, slots_per, stripe_bytes, arena_off, per_arena;
  for (n = n_shards;; n /= 2) {
    uint64_t shard_hdr_bytes = align64(n * sizeof(ShardHeader));
    slots_per = (n_slots + n - 1) / n;
    stripe_bytes = align64(slots_per * sizeof(Slot));
    arena_off = shards_off + shard_hdr_bytes + n * stripe_bytes;
    if (arena_off >= segment_size) {
      if (n == 1) {
        munmap(base, segment_size);
        shm_unlink(name);
        return nullptr;  // segment cannot even hold the tables
      }
      continue;
    }
    per_arena = ((segment_size - arena_off) / n) & ~uint64_t(63);
    if (per_arena >= kMinSubArena || n == 1) break;
  }
  if (per_arena <= kBlockHdr + 64) {
    munmap(base, segment_size);
    shm_unlink(name);
    return nullptr;
  }
  hdr->n_shards = n;
  hdr->n_slots_total = slots_per * n;
  hdr->auto_evict = 1;
  hdr->shards_off = shards_off;

  auto* shards = reinterpret_cast<ShardHeader*>(
      reinterpret_cast<uint8_t*>(base) + shards_off);
  auto* h = new Handle{reinterpret_cast<uint8_t*>(base), segment_size, hdr,
                       shards};

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);

  uint64_t shard_hdr_bytes = align64(n * sizeof(ShardHeader));
  uint64_t slot_base = shards_off + shard_hdr_bytes;
  memset(reinterpret_cast<uint8_t*>(base) + slot_base, 0, n * stripe_bytes);
  for (uint64_t i = 0; i < n; i++) {
    ShardHeader* sh = &shards[i];
    memset(reinterpret_cast<void*>(sh), 0, sizeof(ShardHeader));
    sh->slot_off = slot_base + i * stripe_bytes;
    sh->n_slots = slots_per;
    sh->arena_off = arena_off + i * per_arena;
    sh->arena_size = per_arena;
    pthread_mutex_init(&sh->mutex, &ma);
    pthread_cond_init(&sh->seal_cond, &ca);
    // One giant free block spanning this shard's sub-arena.
    uint64_t first = sh->arena_off + kBlockHdr;
    BlockHeader* b = block_at(h, first);
    b->size = sh->arena_size - kBlockHdr;
    b->prev_size = 0;
    b->free_ = 0;
    b->next_free = b->prev_free = 0;
    freelist_push(h, sh, first);
  }
  hdr->magic = kMagic;  // last: marks init complete for openers
  return h;
}

void* rtpu_store_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  auto* hdr = reinterpret_cast<StoreHeader*>(base);
  if (hdr->magic != kMagic || hdr->layout_version != kLayoutVersion) {
    munmap(base, st.st_size);
    return nullptr;
  }
  auto* shards = reinterpret_cast<ShardHeader*>(
      reinterpret_cast<uint8_t*>(base) + hdr->shards_off);
  return new Handle{reinterpret_cast<uint8_t*>(base), (uint64_t)st.st_size,
                    hdr, shards};
}

void rtpu_store_close(void* hp) {
  auto* h = reinterpret_cast<Handle*>(hp);
  munmap(h->base, h->size);
  delete h;
}

void rtpu_store_unlink(const char* name) { shm_unlink(name); }

// Node-wide eviction policy switch (lives in the shared header so every
// mapping process obeys it). 0 = fail-with-OOM so the client layer can
// spill to disk instead of destroying data.
void rtpu_store_set_auto_evict(void* hp, int on) {
  auto* h = reinterpret_cast<Handle*>(hp);
  __atomic_store_n(&h->hdr->auto_evict, on ? 1 : 0, __ATOMIC_RELAXED);
}

// Live spill-file accounting (approximate, lock-free): the Python layer
// bumps it when a spill file is written and decrements on unlink, then
// skips the per-delete unlink/stat syscalls entirely while it reads 0 —
// those syscalls were ~400us each on overlayfs and dominated put/delete.
void rtpu_store_spill_note(void* hp, int64_t delta) {
  auto* h = reinterpret_cast<Handle*>(hp);
  __atomic_add_fetch(&h->hdr->spill_files, (uint64_t)delta, __ATOMIC_RELAXED);
}

int64_t rtpu_store_spill_count(void* hp) {
  auto* h = reinterpret_cast<Handle*>(hp);
  return (int64_t)__atomic_load_n(&h->hdr->spill_files, __ATOMIC_RELAXED);
}

// Select LRU sealed refcount-0 victims whose sizes sum to >= need (or until
// none remain / max_keys reached). Copies their keys into keys_out
// (kKeySize bytes each) WITHOUT removing them — the caller reads each out
// to disk, then deletes it. Returns the number of keys written. Victims are
// chosen across ALL shards by the global LRU clock.
int rtpu_store_spill_victims(void* hp, uint64_t need, uint8_t* keys_out,
                             int max_keys) {
  auto* h = reinterpret_cast<Handle*>(hp);
  if (max_keys > 256) max_keys = 256;
  uint64_t chosen[256];  // global slot index = shard * stride + i
  int count = 0;
  uint64_t acc = 0;
  uint64_t n = h->hdr->n_shards;
  uint64_t stride = shard(h, 0)->n_slots;
  while (count < max_keys && acc < need) {
    int found = 0;
    uint64_t best_tick = 0, best_idx = 0, best_size = 0;
    uint8_t best_key[kKeySize];
    for (uint64_t si = 0; si < n; si++) {
      ShardHeader* sh = shard(h, si);
      Locker lock(sh);
      Slot* table = stripe(h, sh);
      for (uint64_t i = 0; i < sh->n_slots; i++) {
        Slot* s = &table[i];
        if (s->state != kSealed || s->refcount != 0 || s->doomed) continue;
        uint64_t gidx = si * stride + i;
        bool taken = false;
        for (int j = 0; j < count; j++) {
          if (chosen[j] == gidx) { taken = true; break; }
        }
        if (taken) continue;
        if (!found || s->lru_tick < best_tick) {
          best_tick = s->lru_tick;
          best_idx = gidx;
          best_size = s->data_size;
          memcpy(best_key, s->key, kKeySize);
          found = 1;
        }
      }
    }
    if (!found) break;
    chosen[count] = best_idx;
    memcpy(keys_out + (uint64_t)count * kKeySize, best_key, kKeySize);
    acc += best_size;
    count++;
  }
  return count;
}

uint8_t* rtpu_store_base(void* hp) {
  return reinterpret_cast<Handle*>(hp)->base;
}

// Reserve space for an object. Returns payload offset, or 0 on:
//   errno_out = 1 (already exists), 2 (out of memory even after eviction),
//               3 (slot table full).
//
// Two-phase: a PENDING placeholder slot is inserted under the home shard's
// lock (duplicate creates see err 1 immediately), then the arena block is
// allocated under the owning shard's lock only — concurrent creates from
// separate processes proceed in parallel unless they hash to one shard.
//
// pref_shard (>= 0) is the caller's ALLOCATION-affinity hint, normally
// pid-derived: the slot's home stays key-hashed (lookups are one-shard),
// but the payload block is taken from the preferred sub-arena first, so a
// writer process keeps reusing blocks its own page tables already map.
// Without this, concurrent writers swap first-fit blocks between
// processes and every put pays per-process soft page faults over the
// whole block (~30us/page on sandboxed kernels = the multi-writer put
// collapse). pref_shard < 0 falls back to the home shard.
uint64_t rtpu_obj_create(void* hp, const uint8_t* key, uint64_t data_size,
                         int64_t pref_shard, int* errno_out) {
  auto* h = reinterpret_cast<Handle*>(hp);
  *errno_out = 0;
  uint64_t home = home_of(h, key);
  ShardHeader* hs = shard(h, home);
  {
    Locker lock(hs);
    if (find_slot_in(h, hs, key)) {
      *errno_out = 1;
      return 0;
    }
    Slot* s = find_insert_slot_in(h, hs, key);
    if (!s) {
      *errno_out = 3;
      return 0;
    }
    memcpy(s->key, key, kKeySize);
    s->refcount = 0;
    s->doomed = 0;
    s->offset = 0;
    s->data_size = data_size;
    s->arena_shard = kPendingShard;
    s->lru_tick = clock_tick(h);
    s->state = kCreated;  // visible, but pending: get/seal/delete skip it
    hs->n_objects++;
  }
  uint64_t n = h->hdr->n_shards;
  uint64_t first = (pref_shard >= 0 ? (uint64_t)pref_shard % n : home);
  uint64_t off = 0, ashard = 0;
  for (uint64_t d = 0; d < n && !off; d++) {
    uint64_t si = (first + d) % n;
    ShardHeader* as = shard(h, si);
    Locker lock(as);
    off = arena_alloc(h, as, data_size);
    if (off) ashard = si;
  }
  if (!off && __atomic_load_n(&h->hdr->auto_evict, __ATOMIC_RELAXED)) {
    uint64_t need = align64(data_size ? data_size : 1);
    for (uint64_t d = 0; d < n && !off; d++) {
      uint64_t si = (first + d) % n;
      if (evict_in_shard(h, si, need)) {
        ShardHeader* as = shard(h, si);
        Locker lock(as);
        off = arena_alloc(h, as, data_size);
        if (off) ashard = si;
      }
    }
  }
  int filled = 0;
  {
    Locker lock(hs);
    Slot* s = find_slot_in(h, hs, key);
    if (s && s->state == kCreated && s->arena_shard == kPendingShard) {
      if (off) {
        s->offset = off;
        s->arena_shard = (uint8_t)ashard;
        filled = 1;
      } else {
        s->state = kTombstone;
        hs->n_objects--;
      }
    }
  }
  if (!off) {
    *errno_out = 2;
    return 0;
  }
  if (!filled) {  // placeholder vanished (defensive): return the block
    free_block_in(h, ashard, off);
    *errno_out = 2;
    return 0;
  }
  return off;
}

int rtpu_obj_seal(void* hp, const uint8_t* key) {
  auto* h = reinterpret_cast<Handle*>(hp);
  ShardHeader* hs = shard(h, home_of(h, key));
  Locker lock(hs);
  Slot* s = find_slot_in(h, hs, key);
  if (!s || s->state != kCreated || s->arena_shard == kPendingShard)
    return -1;
  s->state = kSealed;
  pthread_cond_broadcast(&hs->seal_cond);
  return 0;
}

// Blocking get: waits up to timeout_ms (-1 = forever, 0 = nonblocking) for
// the object to be sealed. On success pins (refcount++) and fills
// offset/size. Returns 0 ok, -1 timeout/missing.
int rtpu_obj_get(void* hp, const uint8_t* key, int64_t timeout_ms,
                 uint64_t* offset, uint64_t* size) {
  auto* h = reinterpret_cast<Handle*>(hp);
  ShardHeader* hs = shard(h, home_of(h, key));
  Locker lock(hs);
  struct timespec deadline;
  if (timeout_ms > 0) {
    clock_gettime(CLOCK_MONOTONIC, &deadline);
    deadline.tv_sec += timeout_ms / 1000;
    deadline.tv_nsec += (timeout_ms % 1000) * 1000000L;
    if (deadline.tv_nsec >= 1000000000L) {
      deadline.tv_sec++;
      deadline.tv_nsec -= 1000000000L;
    }
  }
  for (;;) {
    Slot* s = find_slot_in(h, hs, key);
    if (s && s->state == kSealed && !s->doomed) {
      s->refcount++;
      s->lru_tick = clock_tick(h);
      *offset = s->offset;
      *size = s->data_size;
      return 0;
    }
    if (timeout_ms == 0) return -1;
    int rc;
    if (timeout_ms < 0) {
      rc = pthread_cond_wait(&hs->seal_cond, &hs->mutex);
    } else {
      rc = pthread_cond_timedwait(&hs->seal_cond, &hs->mutex, &deadline);
    }
    if (rc == ETIMEDOUT) return -1;
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&hs->mutex);
  }
}

// Returns 0 on plain release, 2 when this was the LAST pin of a doomed
// object (now freed) — the caller must treat the object as deleted.
int rtpu_obj_release(void* hp, const uint8_t* key) {
  auto* h = reinterpret_cast<Handle*>(hp);
  ShardHeader* hs = shard(h, home_of(h, key));
  uint64_t free_off = 0, fshard = 0;
  {
    Locker lock(hs);
    Slot* s = find_slot_in(h, hs, key);
    if (!s || s->refcount <= 0) return -1;
    s->refcount--;
    if (s->refcount == 0 && s->doomed) {
      free_off = s->offset;
      fshard = s->arena_shard;
      s->state = kTombstone;
      s->doomed = 0;
      hs->n_objects--;
    }
  }
  if (free_off) {
    free_block_in(h, fshard, free_off);
    return 2;
  }
  return 0;
}

// Delete: free immediately if unpinned; pinned objects are DOOMED — they
// read as absent immediately (get/contains miss them) and their memory is
// freed by the LAST release. This closes the spill/consume race: a
// concurrent spiller's pin cannot make a consumer's delete silently fail
// (the spiller's release returns 2 so it can discard the spill file it
// wrote). A PENDING create (allocation in flight) reads as missing.
int rtpu_obj_delete(void* hp, const uint8_t* key) {
  auto* h = reinterpret_cast<Handle*>(hp);
  ShardHeader* hs = shard(h, home_of(h, key));
  uint64_t free_off = 0, fshard = 0;
  {
    Locker lock(hs);
    Slot* s = find_slot_in(h, hs, key);
    if (!s || (s->state == kCreated && s->arena_shard == kPendingShard))
      return -1;  // pending placeholders are reclaimed via _reclaim_pending
    if (s->refcount > 0) {
      s->doomed = 1;
      return 0;
    }
    free_off = s->offset;
    fshard = s->arena_shard;
    s->state = kTombstone;
    s->doomed = 0;
    hs->n_objects--;
  }
  free_block_in(h, fshard, free_off);
  return 0;
}

// Reclaim a PENDING placeholder slot (creator died between inserting the
// placeholder and filling it — no other op touches pending slots, so a
// dead creator would wedge the key forever). Touches ONLY pending slots:
// a live writer's kCreated (mid-write, allocation complete) slot is never
// affected. The slot owns no arena block yet; a still-LIVE creator whose
// placeholder was reclaimed out from under it finds the slot gone at fill
// time and returns its freshly-allocated block (the !filled branch in
// rtpu_obj_create). Returns 0 if reclaimed, -1 otherwise.
int rtpu_obj_reclaim_pending(void* hp, const uint8_t* key) {
  auto* h = reinterpret_cast<Handle*>(hp);
  ShardHeader* hs = shard(h, home_of(h, key));
  Locker lock(hs);
  Slot* s = find_slot_in(h, hs, key);
  if (!s || s->state != kCreated || s->arena_shard != kPendingShard)
    return -1;
  s->state = kTombstone;
  hs->n_objects--;
  return 0;
}

int rtpu_obj_contains(void* hp, const uint8_t* key) {
  auto* h = reinterpret_cast<Handle*>(hp);
  ShardHeader* hs = shard(h, home_of(h, key));
  Locker lock(hs);
  Slot* s = find_slot_in(h, hs, key);
  return (s && s->state == kSealed && !s->doomed) ? 1 : 0;
}

// Abort an in-progress create (creator failed before seal).
int rtpu_obj_abort(void* hp, const uint8_t* key) {
  auto* h = reinterpret_cast<Handle*>(hp);
  ShardHeader* hs = shard(h, home_of(h, key));
  uint64_t free_off = 0, fshard = 0;
  {
    Locker lock(hs);
    Slot* s = find_slot_in(h, hs, key);
    if (!s || s->state != kCreated || s->arena_shard == kPendingShard)
      return -1;
    free_off = s->offset;
    fshard = s->arena_shard;
    s->state = kTombstone;
    hs->n_objects--;
  }
  free_block_in(h, fshard, free_off);
  return 0;
}

uint64_t rtpu_store_size(void* hp) {
  return reinterpret_cast<Handle*>(hp)->size;
}

// Fault the whole segment in without touching contents (safe concurrently
// with writers — pages are populated, not modified). Called from a
// background thread by the creator so puts never pay first-touch faults.
int rtpu_store_prefault(void* hp) {
#ifdef MADV_POPULATE_WRITE
  auto* h = reinterpret_cast<Handle*>(hp);
  return madvise(h->base, h->size, MADV_POPULATE_WRITE);
#else
  return -1;
#endif
}

void rtpu_store_stats(void* hp, uint64_t* used, uint64_t* capacity,
                      uint64_t* n_objects, uint64_t* n_evictions) {
  auto* h = reinterpret_cast<Handle*>(hp);
  *used = *capacity = *n_objects = *n_evictions = 0;
  for (uint64_t si = 0; si < h->hdr->n_shards; si++) {
    ShardHeader* sh = shard(h, si);
    Locker lock(sh);
    *used += sh->used_bytes;
    *capacity += sh->arena_size;
    *n_objects += sh->n_objects;
    *n_evictions += sh->n_evictions;
  }
}

}  // extern "C"
