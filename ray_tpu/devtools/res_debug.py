"""Runtime resource-lifetime witness (``RTPU_DEBUG_RES=1``) — the
dynamic half of the ``res`` rtpu-lint rule family, mirroring
``rpc_debug.py`` / ``jax_debug.py`` / ``lock_debug.py``: zero overhead
when the flag is off, and when on it turns the repo's acquire/release
seams into a per-process BALANCE registry:

- **BufferLease pin/release** (``protocol.BufferLease``): every lease
  registers on construction and settles when its release callable runs;
  a lease dropped on an error path (the PR 2 forever-pinned-borrow
  shape) stays outstanding forever and shows up in every snapshot.
- **Lease grant/return** (``node_manager``): the node's lease table was
  the PR 8 leak — grants register, every pop path (return, worker
  death, orphan reclaim) settles.
- **KV speculation begin/commit/release** (``kv_manager``): an
  in-flight reservation that neither commits nor dies with its slot
  strands ``used_blocks()`` permanently.
- **Store seal/delete**: counted as gauges (``counters()``) — the store
  legitimately holds objects across a snapshot, so they ride the dump
  for attribution but are never part of the leak verdict.
- **Tracked threads** (:func:`track_thread` — the make_lock move
  applied to thread registration): a started thread is outstanding
  until its ``run()`` returns; owners assert theirs are gone at
  ``close()``.

The outstanding-count snapshot rides every flight-recorder dump
(``flight_recorder.dump_payload``, ``"res_debug"`` key), so
``bench.py --chaos`` aggregates a CLUSTER-WIDE ``leaked_resources``
count over the same ``dump_flight`` RPC the RPC witness already uses —
and :func:`check_balanced` lets ``LLMEngine.close()`` /
``ClusterCore.shutdown()`` assert their scope drained at teardown
(violations print ``RTPU_DEBUG_RES:`` lines and are queryable via
:func:`violations`).

With ``RTPU_DEBUG_RES`` unset every hook is one env read returning its
input untouched — the instrumented paths are byte-identical to a build
without this module.

Knobs:
  RTPU_DEBUG_RES=1   enable the witness (inherited by every spawned
                     cluster process, like the other RTPU_DEBUG_ flags)
"""

from __future__ import annotations

import itertools
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

#: Kinds whose outstanding count MUST be zero once a workload drains:
#: these feed the bench's cluster-wide ``leaked_resources`` verdict.
#: "thread" is deliberately absent (daemon loops are legitimately alive
#: mid-run; owners assert them at close) and the store gauges are
#: informational only. The channel kinds (dag/ring.py, dag/peer.py)
#: count mapped ring files, spilled payload side-files, and peer
#: sockets: a compiled DAG or disaggregated-serving mesh torn down
#: without releasing them is a leak the chaos bench fails on.
#: ``data_queue`` / ``data_operator`` (data/_queues.py, data/_executor.py)
#: count the streaming Dataset executor's bounded inter-operator queues
#: and long-lived operator actors: a pipeline torn down without closing
#: its edges or killing its lanes is a leak.
#: ``kv_page_obj`` (serve/engine/core.py + kv_fleet.py) counts IN-FLIGHT
#: fleet KV page transfers — a spilled block exported off-device but not
#: yet landed in the page store, or a pulled payload fetched but not yet
#: installed/rejected. Resident store objects are a cache, not a leak;
#: only a tier TRANSITION abandoned halfway is.
#: The PR 19 serving state joins the same ledger: ``qos_tenant``
#: (serve/_private/qos.py) counts live WFQ tenant lanes — configure()d
#: tenants are pinned by the operator, but lazily-minted ones must be
#: reaped once idle or a tenant-churn workload grows the scheduler
#: forever; ``serve_stream`` (serve/_private/replica.py) counts open
#: streaming cursor slots, released on completion, error, cancel, or
#: the TTL reaper; ``parked_kv`` (serve/engine/core.py) counts
#: preempted sessions parked with their KV residency — released on
#: resume or engine close. All three must balance after a
#: tenant-churn + stream-cancel loop drains.
LEAK_KINDS = ("buffer_lease", "lease", "kv_spec",
              "channel_ring", "channel_spill", "channel_sock",
              "data_queue", "data_operator", "kv_page_obj",
              "qos_tenant", "serve_stream", "parked_kv")


def enabled() -> bool:
    return os.environ.get("RTPU_DEBUG_RES", "") == "1"


class _Registry:
    """Process-global balance state: (kind, key) acquisitions vs
    releases, plus monotonic event counters (the store gauges)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._seq = itertools.count(1)
        # (kind, key) -> {"owner": int|None, "note": str}
        self.open: Dict[Tuple[str, Any], dict] = {}
        self.acquired: Dict[str, int] = {}
        self.released: Dict[str, int] = {}
        self.counters: Dict[str, int] = {}
        self.violations: List[dict] = []

    def note_violation(self, kind: str, message: str, **fields) -> None:
        rec = {"kind": kind, "message": message}
        rec.update(fields)
        with self._mu:
            self.violations.append(rec)
        print(f"RTPU_DEBUG_RES: {message}", flush=True)

    def reset(self) -> None:
        with self._mu:
            self.open.clear()
            self.acquired.clear()
            self.released.clear()
            self.counters.clear()
            self.violations.clear()


_REGISTRY = _Registry()


# ----------------------------------------------------------- primitives


def note_acquire(kind: str, key: Any = None, owner: Any = None,
                 note: str = "") -> Any:
    """Register one acquisition; returns the key (minted when None).
    No-op (returns ``key``) when the witness is off."""
    if not enabled():
        return key
    if key is None:
        key = next(_REGISTRY._seq)
    with _REGISTRY._mu:
        _REGISTRY.acquired[kind] = _REGISTRY.acquired.get(kind, 0) + 1
        _REGISTRY.open[(kind, key)] = {"owner": id(owner) if owner
                                       is not None else None,
                                       "note": note}
    return key


def note_release(kind: str, key: Any) -> None:
    """Settle one acquisition. Unknown keys are ignored — release paths
    are legitimately re-entered (idempotent returns, double-release
    guards) and the witness must never turn a benign re-release into a
    false report. No-op when the witness is off."""
    if not enabled() or key is None:
        return
    with _REGISTRY._mu:
        if _REGISTRY.open.pop((kind, key), None) is not None:
            _REGISTRY.released[kind] = \
                _REGISTRY.released.get(kind, 0) + 1


def note_event(kind: str, n: int = 1) -> None:
    """Bump a monotonic gauge (store seal/delete). No-op when off."""
    if not enabled():
        return
    with _REGISTRY._mu:
        _REGISTRY.counters[kind] = _REGISTRY.counters.get(kind, 0) + n


def wrap_release(kind: str, release: Optional[Callable],
                 owner: Any = None) -> Optional[Callable]:
    """Pair an acquisition with its release callable (the BufferLease
    seam): registers now, settles when the returned callable runs.
    Returns ``release`` untouched when the witness is off."""
    if not enabled():
        return release
    key = note_acquire(kind, owner=owner)

    def _wrapped(*a, **kw):
        note_release(kind, key)
        if release is not None:
            return release(*a, **kw)

    return _wrapped


def track_thread(thread: "threading.Thread",
                 owner: Any = None) -> "threading.Thread":
    """make_lock-style registration for threads: the thread counts as
    outstanding from this call until its ``run()`` returns. Returns the
    thread untouched when the witness is off (zero overhead)."""
    if not enabled():
        return thread
    key = note_acquire("thread", owner=owner,
                       note=thread.name or "thread")
    orig_run = thread.run

    def _run():
        try:
            orig_run()
        finally:
            note_release("thread", key)

    thread.run = _run
    return thread


# ------------------------------------------------------------- queries


def outstanding(kind: Optional[str] = None,
                owner: Any = None) -> Dict[str, int]:
    """Open (unreleased) acquisitions per kind, optionally filtered to
    one kind and/or one owner object."""
    want_owner = id(owner) if owner is not None else None
    out: Dict[str, int] = {}
    with _REGISTRY._mu:
        for (k, _key), meta in _REGISTRY.open.items():
            if kind is not None and k != kind:
                continue
            if want_owner is not None and meta["owner"] != want_owner:
                continue
            out[k] = out.get(k, 0) + 1
    return out


def counts() -> Dict[str, Dict[str, int]]:
    """Per-kind {acquired, released, outstanding} totals."""
    with _REGISTRY._mu:
        kinds = set(_REGISTRY.acquired) | set(_REGISTRY.released)
        out = {}
        for k in kinds:
            a = _REGISTRY.acquired.get(k, 0)
            r = _REGISTRY.released.get(k, 0)
            out[k] = {"acquired": a, "released": r, "outstanding": a - r}
        return out


def counters() -> Dict[str, int]:
    """Monotonic event gauges (store seal/delete)."""
    with _REGISTRY._mu:
        return dict(_REGISTRY.counters)


def violations() -> List[dict]:
    with _REGISTRY._mu:
        return [dict(v) for v in _REGISTRY.violations]


def reset() -> None:
    """Clear the witness registry (tests isolate scenarios with this)."""
    _REGISTRY.reset()


def dump_payload() -> Dict[str, Any]:
    """The snapshot that rides ``flight_recorder.dump_payload`` under
    the ``"res_debug"`` key: outstanding per kind, leak-kind total,
    gauges, and violation count — enough for the bench to aggregate a
    cluster-wide leak verdict without a new RPC surface."""
    out = outstanding()
    with _REGISTRY._mu:
        acquired = dict(_REGISTRY.acquired)
    return {
        "outstanding": out,
        "leaked": sum(out.get(k, 0) for k in LEAK_KINDS),
        # Coverage evidence: how many acquisitions the witness actually
        # observed (a leaked==0 verdict over zero acquires is vacuous —
        # the bench surfaces the sum as res_acquires_audited).
        "acquired": acquired,
        "counters": counters(),
        "violations": len(violations()),
    }


def check_balanced(scope: str, kinds: Tuple[str, ...],
                   owner: Any = None) -> bool:
    """Teardown assertion: every acquisition of ``kinds`` (optionally
    owner-scoped) has been released. Imbalance records a violation and
    prints an ``RTPU_DEBUG_RES:`` line — teardown itself proceeds (the
    witness reports, it never breaks the close path). Returns True when
    balanced / witness off."""
    if not enabled():
        return True
    bad = {}
    for k in kinds:
        n = outstanding(kind=k, owner=owner).get(k, 0)
        if n:
            bad[k] = n
    if not bad:
        return True
    detail = ", ".join(f"{k}={n}" for k, n in sorted(bad.items()))
    _REGISTRY.note_violation(
        "unbalanced-at-close",
        f"{scope} closed with unreleased resources: {detail} — an "
        "acquire path has no matching release (see reslint: "
        "acquire-without-release / begin-without-commit)",
        scope=scope, outstanding=dict(bad))
    return False
