"""rtpu devtools: project-specific static analysis + runtime checkers.

Every PR so far has shipped post-review fixes for the same bug families
(lock-ordering hazards, blocking I/O while holding a state lock, sockets
closed without shutdown under readers writing into shm, dashboard
innerHTML XSS, jax<0.5-incompatible API calls, swallowed exceptions).
This package codifies those invariants as tooling instead of reviewer
memory — the same move as the reference's lint-enforced C++ status/ID
conventions and TSan wiring:

- ``python -m ray_tpu.devtools.lint``: AST-based, stdlib-only linter
  enforcing the declared invariants (see ``invariants.py``) against a
  checked-in baseline (``lint_baseline.json``) — legacy violations are
  tracked-not-fatal, NEW violations fail the run.
- ``lock_debug``: ``RTPU_DEBUG_LOCKS=1`` swaps the cluster core's lock
  creation for an ordering witness that records the per-thread lock
  acquisition graph, detects order cycles online, and reports
  excessive hold times via util/metrics.
"""
