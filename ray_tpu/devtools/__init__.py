"""rtpu devtools: project-specific static analysis + runtime checkers.

Every PR so far has shipped post-review fixes for the same bug families
(lock-ordering hazards, blocking I/O while holding a state lock, sockets
closed without shutdown under readers writing into shm, dashboard
innerHTML XSS, jax<0.5-incompatible API calls, swallowed exceptions).
This package codifies those invariants as tooling instead of reviewer
memory — the same move as the reference's lint-enforced C++ status/ID
conventions and TSan wiring:

- ``python -m ray_tpu.devtools.lint``: AST-based, stdlib-only linter
  enforcing the declared invariants against a checked-in baseline
  (``lint_baseline.json``, sectioned per rule family) — legacy
  violations are tracked-not-fatal, NEW violations fail the run. Four
  rule families: ``concurrency`` (tables in ``invariants.py``),
  ``jax`` (``jaxlint.py``: tracing-safety rules codified from the
  model path's post-review bugs — closure constant-folding into jit,
  donation-then-read, hot-path host syncs, unclamped
  dynamic_update_slice, Mosaic kernel shape rules, per-mesh RNG
  re-init), ``dist`` (``distlint.py``: the distributed RPC
  contract — every handler classified in ``protocol.py``'s
  retry/idempotency sets, retrying_call only against retry-safe
  methods, object-directory frames riding their batched outbox,
  fan-out loops deadline-bounded on a monotonic clock, every server
  class chaos-role-targetable), and ``res`` (``reslint.py``: resource
  lifetimes — releasable handles released on every path, KV
  speculation reservations resolved on the failure arm, registries
  fed by handlers/loops carrying eviction evidence, daemon threads
  stopped from the teardown path, fds surviving their error paths).
- ``lock_debug``: ``RTPU_DEBUG_LOCKS=1`` swaps the cluster core's lock
  creation for an ordering witness that records the per-thread lock
  acquisition graph, detects order cycles online, and reports
  excessive hold times via util/metrics.
- ``jax_debug``: ``RTPU_DEBUG_JAX=1`` wraps the engine's and trainer's
  jit entry points in a recompile witness (distinct-signature counts
  vs declared program budgets), counts the engine's device->host
  fetches per tag (one-sync-per-chunk is assertable), and wires
  ``jax.transfer_guard`` around engine ticks
  (``RTPU_DEBUG_JAX_TRANSFER_GUARD=disallow``). Zero overhead off.
- ``rpc_debug``: ``RTPU_DEBUG_RPC=1`` audits the RPC contract at
  dispatch — unclassified methods fail loudly, idempotent requests are
  delivered twice with responses compared (the at-most-once audit),
  and outbox frames carry per-(sender, receiver) sequence checks that
  catch add/remove inversions on arrival. Zero overhead off.
- ``res_debug``: ``RTPU_DEBUG_RES=1`` turns the acquire/release seams
  into a per-process balance registry — BufferLease pin/release, node
  lease grant/return, KV speculation begin/commit/release, store
  seal/delete gauges, tracked threads — asserted drained at
  engine/cluster close, snapshotted into every flight-recorder dump
  (``"res_debug"`` key), and aggregated cluster-wide by
  ``bench.py --chaos`` into ``leaked_resources``. Zero overhead off.
"""
