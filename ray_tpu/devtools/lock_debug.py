"""Runtime lock-order witness (``RTPU_DEBUG_LOCKS=1``).

The cluster core creates its locks through :func:`make_lock` /
:func:`make_rlock`. Normally these return plain ``threading`` locks —
zero overhead. With ``RTPU_DEBUG_LOCKS=1`` in the environment (workers
inherit it from the driver) every named lock is wrapped in a witness
that:

- records the per-thread acquisition graph: an edge ``A -> B`` means
  some thread acquired ``B`` while holding ``A``. Edges are keyed by
  lock NAME, not instance, so the graph stays O(lock classes) and an
  ordering decision made on one connection's ``send_lock`` generalizes
  to all of them. Cross-instance edges between two locks of the SAME
  name are ignored (two actor connections' locks nesting is not an
  ordering fact).
- detects ordering cycles ONLINE: the first edge that closes a cycle
  (``A -> ... -> A``) is reported to stderr once and recorded for
  :func:`get_report` — the witness sees the deadlock *potential* from
  the two halves of an inversion even when the schedule never actually
  deadlocks.
- reports a same-thread re-acquire of a non-reentrant lock (guaranteed
  self-deadlock) before blocking on it.
- measures hold times: a lock held longer than
  ``RTPU_DEBUG_LOCKS_HOLD_S`` (default 1.0s) is recorded and counted on
  the ``rtpu_debug_lock_hold_exceeded`` metric (util/metrics), labelled
  by lock name.

The wrapper implements the private ``Condition`` integration surface
(``_release_save`` / ``_acquire_restore`` / ``_is_owned``) so
``threading.Condition(make_rlock(...))`` works unchanged; a
``Condition.wait`` fully releases the witness's hold bookkeeping and
restarts the hold timer on wakeup (time parked in ``wait`` is not
"holding" time).
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple


def enabled() -> bool:
    return os.environ.get("RTPU_DEBUG_LOCKS", "") == "1"


def hold_threshold_s() -> float:
    try:
        return float(os.environ.get("RTPU_DEBUG_LOCKS_HOLD_S", "1.0"))
    except ValueError:
        return 1.0


def _site() -> str:
    """file:line of the nearest frame outside this module."""
    try:
        for f in reversed(traceback.extract_stack()):
            if os.path.basename(f.filename) != "lock_debug.py":
                return f"{os.path.basename(f.filename)}:{f.lineno}"
    except Exception:  # noqa: BLE001 — diagnostics only
        pass
    return "?"


class _Witness:
    """Process-global acquisition graph + per-thread held stacks."""

    def __init__(self):
        self._mu = threading.Lock()  # guards the graph, NOT a DebugLock
        self._edges: Dict[str, Set[str]] = {}
        self._edge_sites: Dict[Tuple[str, str], str] = {}
        self._cycles: List[dict] = []
        self._cycle_keys: Set[tuple] = set()
        self._long_holds: List[dict] = []
        self._tls = threading.local()

    # ------------------------------------------------------- per thread

    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []  # [lock, name, count, t_acquired]
        return h

    # ----------------------------------------------------------- events

    def on_attempt(self, lock, name: str, reentrant: bool,
                   will_block: bool) -> None:
        """Dependency edges are recorded on the ATTEMPT (lockdep
        semantics): a thread holding A that merely TRIES to acquire B
        establishes A->B — which is how an actual in-progress deadlock
        (where neither second acquire ever succeeds) still closes the
        cycle online."""
        held = self._held()
        for entry in held:
            if entry[0] is lock:
                if not reentrant and will_block:
                    self._record_cycle(
                        [name, name],
                        f"self-deadlock: thread "
                        f"{threading.current_thread().name} re-acquires "
                        f"non-reentrant '{name}' at {_site()}")
                return  # re-entry adds no new dependency
        for entry in held:
            if entry[1] != name:
                self._add_edge(entry[1], name)

    def on_acquired(self, lock, name: str) -> None:
        held = self._held()
        for entry in held:
            if entry[0] is lock:
                entry[2] += 1
                return
        held.append([lock, name, 1, time.monotonic()])

    def on_released(self, lock, name: str) -> None:
        held = self._held()
        for i, entry in enumerate(held):
            if entry[0] is lock:
                entry[2] -= 1
                if entry[2] <= 0:
                    del held[i]
                    self._note_hold(name, time.monotonic() - entry[3])
                return

    def drop_for_wait(self, lock) -> Optional[list]:
        """Condition.wait released the lock out from under us: clear the
        bookkeeping and hand back the entry for restore."""
        held = self._held()
        for i, entry in enumerate(held):
            if entry[0] is lock:
                del held[i]
                return entry
        return None

    def restore_after_wait(self, entry: Optional[list]) -> None:
        if entry is not None:
            entry[3] = time.monotonic()  # waiting is not holding
            self._held().append(entry)

    # ------------------------------------------------------------ graph

    def _add_edge(self, a: str, b: str) -> None:
        with self._mu:
            peers = self._edges.setdefault(a, set())
            if b in peers:
                return
            peers.add(b)
            self._edge_sites[(a, b)] = _site()
            path = self._find_path(b, a)
        if path is not None:
            chain = [a] + path
            self._record_cycle(
                chain,
                f"lock-order cycle {' -> '.join(chain)} (edge {a}->{b} "
                f"at {self._edge_sites.get((a, b), '?')}, thread "
                f"{threading.current_thread().name})")

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS path src..dst through the edge graph (caller holds _mu)."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _record_cycle(self, chain: List[str], message: str) -> None:
        key = tuple(sorted(set(chain)))
        with self._mu:
            if key in self._cycle_keys:
                return
            self._cycle_keys.add(key)
            self._cycles.append({"chain": list(chain),
                                 "message": message})
        print(f"RTPU_DEBUG_LOCKS: {message}", flush=True)

    # ------------------------------------------------------- hold times

    def _note_hold(self, name: str, seconds: float) -> None:
        if seconds <= hold_threshold_s():
            return
        with self._mu:
            self._long_holds.append({
                "lock": name, "seconds": seconds,
                "thread": threading.current_thread().name})
            if len(self._long_holds) > 256:
                del self._long_holds[0]
        try:
            from ray_tpu.util import metrics as _metrics

            m = _metrics.get_metric("rtpu_debug_lock_hold_exceeded")
            if m is None:
                m = _metrics.Counter(
                    "rtpu_debug_lock_hold_exceeded",
                    "lock holds exceeding RTPU_DEBUG_LOCKS_HOLD_S")
            m.inc(labels={"lock": name})
        except Exception:  # noqa: BLE001 — diagnostics must never kill
            pass

    # ---------------------------------------------------------- reports

    def report(self) -> dict:
        with self._mu:
            return {
                "cycles": [dict(c) for c in self._cycles],
                "edges": {a: sorted(bs)
                          for a, bs in sorted(self._edges.items())},
                "long_holds": [dict(h) for h in self._long_holds],
            }

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._edge_sites.clear()
            self._cycles.clear()
            self._cycle_keys.clear()
            self._long_holds.clear()


_WITNESS = _Witness()


class DebugLock:
    """Witness-wrapped lock. Supports the full Lock/RLock surface plus
    the private Condition integration hooks."""

    __slots__ = ("_name", "_inner", "_reentrant")

    def __init__(self, name: str, inner, reentrant: bool):
        self._name = name
        self._inner = inner
        self._reentrant = reentrant

    # -------------------------------------------------- Lock protocol

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        _WITNESS.on_attempt(self, self._name, self._reentrant,
                            will_block=blocking and timeout < 0)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _WITNESS.on_acquired(self, self._name)
        return ok

    def release(self) -> None:
        self._inner.release()
        _WITNESS.on_released(self, self._name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "DebugLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<DebugLock {self._name} {self._inner!r}>"

    # --------------------------------------- Condition integration

    def _release_save(self):
        entry = _WITNESS.drop_for_wait(self)
        if hasattr(self._inner, "_release_save"):
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        return (state, entry)

    def _acquire_restore(self, saved) -> None:
        state, entry = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        _WITNESS.restore_after_wait(entry)

    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        # Plain Lock: mirror Condition's probe, against the INNER lock
        # so the witness doesn't see the probe as an acquisition.
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


def make_lock(name: str):
    """A ``threading.Lock()`` — witness-wrapped under RTPU_DEBUG_LOCKS=1.
    ``name`` identifies the lock CLASS (module.attr), shared by every
    instance created at this site."""
    if enabled():
        return DebugLock(name, threading.Lock(), reentrant=False)
    return threading.Lock()


def make_rlock(name: str):
    """A ``threading.RLock()`` — witness-wrapped under
    RTPU_DEBUG_LOCKS=1."""
    if enabled():
        return DebugLock(name, threading.RLock(), reentrant=True)
    return threading.RLock()


def get_report() -> dict:
    """{"cycles": [...], "edges": {name: [names]}, "long_holds": [...]}
    accumulated since process start / the last reset()."""
    return _WITNESS.report()


def reset() -> None:
    """Clear the witness (tests isolate scenarios with this)."""
    _WITNESS.reset()
