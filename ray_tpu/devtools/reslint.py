"""res-lint: resource-lifetime rules (rule family ``res``).

Stdlib-only AST analysis riding rtpu-lint's fingerprint/baseline/
``# rtpu-lint: disable=<rule>`` machinery (``lint.py`` runs all four
rule families from one CLI). Every rule codifies a lifetime bug this
repo actually shipped and re-found by hand across PRs 1-11:

  acquire-without-release
      a releasable handle (``BufferLease(...)``, ``<x>.pin(...)``) is
      obtained but not released on every path and not consumed as a
      context manager — ``with``, ``stack.enter_context``, a
      try-``finally`` release, or an ownership transfer (returned,
      stored, passed onward) are all OK. The PR 2 borrow-pin bug:
      owners pinned borrowed objects forever because the release half
      was simply absent.
  begin-without-commit
      ``<kv>.begin_speculation(...)`` with no
      ``commit_speculation``/``release`` (or a cleanup helper) on the
      failure arm — a device fault mid-verify would strand the
      reservation and ``used_blocks()`` would never drop (the PR 3
      review hazard, re-opened whenever the tick's error handling is
      touched).
  unbounded-registry-growth
      a ``self.<attr>`` dict/list/set/deque grown from an RPC handler
      or long-lived loop (directly or through same-class helpers) with
      no eviction, ``maxlen``, cap check, or reaper evidence anywhere
      in the class — the PR 4 ``_local_objects`` mirror and the PR 11
      return-lease memo (which needed a hand-picked 4096 cap in
      review) both shipped exactly this shape.
  thread-without-stop
      a daemon ``Thread``/``Timer`` attribute never joined/cancelled —
      and no stop-event set — anywhere REACHABLE from the owning
      class's ``stop()``/``close()``/``shutdown()`` through same-class
      helper calls. Generalizes PR 5's daemon-no-join (which accepted
      a join in ANY method): a join that the stop path never runs is
      teardown theater.
  fd-leak-on-error
      a socket/file opened and bound to a local, followed by calls
      that can raise before the handle is closed or ownership escapes,
      with no enclosing try whose handler/finally closes it and no
      ``with`` — the open fd leaks on the exception path.

``lint_source(source, module, path)`` returns ``lint.Finding`` rows;
module-scoped tables live in ``invariants.py``. The runtime half of
this family is ``res_debug.py`` (``RTPU_DEBUG_RES=1``): the same
acquire/release seams these rules police statically are counted in a
per-process balance registry and asserted drained at close.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools import invariants as inv
# RES_RULES is single-sourced in lint.py (the family/baseline machinery
# keys on it); aliased here so rule code and rule registry can't drift.
from ray_tpu.devtools.lint import (RES_RULES as RULES, Finding, _dotted,
                                   suppressed)


def _leaf(dotted: Optional[str]) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


def _walk_no_nested(root_nodes) -> List[ast.AST]:
    """Child subtree of the given statements, excluding nested function
    and class bodies (they run on their own schedule)."""
    out: List[ast.AST] = []
    todo = list(root_nodes)
    while todo:
        sub = todo.pop()
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda, ast.ClassDef)):
            continue
        out.append(sub)
        todo.extend(ast.iter_child_nodes(sub))
    return out


def _self_attr(node: ast.AST) -> Optional[str]:
    """'attr' for a ``self.attr`` expression, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _ResLinter:
    def __init__(self, module: str, path: str, source: str):
        self.module = module
        self.path = path
        self.lines = source.splitlines()
        self.findings: List[Finding] = []
        self._scope: List[str] = []

    # ------------------------------------------------------------ utils

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        assert rule in RULES, f"unregistered res rule id {rule!r}"
        line = getattr(node, "lineno", 1)
        if suppressed(self.lines, line, rule):
            return
        self.findings.append(Finding(rule, self.path, line,
                                     ".".join(self._scope), message))

    # ------------------------------------------------------------- walk

    def run(self, tree: Optional[ast.AST] = None) -> List[Finding]:
        if tree is None:
            try:
                tree = ast.parse("\n".join(self.lines),
                                 filename=self.path)
            except SyntaxError:
                return []  # the concurrency family reports this
        self._walk(tree)
        return self.findings

    def _walk(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scope.append(child.name)
                self._check_acquire_release(child)
                self._check_begin_commit(child)
                self._check_fd_leak(child)
                self._walk(child)
                self._scope.pop()
                continue
            if isinstance(child, ast.ClassDef):
                self._scope.append(child.name)
                self._check_registry_growth(child)
                self._check_thread_stop(child)
                self._walk(child)
                self._scope.pop()
                continue
            self._walk(child)

    # ----------------------------------------------- acquire vs release

    @staticmethod
    def _is_acquire(call: ast.Call) -> Optional[str]:
        dotted = _dotted(call.func)
        leaf = _leaf(dotted)
        if leaf in inv.RES_ACQUIRE_CONSTRUCTORS:
            return leaf
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr in inv.RES_ACQUIRE_ATTRS:
            return f"{dotted or call.func.attr}()"
        return None

    def _check_acquire_release(self, fn) -> None:
        """A releasable handle bound to a local must be consumed as a
        context manager, released in a ``finally``, or have its
        ownership escape (returned / stored / passed onward). A release
        that only sits on the straight-line success path is the finding
        — the exception path skips it (PR 2's forever-pinned borrow)."""
        nodes = _walk_no_nested(ast.iter_child_nodes(fn))
        ok_ids: Set[int] = set()          # acquire Calls consumed safely
        acquires: List[Tuple[ast.Call, str]] = []
        bound: Dict[str, List[ast.Call]] = {}
        # Pass 1: find acquires + structurally-safe consumptions.
        for sub in nodes:
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    ok_ids.add(id(item.context_expr))
            elif isinstance(sub, ast.Return) and sub.value is not None:
                for c in ast.walk(sub.value):
                    ok_ids.add(id(c))  # ownership transferred to caller
            elif isinstance(sub, ast.Call):
                desc = self._is_acquire(sub)
                if desc is not None:
                    acquires.append((sub, desc))
                # An acquire nested inside ANOTHER call's args is an
                # ownership transfer (enter_context included).
                for arg in list(sub.args) + [kw.value
                                             for kw in sub.keywords]:
                    for c in ast.walk(arg):
                        ok_ids.add(id(c))
            elif isinstance(sub, ast.Assign):
                tgt = sub.targets[0] if len(sub.targets) == 1 else None
                if isinstance(sub.value, ast.Call) and \
                        self._is_acquire(sub.value) is not None:
                    if isinstance(tgt, ast.Name):
                        bound.setdefault(tgt.id, []).append(sub.value)
                    else:
                        # self.x = acquire(...) / container[k] = ...:
                        # lifecycle escapes to the owner object.
                        ok_ids.add(id(sub.value))
        # Pass 2: per bound name, look at how it is used.
        name_events: Dict[str, Dict[str, bool]] = {
            n: {"with": False, "rel_fin": False, "rel_any": False,
                "escape": False} for n in bound}
        finally_ids: Set[int] = set()
        for sub in nodes:
            if isinstance(sub, ast.Try):
                for s in sub.finalbody:
                    for c in ast.walk(s):
                        finally_ids.add(id(c))
        for sub in nodes:
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Name) and ce.id in name_events:
                        name_events[ce.id]["with"] = True
            elif isinstance(sub, ast.Call):
                if isinstance(sub.func, ast.Attribute) and \
                        isinstance(sub.func.value, ast.Name) and \
                        sub.func.value.id in name_events:
                    ev = name_events[sub.func.value.id]
                    if sub.func.attr in inv.RES_RELEASE_ATTRS:
                        ev["rel_any"] = True
                        if id(sub) in finally_ids:
                            ev["rel_fin"] = True
                    elif sub.func.attr == "enter_context":
                        pass
                # The HANDLE ITSELF passed as an argument -> ownership
                # transfer. A sub-attribute (``conn.sendall(buf.view)``)
                # is a use, not a transfer — the PR 2 borrow-pin leaked
                # exactly through that distinction.
                for arg in list(sub.args) + [kw.value
                                             for kw in sub.keywords]:
                    cands = [arg]
                    if isinstance(arg, (ast.Tuple, ast.List)):
                        cands = list(arg.elts)
                    elif isinstance(arg, ast.Starred):
                        cands = [arg.value]
                    for c in cands:
                        if isinstance(c, ast.Name) and \
                                c.id in name_events:
                            name_events[c.id]["escape"] = True
            elif isinstance(sub, ast.Return) and sub.value is not None:
                for c in ast.walk(sub.value):
                    if isinstance(c, ast.Name) and c.id in name_events:
                        name_events[c.id]["escape"] = True
            elif isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        for c in ast.walk(sub.value):
                            if isinstance(c, ast.Name) and \
                                    c.id in name_events:
                                name_events[c.id]["escape"] = True
        for name, calls in bound.items():
            ev = name_events[name]
            if ev["with"] or ev["rel_fin"] or ev["escape"]:
                continue
            for call in calls:
                if id(call) in ok_ids:
                    continue
                desc = self._is_acquire(call) or name
                if ev["rel_any"]:
                    self._emit(
                        "acquire-without-release", call,
                        f"'{name}' ({desc}) is released on the success "
                        "path only — an exception between acquire and "
                        "release pins it forever; use `with`, "
                        "try/finally, or stack.enter_context")
                else:
                    self._emit(
                        "acquire-without-release", call,
                        f"'{name}' ({desc}) is acquired but never "
                        "released and never escapes this function — "
                        "the pin/lease leaks on every path")
        for call, desc in acquires:
            # Bare-expression acquire: the handle is dropped on the
            # floor immediately (not bound, not consumed, not passed).
            if id(call) in ok_ids:
                continue
            if any(call in vals for vals in bound.values()):
                continue
            self._emit(
                "acquire-without-release", call,
                f"{desc} result is discarded — the acquired pin/lease "
                "can never be released")

    # ----------------------------------------------- begin vs commit

    def _check_begin_commit(self, fn) -> None:
        """``begin_speculation`` opens a reservation; the failure arm
        (an ``except``/``finally`` covering the in-flight window) must
        resolve it — ``commit_speculation``/``release`` directly, or a
        same-class cleanup helper (``self._fail_roster(...)`` releases
        every active slot). No failure arm at all is the finding too:
        the first device fault strands the reservation."""
        nodes = _walk_no_nested(ast.iter_child_nodes(fn))
        begin: Optional[ast.Call] = None
        handler_bodies: List[ast.AST] = []
        for sub in nodes:
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "begin_speculation":
                begin = begin or sub
            elif isinstance(sub, ast.Try):
                for h in sub.handlers:
                    handler_bodies.extend(h.body)
                handler_bodies.extend(sub.finalbody)
        if begin is None:
            return
        cleanup = False
        for sub in _walk_no_nested(handler_bodies):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute):
                attr = sub.func.attr
                if attr in inv.RES_COMMIT_ATTRS or \
                        inv.RES_CLEANUP_NAME_RE.search(attr):
                    cleanup = True
                    break
        if cleanup:
            return
        if handler_bodies:
            msg = ("begin_speculation has no commit_speculation/release "
                   "(or cleanup helper) on the failure arm — a device "
                   "fault mid-verify strands the reservation and "
                   "used_blocks() never drops")
        else:
            msg = ("begin_speculation with no try/except/finally "
                   "covering the in-flight window — the first raise "
                   "strands the reservation (no failure arm resolves "
                   "it)")
        self._emit("begin-without-commit", begin, msg)

    # ------------------------------------------- registry growth bounds

    @staticmethod
    def _method_self_calls(fn) -> Set[str]:
        out: Set[str] = set()
        for sub in _walk_no_nested(ast.iter_child_nodes(fn)):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    isinstance(sub.func.value, ast.Name) and \
                    sub.func.value.id == "self":
                out.add(sub.func.attr)
        return out

    def _check_registry_growth(self, cls: ast.ClassDef) -> None:
        """``self.<attr>`` containers grown from RPC handlers or
        long-lived loops (directly or through same-class helpers) need
        eviction evidence somewhere in the class: a pop/del/clear on
        the attr, a ``maxlen=``/cap check, or a reaper-named method
        touching it. The PR 4 ``_local_objects`` mirror and the PR 11
        return-lease memo both grew forever before review caught them
        by hand."""
        if self.module not in inv.RES_REGISTRY_MODULES:
            return
        methods = {m.name: m for m in cls.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        # Hot set: rpc handlers + long-lived loops, closed over
        # same-class helper calls (the historical leaks hid one helper
        # away from the handler).
        hot: Set[str] = set()
        for name, m in methods.items():
            if name.startswith("rpc_") or \
                    inv.RES_LOOP_NAME_RE.search(name):
                hot.add(name)
            else:
                for sub in _walk_no_nested(ast.iter_child_nodes(m)):
                    if isinstance(sub, ast.While) and \
                            isinstance(sub.test, ast.Constant) and \
                            sub.test.value:
                        hot.add(name)
                        break
        frontier = list(hot)
        while frontier:
            callee_sets = [self._method_self_calls(methods[n])
                           for n in frontier if n in methods]
            frontier = []
            for cs in callee_sets:
                for callee in cs:
                    if callee in methods and callee not in hot:
                        hot.add(callee)
                        frontier.append(callee)
        # Growth sites in hot methods.
        growth: Dict[str, ast.AST] = {}   # attr -> first growth node
        evidence: Set[str] = set()        # attrs with bounding evidence
        reaper_methods = {n for n in methods
                          if inv.RES_REAPER_NAME_RE.search(n)}
        for name, m in methods.items():
            in_hot = name in hot
            in_reaper = name in reaper_methods
            # Local aliases of self.<attr> within this method: an
            # eviction call on the alias counts for the attr (the
            # outbox drain binds ``outbox = self._obj_notify_outbox``
            # and poplefts the local; ``subs = self._subs.get(ch)``
            # removes through the fetched inner container).
            aliases: Dict[str, Set[str]] = {}  # local name -> attrs
            body = _walk_no_nested(ast.iter_child_nodes(m))
            for sub in body:
                tgt = None
                src = None
                if isinstance(sub, ast.Assign) and \
                        len(sub.targets) == 1 and \
                        isinstance(sub.targets[0], ast.Name):
                    tgt, src = sub.targets[0].id, sub.value
                elif isinstance(sub, ast.For) and \
                        isinstance(sub.target, ast.Name):
                    tgt, src = sub.target.id, sub.iter
                if tgt is None or src is None:
                    continue
                for c in ast.walk(src):
                    attr = _self_attr(c)
                    if attr:
                        aliases.setdefault(tgt, set()).add(attr)
            for sub in body:
                # self.X[k] = v  /  self.X: T = deque(maxlen=...)
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    targets = (sub.targets
                               if isinstance(sub, ast.Assign)
                               else [sub.target])
                    value = sub.value
                    for tgt in targets:
                        if isinstance(tgt, ast.Subscript):
                            attr = _self_attr(tgt.value)
                            if attr and in_hot and attr not in growth:
                                growth[attr] = tgt
                    # self.X = <call with maxlen=...> (bounded ctor) or
                    # a reassignment outside __init__ (reset evidence).
                    tgt0 = targets[0] if len(targets) == 1 else None
                    attr = _self_attr(tgt0) if tgt0 is not None else None
                    if attr and value is not None:
                        if isinstance(value, ast.Call) and any(
                                kw.arg == "maxlen"
                                for kw in value.keywords):
                            evidence.add(attr)
                        elif name != "__init__":
                            evidence.add(attr)  # re-bound = reset path
                elif isinstance(sub, ast.Delete):
                    for t in sub.targets:
                        if isinstance(t, ast.Subscript):
                            attr = _self_attr(t.value)
                            if attr:
                                evidence.add(attr)
                elif isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute):
                    if isinstance(sub.func.value, ast.Name) and \
                            sub.func.attr in inv.RES_EVICT_ATTRS:
                        # Eviction through a local alias of self.<attr>.
                        evidence.update(
                            aliases.get(sub.func.value.id, ()))
                    attr = _self_attr(sub.func.value)
                    if attr is None:
                        # self.X.setdefault(k, []).append(v) — receiver
                        # is itself a call on self.X.
                        base = sub.func.value
                        if isinstance(base, ast.Call) and \
                                isinstance(base.func, ast.Attribute):
                            attr = _self_attr(base.func.value)
                    if attr is None:
                        continue
                    a = sub.func.attr
                    if a in inv.RES_EVICT_ATTRS:
                        evidence.add(attr)
                    elif in_reaper:
                        evidence.add(attr)
                    elif a in ("append", "add", "appendleft", "insert",
                               "setdefault", "update") and in_hot:
                        growth.setdefault(attr, sub)
                # len(self.X) anywhere = a cap check exists.
                elif isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Name) and \
                        sub.func.id == "len" and sub.args:
                    attr = _self_attr(sub.args[0])
                    if attr:
                        evidence.add(attr)
        for attr, node in sorted(growth.items()):
            if attr in evidence:
                continue
            self._emit(
                "unbounded-registry-growth", node,
                f"self.{attr} grows from an RPC handler / long-lived "
                "loop with no eviction, maxlen, cap check, or reaper "
                "evidence anywhere in this class — it leaks one entry "
                "per request forever (the _local_objects / return-"
                "lease-memo shape); bound it or reap it")

    # --------------------------------------------- thread stop lifecycle

    def _check_thread_stop(self, cls: ast.ClassDef) -> None:
        """Daemon Thread/Timer attrs must be joined/cancelled — or a
        stop event set — on a path REACHABLE from the class's
        stop/close/shutdown. A join in an unrelated method passes PR
        5's daemon-no-join but still leaves teardown unordered."""
        methods = {m.name: m for m in cls.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        stop_roots = [n for n in methods if n in inv.RES_STOP_METHOD_NAMES]
        if not stop_roots:
            return  # no teardown surface: daemon-no-join covers it
        threads: List[Tuple[str, ast.AST, str]] = []  # (attr, node, kind)
        for sub in ast.walk(cls):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                tgt = sub.targets[0]
                attr = _self_attr(tgt)
                if attr is None or not isinstance(sub.value, ast.Call):
                    continue
                fn = _dotted(sub.value.func) or ""
                if fn.endswith("Timer"):
                    threads.append((attr, sub, "Timer"))
                elif fn.endswith("Thread"):
                    for kw in sub.value.keywords:
                        if kw.arg == "daemon" and \
                                isinstance(kw.value, ast.Constant) and \
                                kw.value.value is True:
                            threads.append((attr, sub, "Thread"))
        if not threads:
            return
        reachable: Set[str] = set(stop_roots)
        frontier = list(stop_roots)
        while frontier:
            name = frontier.pop()
            if name not in methods:
                continue
            for callee in self._method_self_calls(methods[name]):
                if callee in methods and callee not in reachable:
                    reachable.add(callee)
                    frontier.append(callee)
        joined: Set[str] = set()
        stop_evented = False
        for name in reachable:
            for sub in _walk_no_nested(
                    ast.iter_child_nodes(methods[name])):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute):
                    attr = _self_attr(sub.func.value)
                    if attr is not None:
                        if sub.func.attr in ("join", "cancel"):
                            joined.add(attr)
                        elif sub.func.attr == "set" and \
                                inv.RES_STOP_EVENT_NAME_RE.search(attr):
                            stop_evented = True
        for attr, node, kind in threads:
            if attr in joined or stop_evented:
                continue
            roots = "/".join(sorted(stop_roots))
            self._emit(
                "thread-without-stop", node,
                f"daemon {kind} self.{attr} is never joined/cancelled "
                f"(and no stop event is set) on any path reachable "
                f"from {roots}() — teardown leaves it running against "
                "freed state")

    # ------------------------------------------------- fd leak on error

    @staticmethod
    def _is_open_call(call: ast.Call) -> Optional[str]:
        dotted = _dotted(call.func)
        if dotted is None:
            return None
        if isinstance(call.func, ast.Name):
            if dotted in inv.RES_OPEN_NAME_CALLS:
                return dotted
            return None
        for suffix in inv.RES_OPEN_CALL_SUFFIXES:
            if dotted == suffix or dotted.endswith("." + suffix):
                return dotted
        return None

    def _check_fd_leak(self, fn) -> None:
        """``name = socket.socket(...)`` / ``name = open(...)`` followed
        by calls that can raise before the handle escapes or closes,
        with no try whose handler/finally closes it: the fd leaks on
        the exception path. ``with`` and immediate escape are fine."""
        # Statement-level linear scan in source order, per open.
        stmts = [s for s in _walk_no_nested(ast.iter_child_nodes(fn))
                 if isinstance(s, ast.stmt)]
        stmts.sort(key=lambda s: (s.lineno, s.col_offset))
        # Protected region: statements inside a Try whose handlers or
        # finally close SOME name — map try node -> closed names.
        protected: Dict[int, Set[str]] = {}  # id(stmt) -> closing names
        for sub in _walk_no_nested(ast.iter_child_nodes(fn)):
            if not isinstance(sub, ast.Try):
                continue
            closing: Set[str] = set()
            for region in ([h.body for h in sub.handlers]
                           + [sub.finalbody]):
                for s in _walk_no_nested(region):
                    if isinstance(s, ast.Call) and \
                            isinstance(s.func, ast.Attribute) and \
                            s.func.attr in inv.RES_CLOSE_ATTRS and \
                            isinstance(s.func.value, ast.Name):
                        closing.add(s.func.value.id)
                    elif isinstance(s, ast.Call) and \
                            isinstance(s.func, ast.Name) and \
                            "shutdown" in s.func.id and s.args and \
                            isinstance(s.args[0], ast.Name):
                        closing.add(s.args[0].id)
            for s in _walk_no_nested(sub.body):
                if isinstance(s, ast.stmt):
                    protected.setdefault(id(s), set()).update(closing)
        opens: List[Tuple[str, ast.Call, int]] = []  # (name, call, idx)
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Assign) and len(s.targets) == 1 and \
                    isinstance(s.targets[0], ast.Name) and \
                    isinstance(s.value, ast.Call):
                desc = self._is_open_call(s.value)
                if desc is not None:
                    opens.append((s.targets[0].id, s.value, i))
        for name, call, idx in opens:
            risky: Optional[ast.AST] = None
            for s in stmts[idx + 1:]:
                text_nodes = list(ast.walk(s))
                mentions = any(isinstance(c, ast.Name) and c.id == name
                               for c in text_nodes)
                closes = any(
                    isinstance(c, ast.Call)
                    and isinstance(c.func, ast.Attribute)
                    and c.func.attr in inv.RES_CLOSE_ATTRS
                    and isinstance(c.func.value, ast.Name)
                    and c.func.value.id == name
                    for c in text_nodes)
                if closes:
                    risky = None
                    break
                escapes = False
                if isinstance(s, ast.Return):
                    if mentions:
                        escapes = True  # ownership goes to the caller
                elif isinstance(s, ast.Assign):
                    for tgt in s.targets:
                        if isinstance(tgt, (ast.Attribute, ast.Subscript)) \
                                and mentions:
                            escapes = True
                        if isinstance(tgt, ast.Name) and tgt.id == name:
                            escapes = True  # rebound; we lose track
                elif mentions:
                    for c in text_nodes:
                        if isinstance(c, ast.Call):
                            for arg in list(c.args) + \
                                    [kw.value for kw in c.keywords]:
                                if any(isinstance(x, ast.Name)
                                       and x.id == name
                                       for x in ast.walk(arg)):
                                    escapes = True
                if escapes:
                    break
                has_call = any(isinstance(c, ast.Call)
                               for c in text_nodes)
                if has_call and name not in protected.get(id(s), set()):
                    risky = risky or s
            if risky is not None:
                self._emit(
                    "fd-leak-on-error", call,
                    f"'{name}' is opened, then line {risky.lineno} can "
                    "raise before it is closed or ownership escapes — "
                    "the fd leaks on the exception path; use `with`, "
                    "or try/except-close-reraise")


def lint_source(source: str, module: str, path: str,
                tree: Optional[ast.AST] = None) -> List[Finding]:
    """Run the res rule family over one module's source. ``tree``
    reuses a caller-side parse (lint_paths parses once per file for
    every family)."""
    return _ResLinter(module, path, source).run(tree)
