"""Runtime RPC-contract witness (``RTPU_DEBUG_RPC=1``) — the dynamic
half of the ``dist`` rtpu-lint rule family, mirroring ``jax_debug.py``
and ``lock_debug.py``: zero overhead when the flag is off, and when on
it turns the protocol's declared retry/idempotency contract
(``protocol.READONLY_RPCS`` / ``IDEMPOTENT_RPCS`` / ``ACKED_RETRY_RPCS``
/ ``NON_RETRYABLE_RPCS``) into observable, assertable facts:

- **Classification hole** (:func:`dispatch_audit`): every method a
  server actually dispatches must appear in one of the declared sets.
  An unclassified method fails its RPC loudly (``UnclassifiedRpcError``
  back to the caller, ``RTPU_DEBUG_RPC:`` line on the server) instead
  of silently riding whatever retry semantics the caller assumed — the
  exact "RETRY_SAFE_RPCS += ... as a review afterthought" failure mode
  PRs 8-10 shipped.
- **Duplicate-delivery audit**: requests for methods in
  ``IDEMPOTENT_RPCS`` are delivered TWICE (second delivery after the
  first completes — the lost-ack-then-retry shape) and the two
  responses must be equivalent: a mismatch means the handler's dedup
  key / state check does not actually make re-delivery a no-op, which
  is precisely what ROADMAP item 3's WAL replay and re-delivery would
  silently corrupt. Read-only and acked-retry methods are exempt by
  classification (their responses may legitimately differ).
- **Outbox ordering witness** (:func:`stamp_outbox` /
  :func:`check_outbox`): object-directory ``object_batch`` frames are
  stamped with a per-(sender, receiver) sequence number at the sending
  outbox and checked monotonic at the receiver — a reordered,
  re-delivered, or outbox-bypassing add/remove frame (the PR 4 round-2
  inversion) is caught at the moment it arrives.

Violations are recorded in a process-local registry (:func:`violations`)
and printed as ``RTPU_DEBUG_RPC:`` lines; chaos scenarios and the bench
assert the registry (and the cluster logs) stay empty. With
``RTPU_DEBUG_RPC`` unset every hook is one env read returning
``None``/its input untouched — the dispatch path is byte-identical to a
build without this module.

Knobs:
  RTPU_DEBUG_RPC=1            enable the witness
  RTPU_DEBUG_RPC_DUP_NTH=N    duplicate every Nth idempotent request
                              (default 1 = every one; 0 disables the
                              duplicate-delivery audit only)
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple


def enabled() -> bool:
    return os.environ.get("RTPU_DEBUG_RPC", "") == "1"


def _dup_nth() -> int:
    try:
        return int(os.environ.get("RTPU_DEBUG_RPC_DUP_NTH", "1"))
    except ValueError:
        return 1


class UnclassifiedRpcError(RuntimeError):
    """A dispatched method is in neither RETRY_SAFE_RPCS (any group)
    nor NON_RETRYABLE_RPCS — its retry semantics are undeclared."""


#: IDEMPOTENT_RPCS members whose duplicate is effect-idempotent but
#: whose RESPONSE intentionally reports information a re-delivery
#: cannot observe. Kept deliberately tiny; every entry needs a reason.
DUP_RESPONSE_EXEMPT = {
    # Response is "did the key exist" — a duplicate of a successful
    # delete correctly reports False; the deletion itself is a no-op.
    "kv_del",
}

#: IDEMPOTENT_RPCS members the audit does NOT double-deliver: whole
#: object transfers whose duplicate costs a full re-copy and whose
#: outcome legitimately tracks concurrent peer liveness (under chaos
#: SIGKILLs the two deliveries can truthfully answer differently).
#: Their re-delivery safety ("local copy already present" fast path)
#: is covered by the chaos scenarios' real retries instead.
DUP_INJECT_SKIP = {
    "pull_object", "pull_direct", "push_object",
}


class _Registry:
    """Process-global witness state."""

    def __init__(self):
        self._mu = threading.Lock()
        self.violations: List[dict] = []
        self.dup_checked: Dict[str, int] = {}   # method -> dups injected
        self._dup_calls: Dict[str, int] = {}    # method -> calls seen
        self.send_seq: Dict[str, int] = {}      # sender -> last seq sent
        # (sender, receiver) -> highest seq accepted
        self.recv_seq: Dict[Tuple[str, str], int] = {}

    def note_violation(self, kind: str, message: str, **fields) -> None:
        rec = {"kind": kind, "message": message}
        rec.update(fields)
        with self._mu:
            self.violations.append(rec)
        print(f"RTPU_DEBUG_RPC: {message}", flush=True)

    def should_dup(self, method: str) -> bool:
        nth = _dup_nth()
        if nth <= 0:
            return False
        with self._mu:
            n = self._dup_calls.get(method, 0) + 1
            self._dup_calls[method] = n
            return n % nth == 0

    def note_dup(self, method: str) -> None:
        with self._mu:
            self.dup_checked[method] = self.dup_checked.get(method, 0) + 1

    def reset(self) -> None:
        with self._mu:
            self.violations.clear()
            self.dup_checked.clear()
            self._dup_calls.clear()
            self.send_seq.clear()
            self.recv_seq.clear()


_REGISTRY = _Registry()


def violations() -> List[dict]:
    with _REGISTRY._mu:
        return [dict(v) for v in _REGISTRY.violations]


def dup_audit_counts() -> Dict[str, int]:
    """How many duplicate deliveries were injected, per method."""
    with _REGISTRY._mu:
        return dict(_REGISTRY.dup_checked)


def reset() -> None:
    """Clear the witness registry (tests isolate scenarios with this)."""
    _REGISTRY.reset()


# ------------------------------------------------------------- dispatch


def _sets():
    # Deferred: protocol imports this module at its top level.
    from ray_tpu.cluster import protocol as _p

    return (_p.RETRY_SAFE_RPCS, _p.IDEMPOTENT_RPCS, _p.NON_RETRYABLE_RPCS)


def _canonical(value: Any) -> Any:
    """A comparable form of a handler response: serialized header bytes
    plus raw buffer bytes (covers numpy arrays, PickleBuffer views, shm
    memoryviews). Falls back to ``==``-comparable passthrough."""
    from ray_tpu.core.serialization import SERIALIZER

    header, buffers = SERIALIZER.serialize(value)
    return (bytes(header),
            [bytes(memoryview(b).cast("B")) for b in buffers])


def _equivalent(a: Any, b: Any) -> bool:
    try:
        return _canonical(a) == _canonical(b)
    except Exception:  # noqa: BLE001 — the witness must never break the
        try:           # call it observes; degrade to weaker comparisons
            return bool(a == b)
        except Exception:  # noqa: BLE001
            return repr(a) == repr(b)


def dispatch_audit(method: str,
                   handler_obj: Any = None) -> Optional[Callable]:
    """Per-dispatch audit hook. Returns None when the witness is off
    (the server's dispatch then runs the handler directly — unwrapped);
    when on, returns ``audit(fn, conn, args)`` which enforces the
    classification contract and injects duplicate delivery for
    idempotent methods.

    Server classes OUTSIDE the cluster control plane (test fixtures,
    future plugin servers) declare their methods locally instead of
    growing protocol.py's sets: class attributes
    ``extra_retry_safe_rpcs`` / ``extra_idempotent_rpcs`` /
    ``extra_non_retryable_rpcs`` (the ``dist`` lint family honors the
    same declarations)."""
    if not enabled():
        return None
    retry_safe, idempotent, non_retryable = _sets()
    if handler_obj is not None:
        retry_safe = retry_safe | frozenset(
            getattr(handler_obj, "extra_retry_safe_rpcs", ()))
        extra_idem = frozenset(
            getattr(handler_obj, "extra_idempotent_rpcs", ()))
        idempotent = idempotent | extra_idem
        retry_safe = retry_safe | extra_idem
        non_retryable = non_retryable | frozenset(
            getattr(handler_obj, "extra_non_retryable_rpcs", ()))
    if method not in retry_safe and method not in non_retryable:
        _REGISTRY.note_violation(
            "classification-hole",
            f"dispatched method '{method}' is in neither RETRY_SAFE_RPCS "
            "nor NON_RETRYABLE_RPCS — declare its retry semantics in "
            "cluster/protocol.py (unclassified-rpc-handler)",
            method=method)

        def refuse(fn, conn, args):
            raise UnclassifiedRpcError(
                f"rpc method '{method}' has no declared retry/idempotency "
                "classification (see cluster/protocol.py)")

        return refuse
    if method not in idempotent or method in DUP_INJECT_SKIP:
        return None  # classified; nothing further to audit per-call

    def audit(fn, conn, args):
        result = fn(conn, *args)
        if not _REGISTRY.should_dup(method):
            return result
        # Duplicate delivery: the lost-ack-then-retry shape — the same
        # request arrives again AFTER the first delivery completed.
        _REGISTRY.note_dup(method)
        try:
            dup = fn(conn, *args)
        except Exception as e:  # noqa: BLE001 — a raising duplicate IS
            _REGISTRY.note_violation(  # the reported defect
                "dup-raised",
                f"duplicate delivery of idempotent '{method}' raised "
                f"{e!r} where the first delivery succeeded — the "
                "handler's dedup does not tolerate re-delivery",
                method=method)
            return result
        # BufferLease duplicates borrow pinned memory: compare the
        # value, then release the duplicate's pin (the original lease
        # flows onward to the response path as usual).
        from ray_tpu.cluster.protocol import BufferLease

        r_val = result.value if isinstance(result, BufferLease) else result
        d_val = dup.value if isinstance(dup, BufferLease) else dup
        try:
            if method not in DUP_RESPONSE_EXEMPT and \
                    not _equivalent(r_val, d_val):
                _REGISTRY.note_violation(
                    "dup-mismatch",
                    f"duplicate delivery of idempotent '{method}' "
                    f"returned a different response ({_clip(r_val)} vs "
                    f"{_clip(d_val)}) — at-most-once is not actually "
                    "held by its dedup key/state check",
                    method=method)
        finally:
            if isinstance(dup, BufferLease):
                dup.release()
        return result

    return audit


def _clip(v: Any, limit: int = 80) -> str:
    s = repr(v)
    return s if len(s) <= limit else s[: limit - 3] + "..."


# ------------------------------------------------------- outbox ordering

#: Marker entry prepended to a stamped object_batch frame. Shaped like a
#: real ("kind", oid, size) entry so an unmatched receiver (which cannot
#: happen with inherited env, but defensively) degrades harmlessly.
SEQ_KIND = "__rtpu_dbg_seq__"


def stamp_outbox(sender: str, entries: list) -> list:
    """Prepend a per-sender sequence entry to an outbox frame (no-op
    when the witness is off or the frame is empty). ``sender`` must be
    stable for the life of the sending process (owner address, node
    id); a respawned process is a new sender."""
    if not enabled() or not entries:
        return entries
    with _REGISTRY._mu:
        n = _REGISTRY.send_seq.get(sender, 0) + 1
        _REGISTRY.send_seq[sender] = n
    return [(SEQ_KIND, sender, n)] + list(entries)


def check_outbox(receiver: str, entries: list) -> list:
    """Strip sequence entries from a received outbox frame, asserting
    per-(sender, receiver) monotonicity: a frame arriving with a
    sequence number at or below the last accepted one was re-delivered
    or reordered — an add/remove inversion waiting to corrupt the
    directory. A frame carrying NO stamp at all is a violation too:
    with the witness on, every designated outbox sender stamps (the
    env is inherited process-tree-wide), so an unstamped frame came
    from a path that bypassed the outbox — the PR 4 bug class, caught
    on arrival. Returns the frame without the marker entries."""
    if not entries:
        return entries
    if enabled():
        try:
            stamped = any(e and e[0] == SEQ_KIND for e in entries)
        except Exception:  # noqa: BLE001 — malformed entries are the
            stamped = True  # receiver's problem, not the witness's
        if not stamped:
            _REGISTRY.note_violation(
                "outbox-unstamped",
                f"outbox frame arrived at '{receiver}' with no "
                "sequence stamp — it was sent outside the designated "
                "outbox sender (direct-notify-bypasses-outbox, the "
                "PR 4 stale-directory bug class)",
                receiver=receiver)
    out = []
    for e in entries:
        try:
            is_seq = e[0] == SEQ_KIND
        except Exception:  # noqa: BLE001 — malformed entries are the
            is_seq = False  # receiver's problem, not the witness's
        if not is_seq:
            out.append(e)
            continue
        _, sender, n = e
        inverted = False
        with _REGISTRY._mu:
            # pop + reinsert = move-to-end: eviction below is LRU by
            # last frame, not insertion order (a dict updated in place
            # keeps its original position, so plain FIFO would evict
            # the busiest LIVE streams — inserted at cluster start —
            # while dead respawned senders survived).
            last = _REGISTRY.recv_seq.pop((sender, receiver), None)
            if last is not None and n <= last:
                inverted = True
            _REGISTRY.recv_seq[(sender, receiver)] = max(n, last or 0)
            # Bounded: every respawned peer is a NEW sender (that is
            # the point of the per-incarnation stream), so a long
            # chaos run accretes dead-sender entries forever — the
            # exact unbounded-registry-growth shape this repo's res
            # lint family polices. Evict the least-recently-heard-from
            # stream (a dead sender, by construction); losing its
            # high-water mark can only relax a check, never fabricate
            # a violation.
            while len(_REGISTRY.recv_seq) > 4096:
                _REGISTRY.recv_seq.pop(next(iter(_REGISTRY.recv_seq)))
        if inverted:
            _REGISTRY.note_violation(
                "outbox-inversion",
                f"outbox frame from '{sender}' arrived at '{receiver}' "
                f"with seq {n} <= last accepted {last} — frames were "
                "reordered or re-delivered (add/remove inversion "
                "hazard)",
                sender=sender, receiver=receiver, seq=n, last=last)
    return out
