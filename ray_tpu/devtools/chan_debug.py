"""Runtime channel-protocol witness (``RTPU_DEBUG_CHAN=1``) — the
dynamic half of the ``chan`` rtpu-lint rule family, mirroring
``rpc_debug.py`` / ``res_debug.py``: zero overhead when the flag is
off, and when on it checks the frame-stream invariants every channel
transport (``dag/ring.py`` shm rings, ``dag/peer.py`` peer sockets)
promises, ONLINE, per edge endpoint:

- **seq discipline** — a writer's seqs are gapless and duplicate-free
  (``note_send``), a consumer's arrive in order (``note_consume``).
  The static side is chan-raw-seq-send: sends that bypass the auto-seq
  facades are exactly how a gap ships.
- **credit accounting** — a send admitted while more than ``capacity``
  messages are unacked/unconsumed overran the credit window
  (``note_send(window=...)``); an ack for a seq the application never
  consumed is a phantom credit (``note_ack``).
- **cursor monotonicity** — ring wpos/rpos only ever advance
  (``note_cursor``); a regression means a torn or reordered publish.
- **frame checksums** — every ``SAMPLE_EVERY``-th frame carries a crc32
  of its payload, computed at send and recomputed at consume. A
  mismatch is a torn read or a writer that mutated the buffer after
  handing it to the transport (the chan-mutate-after-send race,
  observed empirically).
- **Lamport clocks** — every frame carries the sending process's
  Lamport stamp; consumers merge it and require per-edge monotonicity,
  so a frame reordered against its own stream (the PR 4
  object_batch add/remove-inversion class) is caught even when seqs
  were re-minted.
- **spill pin/reclaim pairing** — a ring spill side-file pinned at
  send must be settled once its record's consumption is observed;
  ``note_close`` flags any pin whose record the reader already
  consumed (end_pos <= rpos) that was never settled — the exact PR 19
  ``_spill_in`` reclaim race shape, caught at writer close instead of
  as a reader FileNotFoundError.

Violations print one ``RTPU_CHAN:`` line each (plus a compact registry
report) and are queryable via :func:`violations`; the per-process
summary rides every flight-recorder dump (``"chan_debug"`` key) so
``bench.py --chaos`` aggregates a cluster-wide ``chan_violations``
verdict over the same ``dump_flight`` RPC the other witnesses use.

With ``RTPU_DEBUG_CHAN`` unset every hook is one env read returning
its input untouched, and the transports skip the hook blocks entirely
— frame headers carry zeros in the clock/crc fields.

Knobs:
  RTPU_DEBUG_CHAN=1  enable the witness (inherited by every spawned
                     cluster process, like the other RTPU_DEBUG_ flags)
"""

from __future__ import annotations

import os
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

#: Sample the payload checksum on every Nth seq per edge (seq % N == 0).
#: A full crc32 on every frame would eat the <5% witness-overhead
#: budget on a ~26us ring hop; sampling keeps the empirical
#: mutate-after-send/torn-read check while staying off the hot cost.
SAMPLE_EVERY = 16

_CRC_MASK = 0xFFFFFFFF


def enabled() -> bool:
    return os.environ.get("RTPU_DEBUG_CHAN", "") == "1"


class _Registry:
    """Process-global per-edge frame-stream state. Keys are ENDPOINT
    tokens (edge name + object id), not bare edge names: a process that
    reopens a channel under the same edge restarts its seqs at 0, and
    the two streams must not be conflated."""

    def __init__(self):
        self._mu = threading.Lock()
        self.clock = 0  # process Lamport clock (merged on consume)
        self.frames = 0
        # endpoint token -> stream state
        self.edges: Dict[str, Dict[str, Any]] = {}
        # (endpoint token, spill path) -> record end_pos
        self.pins: Dict[Tuple[str, str], int] = {}
        self.violations: List[dict] = []

    def edge(self, tok: str) -> Dict[str, Any]:
        st = self.edges.get(tok)
        if st is None:
            st = self.edges[tok] = {"sent": -1, "consumed": -1,
                                    "acked": -1, "clock_seen": 0}
        return st

    def note_violation(self, kind: str, edge: str, message: str,
                       **fields) -> None:
        rec = {"kind": kind, "edge": edge, "message": message}
        rec.update(fields)
        with self._mu:
            self.violations.append(rec)
            st = dict(self.edges.get(edge, {}))
        print(f"RTPU_CHAN: [{kind}] {edge}: {message} (edge state {st})",
              flush=True)

    def reset(self) -> None:
        with self._mu:
            self.clock = 0
            self.frames = 0
            self.edges.clear()
            self.pins.clear()
            self.violations.clear()


_REGISTRY = _Registry()


# ------------------------------------------------------------ frame hooks


def clock_stamp(edge: str) -> int:
    """Writer-side Lamport stamp for the next frame; 0 when off (the
    header field ships 0 and consumers skip the check)."""
    if not enabled():
        return 0
    with _REGISTRY._mu:
        _REGISTRY.clock += 1
        return _REGISTRY.clock


def payload_crc(seq: int, *parts) -> int:
    """Sampled frame checksum: crc32 over the payload parts on every
    SAMPLE_EVERY-th seq, else 0 ("not sampled"). A real crc of 0 maps
    to 1 so 0 stays unambiguous. Returns 0 when off."""
    if not enabled() or seq % SAMPLE_EVERY:
        return 0
    c = 0
    for p in parts:
        c = zlib.crc32(p, c)
    return (c & _CRC_MASK) or 1


def note_send(edge: str, seq: int, nbytes: int,
              window: Optional[Tuple[int, int]] = None) -> None:
    """One frame handed to the transport. ``window=(floor, capacity)``
    is the writer's credit view (ring: read_seq; peer: acked seq) —
    admission more than ``capacity`` past the floor overran the
    window."""
    if not enabled():
        return
    gap = dup = False
    with _REGISTRY._mu:
        _REGISTRY.frames += 1
        st = _REGISTRY.edge(edge)
        last_sent = st["sent"]
        if last_sent >= 0 and seq != last_sent + 1:
            dup = seq <= last_sent
            gap = not dup
        if seq > st["sent"]:
            st["sent"] = seq
    if dup:
        _REGISTRY.note_violation(
            "send-seq-duplicate", edge,
            f"seq {seq} re-sent (stream already at {last_sent}) — a "
            "duplicate frame on an SPSC stream (route sends through "
            "the ChannelWriter facade)", seq=seq)
    elif gap:
        _REGISTRY.note_violation(
            "send-seq-gap", edge,
            f"seq {seq} sent after a gap — the stream skipped at least "
            "one seq (a raw-seq send bypassed the auto-seq facade)",
            seq=seq)
    if window is not None:
        floor, cap = window
        if seq - floor > cap:
            _REGISTRY.note_violation(
                "credit-overrun", edge,
                f"seq {seq} admitted {seq - floor} past the consumption "
                f"floor {floor} (capacity {cap}) — a send bypassed the "
                "credit window", seq=seq, floor=floor, capacity=cap)


def note_consume(edge: str, seq: int, clock: int, crc: int,
                 *parts) -> None:
    """One frame consumed by the application. Recomputes the sampled
    checksum and checks seq + Lamport-clock monotonicity."""
    if not enabled():
        return
    if crc:
        c = 0
        for p in parts:
            c = zlib.crc32(p, c)
        c = (c & _CRC_MASK) or 1
        if c != crc:
            _REGISTRY.note_violation(
                "payload-mismatch", edge,
                f"seq {seq}: payload crc at consume ({c:#x}) != crc at "
                f"send ({crc:#x}) — a torn read, or the writer mutated "
                "the buffer after handing it to the transport "
                "(chan-mutate-after-send)", seq=seq)
    gap = inversion = clock_bad = False
    with _REGISTRY._mu:
        st = _REGISTRY.edge(edge)
        if st["consumed"] >= 0 and seq != st["consumed"] + 1:
            inversion = seq <= st["consumed"]
            gap = not inversion
        if seq > st["consumed"]:
            st["consumed"] = seq
        if clock:
            if clock <= st["clock_seen"]:
                clock_bad = True
            else:
                st["clock_seen"] = clock
            if clock > _REGISTRY.clock:  # Lamport merge
                _REGISTRY.clock = clock
    if inversion:
        _REGISTRY.note_violation(
            "consume-seq-inversion", edge,
            f"seq {seq} consumed after the stream already passed it — "
            "re-delivery or inversion on an SPSC stream", seq=seq)
    elif gap:
        _REGISTRY.note_violation(
            "consume-seq-gap", edge,
            f"seq {seq} consumed after a gap — at least one frame was "
            "lost or skipped", seq=seq)
    if clock_bad:
        _REGISTRY.note_violation(
            "clock-inversion", edge,
            f"seq {seq} carries Lamport clock {clock} <= the edge's "
            "last observed stamp — frames reordered against their own "
            "send order (the PR 4 add/remove-inversion class)",
            seq=seq, clock=clock)


def note_ack(edge: str, seq: int) -> None:
    """A consumption ack leaving this endpoint: acking past the last
    application consume mints phantom credit."""
    if not enabled():
        return
    bad = False
    with _REGISTRY._mu:
        st = _REGISTRY.edge(edge)
        if seq > st["consumed"]:
            bad = True
        if seq > st["acked"]:
            st["acked"] = seq
    if bad:
        _REGISTRY.note_violation(
            "ack-before-consume", edge,
            f"seq {seq} acked before the application consumed it — the "
            "credit window no longer bounds unconsumed frames",
            seq=seq)


def note_cursor(edge: str, name: str, value: int) -> None:
    """A ring cursor publish (wpos/rpos). Cursors are monotonic byte
    counters; a regression means a torn or reordered publish."""
    if not enabled():
        return
    bad = last = None
    with _REGISTRY._mu:
        st = _REGISTRY.edge(edge)
        key = "cur_" + name
        last = st.get(key, -1)
        if value < last:
            bad = True
        else:
            st[key] = value
    if bad:
        _REGISTRY.note_violation(
            "cursor-regression", edge,
            f"{name} published {value} after {last} — ring cursors "
            "only advance (publish-before-fill or a reordered store)",
            cursor=name, value=value, last=last)


# ------------------------------------------------------------ spill pins


def note_spill_pin(edge: str, path: str, end_pos: int) -> None:
    """A ring spill side-file pinned at send; ``end_pos`` is its ring
    record's end cursor (consumption is observable as rpos >= end_pos)."""
    if not enabled():
        return
    with _REGISTRY._mu:
        _REGISTRY.pins[(edge, path)] = end_pos


def note_spill_release(edge: str, path: str) -> None:
    """The pin settled (consumption observed, or legitimately reclaimed
    as stranded at close). Idempotent, unknown pins ignored."""
    if not enabled():
        return
    with _REGISTRY._mu:
        _REGISTRY.pins.pop((edge, path), None)


def note_close(edge: str, rpos: int) -> None:
    """Writer close: a pin whose record the reader ALREADY consumed
    (end_pos <= rpos) but that was never settled means the writer is
    about to reclaim — or already failed to settle — a spill the
    consumption path raced (the PR 19 ``_spill_in`` shape)."""
    if not enabled():
        return
    with _REGISTRY._mu:
        stale = [(path, end) for (e, path), end in _REGISTRY.pins.items()
                 if e == edge and end <= rpos]
    for path, end in stale:
        _REGISTRY.note_violation(
            "spill-reclaim-race", edge,
            f"spill {os.path.basename(path)} consumed by the reader "
            f"(record end {end} <= rpos {rpos}) but never settled — "
            "writer close would reclaim a file the reader's _spill_in "
            "may still open (the pre-PR-19 race)", path=path)


# -------------------------------------------------------------- queries


def violations() -> List[dict]:
    with _REGISTRY._mu:
        return [dict(v) for v in _REGISTRY.violations]


def frames_witnessed() -> int:
    with _REGISTRY._mu:
        return _REGISTRY.frames


def reset() -> None:
    """Clear the witness registry (tests isolate scenarios with this)."""
    _REGISTRY.reset()


def report() -> Dict[str, Any]:
    """Compact per-edge registry report (tests and the bench print
    this next to a violation verdict)."""
    with _REGISTRY._mu:
        return {
            "edges": {tok: dict(st)
                      for tok, st in _REGISTRY.edges.items()},
            "pins": len(_REGISTRY.pins),
            "frames": _REGISTRY.frames,
            "clock": _REGISTRY.clock,
            "violations": len(_REGISTRY.violations),
        }


def dump_payload() -> Dict[str, Any]:
    """The snapshot riding ``flight_recorder.dump_payload`` under the
    ``"chan_debug"`` key: enough for bench.py --chaos to aggregate a
    cluster-wide chan_violations verdict (frames_witnessed is the
    coverage evidence — a 0-violation verdict over 0 frames is
    vacuous)."""
    with _REGISTRY._mu:
        return {
            "frames": _REGISTRY.frames,
            "edges": len(_REGISTRY.edges),
            "open_pins": len(_REGISTRY.pins),
            "violations": len(_REGISTRY.violations),
        }
