"""Runtime JAX witness (``RTPU_DEBUG_JAX=1``) — the dynamic half of the
jax-lint rule family, mirroring ``lock_debug.py``'s design: zero
overhead when the flag is off, and when on it turns the model path's
implicit performance contracts into observable, assertable facts:

- **Recompile witness** (:func:`wrap_jit`): wraps a jitted callable,
  counts DISTINCT call signatures (pytree structure + per-leaf
  shape/dtype), and reports when a function exceeds its declared
  program budget. Steady-state decode compiles ONE chunk program and
  one prefill program per prompt bucket; a silent retrace per tick is
  the single most expensive way to lose that (and invisible without
  this — the step still returns correct numbers, just 100x slower).
- **Host-sync counter** (:func:`note_host_sync`): the engine's counted
  device->host fetch points call it, so tests can assert decode does
  EXACTLY one sync per chunk — spec-on and spec-off (PAPER.md's
  core-worker hot-path discipline applied to the decode loop).
- **Transfer guard** (:func:`transfer_guard` / :func:`tick_guard`):
  wires ``jax.transfer_guard`` as a context manager. Under
  ``RTPU_DEBUG_JAX_TRANSFER_GUARD=disallow`` the engine runs every
  tick inside the guard: all device traffic must be EXPLICIT
  (``device_put``/``device_get``) — a stray ``np.asarray`` or a python
  scalar leaking into a dispatch raises instead of silently syncing.

With ``RTPU_DEBUG_JAX`` unset, :func:`wrap_jit` returns the function
untouched and every hook is a dict-lookup no-op — the flag-off decode
path is byte-identical to a build without this module.
"""

from __future__ import annotations

import contextlib
import os
import threading
import weakref
from typing import Dict, List, Optional


def enabled() -> bool:
    return os.environ.get("RTPU_DEBUG_JAX", "") == "1"


def guard_level() -> str:
    """Transfer-guard level for :func:`tick_guard` ("" = off). Valid
    jax levels: "log", "disallow", "log_explicit", "disallow_explicit".
    """
    return os.environ.get("RTPU_DEBUG_JAX_TRANSFER_GUARD", "")


class _Registry:
    """Process-global witness state (host syncs + live jit wrappers)."""

    def __init__(self):
        self._mu = threading.Lock()
        self.syncs: Dict[str, int] = {}
        # Weak refs: a witness (and the jitted closure + trace cache +
        # XLA executables it holds) must die with its engine/step — a
        # strong registry would leak one program set per engine built
        # over a long RTPU_DEBUG_JAX=1 session.
        self.witnesses: List["weakref.ref[JitWitness]"] = []
        self.over_budget: List[dict] = []

    def note_sync(self, tag: str) -> None:
        with self._mu:
            self.syncs[tag] = self.syncs.get(tag, 0) + 1

    def add_witness(self, w: "JitWitness") -> None:
        with self._mu:
            self.witnesses.append(weakref.ref(w))

    def live_witnesses(self) -> List["JitWitness"]:
        """Live witnesses; dead refs are pruned as a side effect.
        Caller must hold ``_mu``."""
        out: List[JitWitness] = []
        keep = []
        for ref in self.witnesses:
            w = ref()
            if w is not None:
                out.append(w)
                keep.append(ref)
        self.witnesses[:] = keep
        return out

    def note_over_budget(self, report: dict) -> None:
        with self._mu:
            self.over_budget.append(report)
        print(f"RTPU_DEBUG_JAX: {report['message']}", flush=True)

    def reset(self) -> None:
        with self._mu:
            self.syncs.clear()
            self.witnesses.clear()
            self.over_budget.clear()


_REGISTRY = _Registry()


def _signature(args, kwargs) -> tuple:
    """Trace-cache key of a call: pytree structure + per-leaf
    (shape, dtype); non-array leaves key by type (their VALUES do not
    retrace — their structure does)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype)))
        else:
            sig.append(type(leaf).__name__)
    # PyTreeDef is hashable — keying on the object (not its str, which
    # serializes the whole params tree per call) keeps the witness
    # cheap enough to leave on during the bench's timed region.
    return (treedef, tuple(sig))


class JitWitness:
    """A jitted callable under observation: every call records its
    signature; crossing ``budget`` distinct signatures is reported once
    (the steady-state program count is a declared invariant, not a
    vibe). Transparent passthrough otherwise."""

    def __init__(self, fn, name: str, budget: Optional[int] = None):
        self._fn = fn
        self.name = name
        self.budget = budget
        self.__name__ = getattr(fn, "__name__", name)
        self._sigs: set = set()
        self._reported = False
        _REGISTRY.add_witness(self)

    def __call__(self, *args, **kwargs):
        try:
            sig = _signature(args, kwargs)
        except Exception:  # noqa: BLE001 — the witness must never break
            sig = None     # the call it observes
        if sig is not None and sig not in self._sigs:
            self._sigs.add(sig)
            if (self.budget is not None and not self._reported
                    and len(self._sigs) > self.budget):
                self._reported = True
                _REGISTRY.note_over_budget({
                    "name": self.name,
                    "budget": self.budget,
                    "programs": len(self._sigs),
                    "message": (
                        f"'{self.name}' compiled {len(self._sigs)} "
                        f"distinct programs, budget is {self.budget} — "
                        "an argument's shape/dtype/structure churns "
                        "per call (steady state should hit the trace "
                        "cache every time)"),
                })
        return self._fn(*args, **kwargs)

    @property
    def program_count(self) -> int:
        return len(self._sigs)

    def __getattr__(self, item):
        return getattr(self._fn, item)

    def __repr__(self) -> str:
        return (f"<JitWitness {self.name} programs={len(self._sigs)} "
                f"budget={self.budget}>")


def wrap_jit(fn, name: str, budget: Optional[int] = None):
    """Witness-wrap a jitted callable under ``RTPU_DEBUG_JAX=1``;
    return it UNTOUCHED otherwise (zero overhead off). ``budget`` is
    the declared steady-state program count (None = count only)."""
    if not enabled():
        return fn
    return JitWitness(fn, name, budget)


def note_host_sync(tag: str) -> None:
    """Count one device->host sync at a named point (no-op when the
    witness is off)."""
    if enabled():
        _REGISTRY.note_sync(tag)


def host_sync_counts() -> Dict[str, int]:
    with _REGISTRY._mu:
        return dict(_REGISTRY.syncs)


def program_counts() -> Dict[str, int]:
    """Aggregated distinct-program counts per LIVE wrapper name
    (summed over instances — one engine = one instance per program;
    a closed, collected engine's witnesses drop out)."""
    with _REGISTRY._mu:
        out: Dict[str, int] = {}
        for w in _REGISTRY.live_witnesses():
            out[w.name] = out.get(w.name, 0) + w.program_count
        return out


def over_budget_reports() -> List[dict]:
    with _REGISTRY._mu:
        return [dict(r) for r in _REGISTRY.over_budget]


def reset() -> None:
    """Clear the witness registry (tests isolate scenarios with this).
    Already-wrapped callables keep counting into fresh state only via
    new wrappers; drop engine/step objects alongside."""
    _REGISTRY.reset()


@contextlib.contextmanager
def transfer_guard(level: str = "disallow"):
    """``jax.transfer_guard(level)`` as a reusable context manager —
    used by the witness tests and bench to prove a region's device
    traffic is all explicit. No-op where jax lacks the API."""
    import jax

    tg = getattr(jax, "transfer_guard", None)
    if tg is None:
        yield
        return
    with tg(level):
        yield


def tick_guard():
    """The engine wraps each tick in this: a transfer guard at
    ``RTPU_DEBUG_JAX_TRANSFER_GUARD``'s level when the witness is on,
    else a null context."""
    level = guard_level()
    if not enabled() or not level:
        return contextlib.nullcontext()
    return transfer_guard(level)
