"""chan-lint: channel-protocol rules (rule family ``chan``).

Stdlib-only AST analysis riding rtpu-lint's fingerprint/baseline/
``# rtpu-lint: disable=<rule>`` machinery. The pre-negotiated channel
plane (``dag/ring.py`` shm SPSC rings, ``dag/peer.py`` peer sockets,
the pickle-5 scatter frames both carry) became the hot data path in
PRs 15-19 — and every recent real bug lived there. Every rule
codifies one of those bug classes; the runtime half is
``devtools/chan_debug.py`` (``RTPU_DEBUG_CHAN=1``).

  chan-cursor-publish-order
      a ring writer that publishes the write cursor (``_set_u64``
      with a wpos-flavored offset, or a wpos-named attribute store)
      BEFORE the payload memcpy into the mmap. The SPSC ring's only
      memory-ordering contract is publish-after-fill; a reordered
      publish hands the reader a cursor over garbage bytes.
  chan-spill-pin-unreleased
      a teardown path (close/stop/shutdown/...) that unlinks spill
      side-files with no consumption evidence (settle helper, rpos
      check, reclaim grace, rename-claim) in the function — the exact
      PR 19 ``_spill_in`` race: writer close reclaimed a file the
      reader was still opening.
  chan-ack-before-consume
      a reader that sends the consumption ack BEFORE the application
      dequeues the frame from the inbox — the credit window then
      bounds socket receipt, not application consumption, and a slow
      consumer overruns its own bounded inbox.
  chan-raw-seq-send
      a ``write``/``write_error``/``write_stop`` carrying an explicit
      seq on a channel-ish receiver outside the auto-seq facades
      (``CHAN_SEQ_EXEMPT_MODULES``): hand-minted seqs are how gaps
      and duplicates ship (the witness sees them as send-seq-gap).
  chan-register-without-unregister
      a module that RPCs ``channel_register`` but never
      ``channel_unregister`` anywhere: dead channels pin directory
      entries on the head forever and writers dial corpses.
  chan-dial-without-liveness
      a transport class (``CHAN_TRANSPORT_MODULES``) dialing with
      ``create_connection`` and no _GONE/liveness handling anywhere
      in the class: a dial with no death branch spins forever on a
      torn-down reader.
  chan-blocking-op-no-deadline
      a channel ``read``/``recv`` with no timeout argument and no
      deadline evidence in the enclosing function — a dead peer turns
      the caller into a zombie (the channel analog of dist-lint's
      serial-fanout-no-deadline).
  chan-mutate-after-send
      a buffer handed to a channel send and then mutated in the same
      function (subscript store, augmented assign, or a mutating
      method). Sends are zero-copy — pickle-5 out-of-band views and
      ring spills alias the caller's memory, so the mutation races
      the reader's view of the frame. The witness catches surviving
      instances empirically via sampled frame checksums.

``lint_source(source, module, path)`` returns ``lint.Finding`` rows;
module-scoped tables live in ``invariants.py``.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from ray_tpu.devtools import invariants as inv
# CHAN_RULES is single-sourced in lint.py (the family/baseline
# machinery keys on it); aliased here so rule code and rule registry
# can't drift.
from ray_tpu.devtools.lint import (CHAN_RULES as RULES, Finding, _dotted,
                                   suppressed)

_CLOSE_NAME_RE = re.compile(
    r"(close|shutdown|stop|teardown|__exit__|__del__)")
_UNLINK_NAMES = {"unlink", "remove"}
_SEND_ATTRS = {"write", "send"}
_RAW_SEQ_ATTRS = {"write", "write_error", "write_stop"}
_RPC_SEND_ATTRS = {"retrying_call", "call", "notify"}


def _receiver_dotted(func: ast.AST) -> Optional[str]:
    """Dotted form of an attribute-call's receiver, looking through a
    subscript (``self._channels[key].write`` -> ``self._channels``)."""
    if not isinstance(func, ast.Attribute):
        return None
    base = func.value
    d = _dotted(base)
    if d is None and isinstance(base, ast.Subscript):
        d = _dotted(base.value)
    if d is None and isinstance(base, ast.Call):
        d = _dotted(base.func)
    return d


def _channelish(func: ast.AST) -> bool:
    d = _receiver_dotted(func)
    if not d:
        return False
    return any(inv.CHAN_RECEIVER_RE.search(part)
               for part in d.split("."))


class _ChanLinter:
    def __init__(self, module: str, path: str, source: str):
        self.module = module
        self.path = path
        self.lines = source.splitlines()
        self.findings: List[Finding] = []
        self._scope: List[str] = []
        self._fn_stack: List[ast.AST] = []

    # ------------------------------------------------------------ utils

    def _emit(self, rule: str, node: ast.AST, message: str,
              scope: Optional[str] = None) -> None:
        assert rule in RULES, f"unregistered chan rule id {rule!r}"
        line = getattr(node, "lineno", 1)
        if suppressed(self.lines, line, rule):
            return
        self.findings.append(Finding(
            rule, self.path, line,
            scope if scope is not None else ".".join(self._scope),
            message))

    def _src(self, node: ast.AST) -> str:
        lo = getattr(node, "lineno", 1) - 1
        hi = getattr(node, "end_lineno", lo + 1)
        return "\n".join(self.lines[lo:hi])

    # ------------------------------------------------------------- walk

    def run(self, tree: Optional[ast.AST] = None) -> List[Finding]:
        if tree is None:
            try:
                tree = ast.parse("\n".join(self.lines),
                                 filename=self.path)
            except SyntaxError:
                return []  # the concurrency family reports this
        self._check_register_lifecycle(tree)
        self._walk(tree)
        return self.findings

    def _walk(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scope.append(child.name)
                self._fn_stack.append(child)
                self._check_cursor_publish_order(child)
                self._check_spill_pin(child)
                self._check_ack_before_consume(child)
                self._check_mutate_after_send(child)
                self._walk(child)
                self._fn_stack.pop()
                self._scope.pop()
                continue
            if isinstance(child, ast.ClassDef):
                self._scope.append(child.name)
                self._check_dial_liveness(child)
                self._walk(child)
                self._scope.pop()
                continue
            if isinstance(child, ast.Call):
                self._check_raw_seq_send(child)
                self._check_blocking_op(child)
            self._walk(child)

    # --------------------------------------------- cursor publish order

    def _check_cursor_publish_order(
            self, fn: ast.AST) -> None:
        fills: List[int] = []
        pubs: List[Tuple[int, ast.AST]] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    # payload memcpy: mm[a:b] = ...
                    if isinstance(tgt, ast.Subscript):
                        d = _dotted(tgt.value) or ""
                        last = d.rsplit(".", 1)[-1]
                        if inv.CHAN_MM_NAME_RE.search(last):
                            fills.append(node.lineno)
                    # cursor store as attribute: self._wpos = ...
                    elif isinstance(tgt, ast.Attribute) and \
                            inv.CHAN_CURSOR_PUBLISH_RE.search(tgt.attr):
                        pubs.append((node.lineno, node))
            elif isinstance(node, ast.Call):
                d = _dotted(node.func) or ""
                last = d.rsplit(".", 1)[-1]
                if last == "pack_into" and len(node.args) >= 2:
                    arg_d = _dotted(node.args[1]) or ""
                    arg_last = arg_d.rsplit(".", 1)[-1]
                    if inv.CHAN_MM_NAME_RE.search(arg_last):
                        fills.append(node.lineno)
                elif last.endswith("_set_u64") or last == "set_u64":
                    if node.args:
                        off = (_dotted(node.args[0]) or "")
                        if inv.CHAN_CURSOR_PUBLISH_RE.search(off):
                            pubs.append((node.lineno, node))
        if not fills or not pubs:
            return
        first_pub_line, pub_node = min(pubs, key=lambda p: p[0])
        if first_pub_line < max(fills):
            self._emit(
                "chan-cursor-publish-order", pub_node,
                "write cursor published before the payload fill "
                f"completes (publish at line {first_pub_line}, fill at "
                f"line {max(fills)}) — the reader observes a cursor "
                "over garbage bytes; publish AFTER the memcpy")

    # ------------------------------------------------- spill pin pairing

    def _check_spill_pin(self, fn: ast.AST) -> None:
        if not _CLOSE_NAME_RE.search(fn.name):
            return
        touches_spill = any(
            isinstance(n, ast.Attribute)
            and inv.CHAN_SPILL_ATTR_RE.search(n.attr)
            for n in ast.walk(fn))
        if not touches_spill:
            return
        unlinks = [n for n in ast.walk(fn)
                   if isinstance(n, ast.Call)
                   and (_dotted(n.func) or "").rsplit(".", 1)[-1]
                   in _UNLINK_NAMES]
        if not unlinks:
            return
        if inv.CHAN_SETTLE_EVIDENCE_RE.search(self._src(fn)):
            return
        self._emit(
            "chan-spill-pin-unreleased", unlinks[0],
            f"{fn.name} reclaims spill side-files with no consumption "
            "evidence (no settle/rpos check, no reclaim grace, no "
            "rename-claim) — the reader's _spill_in may still open the "
            "file this unlink destroys (the PR 19 race)")

    # ------------------------------------------------ ack before consume

    def _check_ack_before_consume(self, fn: ast.AST) -> None:
        gets: List[int] = []
        acks: List[Tuple[int, ast.AST]] = []
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            if node.func.attr == "get":
                d = _receiver_dotted(node.func) or ""
                if any(inv.CHAN_INBOX_NAME_RE.search(part)
                       for part in d.split(".")):
                    gets.append(node.lineno)
            elif node.func.attr == "ack":
                acks.append((node.lineno, node))
        if not gets or not acks:
            return
        first_ack_line, ack_node = min(acks, key=lambda a: a[0])
        if first_ack_line < min(gets):
            self._emit(
                "chan-ack-before-consume", ack_node,
                "consumption ack sent before the application dequeues "
                "the frame — the credit window stops bounding "
                "unconsumed frames and a slow consumer overruns its "
                "bounded inbox")

    # ---------------------------------------------------- raw seq sends

    def _check_raw_seq_send(self, call: ast.Call) -> None:
        func = call.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in _RAW_SEQ_ATTRS):
            return
        if self.module in inv.CHAN_SEQ_EXEMPT_MODULES:
            return
        if not _channelish(func):
            return
        nargs = len(call.args)
        carries_seq = (
            (func.attr == "write" and nargs >= 2)
            or (func.attr == "write_error" and nargs >= 2)
            or (func.attr == "write_stop" and nargs >= 1)
            or any(kw.arg == "seq" for kw in call.keywords))
        if not carries_seq:
            return
        self._emit(
            "chan-raw-seq-send", call,
            f"explicit seq passed to .{func.attr}() outside the "
            "auto-seq facades — hand-minted seqs ship gaps/duplicates "
            "(route through ChannelWriter, or add the module to "
            "CHAN_SEQ_EXEMPT_MODULES if it IS a facade)")

    # ------------------------------------------------ register lifecycle

    def _check_register_lifecycle(self, tree: ast.AST) -> None:
        register: Optional[ast.Call] = None
        has_unregister = False
        for node in ast.walk(tree):
            # an RPC-shaped send whose first arg is the method name
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _RPC_SEND_ATTRS
                    and node.args):
                continue
            arg0 = node.args[0]
            if not (isinstance(arg0, ast.Constant)
                    and isinstance(arg0.value, str)):
                continue
            if arg0.value == "channel_register" and register is None:
                register = node
            elif arg0.value == "channel_unregister":
                has_unregister = True
        if register is not None and not has_unregister:
            self._emit(
                "chan-register-without-unregister", register,
                "module RPCs channel_register but never "
                "channel_unregister — dead channels pin directory "
                "entries on the head and writers dial corpses",
                scope="")

    # ---------------------------------------------------- dial liveness

    def _check_dial_liveness(self, cls: ast.ClassDef) -> None:
        if self.module not in inv.CHAN_TRANSPORT_MODULES:
            return
        dials = [n for n in ast.walk(cls)
                 if isinstance(n, ast.Call)
                 and (_dotted(n.func) or "").rsplit(".", 1)[-1]
                 == "create_connection"]
        if not dials:
            return
        if inv.CHAN_LIVENESS_RE.search(self._src(cls)):
            return
        self._emit(
            "chan-dial-without-liveness", dials[0],
            f"{cls.name} dials peers but has no _GONE/liveness "
            "handling anywhere in the class — a dial with no death "
            "branch spins forever on a torn-down reader")

    # ------------------------------------------------------ blocking ops

    def _check_blocking_op(self, call: ast.Call) -> None:
        func = call.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in ("read", "recv")):
            return
        if not _channelish(func):
            return
        if any(kw.arg == "timeout" for kw in call.keywords):
            return
        # A second positional to read() (after seq) is the timeout.
        max_pos = 1 if func.attr == "read" else 0
        if len(call.args) > max_pos:
            return
        fn = self._fn_stack[-1] if self._fn_stack else None
        if fn is not None and inv.RETRY_DEADLINE_NAME_RE.search(
                self._src(fn)):
            return
        self._emit(
            "chan-blocking-op-no-deadline", call,
            f"channel .{func.attr}() with no timeout and no deadline "
            "in the enclosing function — a dead peer turns this "
            "caller into a zombie")

    # ------------------------------------------------- mutate after send

    def _check_mutate_after_send(self, fn: ast.AST) -> None:
        # buffer name -> first send line
        sent: dict = {}
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SEND_ATTRS):
                continue
            if not _channelish(node.func):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    sent.setdefault(arg.id, node.lineno)
        if not sent:
            return
        for node in ast.walk(fn):
            line = getattr(node, "lineno", 0)
            name = None
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript) and \
                            isinstance(tgt.value, ast.Name):
                        name = tgt.value.id
            elif isinstance(node, ast.AugAssign):
                tgt = node.target
                if isinstance(tgt, ast.Subscript) and \
                        isinstance(tgt.value, ast.Name):
                    name = tgt.value.id
                elif isinstance(tgt, ast.Name):
                    name = tgt.id
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in inv.CHAN_MUTATING_ATTRS and \
                    isinstance(node.func.value, ast.Name):
                name = node.func.value.id
            if name is not None and name in sent \
                    and line > sent[name]:
                self._emit(
                    "chan-mutate-after-send", node,
                    f"buffer {name!r} mutated after being handed to a "
                    f"channel send at line {sent[name]} — sends are "
                    "zero-copy (pickle-5 out-of-band / ring spill "
                    "views alias this memory), so the mutation races "
                    "the reader (copy first, or mutate before "
                    "sending)")


def lint_source(source: str, module: str, path: str,
                tree: Optional[ast.AST] = None) -> List[Finding]:
    return _ChanLinter(module, path, source).run(tree)
