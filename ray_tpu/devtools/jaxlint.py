"""jax-lint: JAX/XLA tracing-safety rules (rule family ``jax``).

Stdlib-only AST analysis riding rtpu-lint's fingerprint/baseline/
``# rtpu-lint: disable=<rule>`` machinery (``lint.py`` runs both rule
families from one CLI). Every rule is a bug this repo actually shipped
and found by hand in post-review:

  closure-captured-array-into-jit
      an array built in an enclosing/module scope referenced FREE
      inside a jitted function — jit bakes it in as a compile-time
      constant (PR 6: the int8 bench closed over the int8 weight, XLA
      constant-folded it to full width and the "int8" timing silently
      streamed full-precision bytes). Pass arrays as jit ARGUMENTS.
  donation-then-read
      an argument at a ``donate_argnums`` position read again after
      the call in the same function — the buffer was donated; the read
      sees freed/aliased memory (PR 6: the dryrun computed its
      reference loss from params the donating step had consumed).
  host-sync-in-hot-path
      ``.item()``, ``float()``/``int()``/``np.asarray`` on a value a
      device program produced, bare ``device_get``, or a python
      ``if``/``while`` branching on a device value, inside a function
      reachable from a declared hot-path root (engine decode tick,
      train step). The intended once-per-chunk sync carries an inline
      allow-comment; everything else serializes the device pipeline.
  unclamped-dynamic-update-slice
      a ``dynamic_update_slice`` start index that is neither constant
      nor visibly clamped — XLA CLAMPS out-of-range starts instead of
      failing, so an unbounded traced start slides the write window
      backwards over valid data (PR 3's verify window needed scratch
      rows past max_len for exactly this reason).
  pallas-shape-rules
      inside a ``pl.pallas_call`` kernel body: reductions without
      ``keepdims=True`` (sub-2D intermediate), ``jnp.arange`` (1D
      iota), or ``reshape`` (cross-lane relayout) — the classic Mosaic
      lowering failures PR 6 worked around by hand.
  rng-reinit-per-mesh
      ``jax.random.PRNGKey`` called inside a mesh context in a
      sharded-equivalence module — with jax<0.5 non-partitionable
      threefry, jitted RNG VALUES depend on out_shardings, so
      equivalence checks must ``device_put`` ONE host init.

``lint_source(source, module, path)`` returns ``lint.Finding`` rows;
module-scoped tables live in ``invariants.py``.
"""

from __future__ import annotations

import ast
import builtins
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.devtools import invariants as inv
# JAX_RULES is single-sourced in lint.py (the family/baseline machinery
# keys on it); aliased here so rule code and rule registry can't drift.
from ray_tpu.devtools.lint import (Finding, JAX_RULES as RULES, _dotted,
                                   suppressed)

_BUILTINS = set(dir(builtins))


def _snippet(node: ast.AST, limit: int = 48) -> str:
    try:
        text = ast.unparse(node)
    except Exception:  # noqa: BLE001 — diagnostics only
        text = "<expr>"
    return text if len(text) <= limit else text[: limit - 3] + "..."


class _Scope:
    """One lexical scope: its array-ish bindings and local defs."""

    __slots__ = ("node", "bindings", "defs")

    def __init__(self, node):
        self.node = node
        self.bindings: Dict[str, str] = {}   # name -> "array" | "other"
        self.defs: Dict[str, ast.AST] = {}   # name -> FunctionDef


def _is_array_expr(expr: ast.AST) -> bool:
    """Heuristic: does this binding's RHS construct/transform an array?
    Conservative on purpose — only positively-identified arrays flag the
    closure rule, so false positives stay near zero."""
    for sub in ast.walk(expr):
        if not isinstance(sub, ast.Call):
            continue
        dotted = _dotted(sub.func)
        if dotted is None:
            if isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in inv.ARRAY_FACTORY_SUFFIXES:
                return True
            continue
        if dotted in inv.ARRAY_FACTORY_CALLS:
            return True
        if dotted.startswith(inv.ARRAY_FACTORY_PREFIXES):
            return True
        if dotted.rsplit(".", 1)[-1] in inv.ARRAY_FACTORY_SUFFIXES:
            return True
    return False


def _bound_names(fn) -> Set[str]:
    """Every name bound anywhere inside ``fn`` (params, assignments,
    loop targets, nested defs, imports) — the complement of 'free'."""
    bound: Set[str] = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        bound.add(a.arg)
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Name) and isinstance(
                    sub.ctx, (ast.Store, ast.Del)):
                bound.add(sub.id)
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                bound.add(sub.name)
                if sub is not fn:
                    a2 = getattr(sub, "args", None)
                    if a2 is not None:
                        for a in (a2.posonlyargs + a2.args
                                  + a2.kwonlyargs):
                            bound.add(a.arg)
            elif isinstance(sub, ast.Lambda):
                for a in (sub.args.posonlyargs + sub.args.args
                          + sub.args.kwonlyargs):
                    bound.add(a.arg)
            elif isinstance(sub, (ast.Import, ast.ImportFrom)):
                for alias in sub.names:
                    bound.add((alias.asname
                               or alias.name).split(".")[0])
            elif isinstance(sub, ast.ExceptHandler) and sub.name:
                bound.add(sub.name)
    return bound


def _refs_name(expr: ast.AST, names: Set[str],
               skip_fetch: bool = True) -> Optional[str]:
    """First dotted read in ``expr`` matching ``names`` (a device-value
    set). Subtrees under a host-fetch call are excluded: the fetch IS
    the sanctioned sync, its result is host data."""
    todo = [expr]
    while todo:
        sub = todo.pop()
        if skip_fetch and isinstance(sub, ast.Call):
            d = _dotted(sub.func)
            if d is not None and d.rsplit(".", 1)[-1] in \
                    inv.HOST_FETCH_SUFFIXES:
                continue  # do not descend into the fetch's operands
        if isinstance(sub, (ast.Attribute, ast.Name)):
            d = _dotted(sub)
            if d is not None:
                for n in names:
                    if d == n or d.startswith(n + "."):
                        return n
        todo.extend(ast.iter_child_nodes(sub))
    return None


class _JaxLinter:
    def __init__(self, module: str, path: str, source: str):
        self.module = module
        self.path = path
        self.lines = source.splitlines()
        self.findings: List[Finding] = []
        self._scope_names: List[str] = []
        # (fn_node, scope_chain, label) — label names the jit site.
        self._jit_targets: List[Tuple[ast.AST, Tuple[_Scope, ...], str]] = []
        self._seen_jit: Set[int] = set()
        self._kernels: List[Tuple[ast.AST, str]] = []
        self._seen_kernels: Set[int] = set()
        self._functions: Dict[str, List[ast.AST]] = {}

    # ------------------------------------------------------------ utils

    def _emit(self, rule: str, node: ast.AST, message: str,
              scope: Optional[str] = None) -> None:
        # A typoed rule id would be filed under the WRONG family by the
        # baseline writer (RULE_FAMILY defaults to concurrency) and
        # become invisible to --family jax — fail at the source.
        assert rule in RULES, f"unregistered jax rule id {rule!r}"
        line = getattr(node, "lineno", 1)
        if suppressed(self.lines, line, rule):
            return
        self.findings.append(Finding(
            rule, self.path, line,
            scope if scope is not None else ".".join(self._scope_names),
            message))

    # ------------------------------------------------------------- walk

    def run(self, tree: Optional[ast.AST] = None) -> List[Finding]:
        if tree is None:
            try:
                tree = ast.parse("\n".join(self.lines),
                                 filename=self.path)
            except SyntaxError:
                return []  # the concurrency family reports this
        module_scope = _Scope(tree)
        self._walk(tree, (module_scope,), mesh_depth=0)
        self._check_jit_targets()
        self._check_kernels()
        if self.module in inv.JAX_HOT_PATH_ROOTS:
            self._check_hot_paths()
        return self.findings

    def _walk(self, node: ast.AST, scopes: Tuple[_Scope, ...],
              mesh_depth: int) -> None:
        scope = scopes[-1]
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope.defs[child.name] = child
                self._functions.setdefault(child.name, []).append(child)
                self._maybe_decorated_jit(child, scopes)
                self._scope_names.append(child.name)
                self._check_donation_then_read(child)
                self._walk(child, scopes + (_Scope(child),), mesh_depth)
                self._scope_names.pop()
                continue
            if isinstance(child, ast.ClassDef):
                # Python closures skip class scope: class-level array
                # assigns land in the ENCLOSING scope for lookup, which
                # is exactly the "class-level weight" capture case.
                self._scope_names.append(child.name)
                self._walk(child, scopes, mesh_depth)
                self._scope_names.pop()
                continue
            if isinstance(child, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = getattr(child, "value", None)
                if value is not None:
                    kind = "array" if _is_array_expr(value) else "other"
                    targets = (child.targets
                               if isinstance(child, ast.Assign)
                               else [child.target])
                    names: List[str] = []
                    for tgt in targets:
                        if isinstance(tgt, ast.Name):
                            names.append(tgt.id)
                        elif isinstance(tgt, (ast.Tuple, ast.List)):
                            names.extend(e.id for e in tgt.elts
                                         if isinstance(e, ast.Name))
                    for n in names:
                        if kind == "array" or n not in scope.bindings:
                            scope.bindings[n] = kind
                self._walk(child, scopes, mesh_depth)
                continue
            if isinstance(child, (ast.With, ast.AsyncWith)):
                d = 0
                for item in child.items:
                    text = _snippet(item.context_expr, 200).lower()
                    if any(m in text for m in inv.MESH_CONTEXT_MARKERS):
                        d = 1
                self._walk(child, scopes, mesh_depth + d)
                continue
            if isinstance(child, ast.Call):
                self._visit_call(child, scopes, mesh_depth)
            self._walk(child, scopes, mesh_depth)

    # ------------------------------------------------------- call rules

    def _visit_call(self, node: ast.Call, scopes: Tuple[_Scope, ...],
                    mesh_depth: int) -> None:
        dotted = _dotted(node.func) or ""
        tail = dotted.rsplit(".", 1)[-1]
        # jit(X) call sites.
        if dotted in ("jax.jit", "jit") and node.args:
            self._note_jit_target(node.args[0], scopes,
                                  f"jax.jit at line {node.lineno}")
        # pallas_call(kernel | partial(kernel, ...), ...).
        if tail == "pallas_call" and node.args:
            self._note_kernel(node.args[0], scopes)
        # Unclamped dynamic_update_slice starts.
        if tail in ("dynamic_update_slice", "dynamic_update_slice_in_dim"):
            self._check_dus(node, tail)
        # PRNGKey inside a mesh context (declared modules only).
        if (tail == "PRNGKey" and mesh_depth > 0
                and self.module in inv.RNG_SINGLE_INIT_MODULES):
            self._emit(
                "rng-reinit-per-mesh", node,
                "jax.random.PRNGKey called inside a mesh context — "
                "sharded-equivalence paths must device_put ONE host "
                "init (jax<0.5 jitted RNG values depend on "
                "out_shardings)")

    def _check_dus(self, node: ast.Call, tail: str) -> None:
        if tail == "dynamic_update_slice":
            if len(node.args) < 3:
                return
            start = node.args[2]
            starts = start.elts if isinstance(start, ast.Tuple) \
                else [start] + list(node.args[3:])
        else:
            if len(node.args) < 3:
                return
            starts = [node.args[2]]
        for s in starts:
            if isinstance(s, ast.Constant):
                continue
            if isinstance(s, ast.UnaryOp) and \
                    isinstance(s.operand, ast.Constant):
                continue
            clamped = False
            for sub in ast.walk(s):
                if isinstance(sub, ast.Call):
                    d = _dotted(sub.func) or ""
                    if d.rsplit(".", 1)[-1] in inv.DUS_CLAMP_CALLS:
                        clamped = True
                        break
            if not clamped:
                self._emit(
                    "unclamped-dynamic-update-slice", node,
                    f"{tail} start '{_snippet(s)}' is neither constant "
                    "nor clamped — XLA CLAMPS out-of-range starts, so "
                    "an unbounded index silently slides the write over "
                    "valid rows; clamp it or document the bound")

    # ------------------------------------------------------ jit targets

    def _maybe_decorated_jit(self, fn, scopes) -> None:
        for dec in fn.decorator_list:
            d = _dotted(dec) or ""
            if d in ("jax.jit", "jit"):
                self._note_jit_target(fn, scopes, f"@{d}")
                return
            if isinstance(dec, ast.Call):
                dc = _dotted(dec.func) or ""
                if dc in ("jax.jit", "jit"):
                    self._note_jit_target(fn, scopes, f"@{dc}(...)")
                    return
                if dc.rsplit(".", 1)[-1] == "partial" and dec.args:
                    inner = _dotted(dec.args[0]) or ""
                    if inner in ("jax.jit", "jit"):
                        self._note_jit_target(fn, scopes,
                                              f"@partial({inner}, ...)")
                        return

    def _note_jit_target(self, target: ast.AST,
                         scopes: Tuple[_Scope, ...], label: str) -> None:
        fn: Optional[ast.AST] = None
        if isinstance(target, (ast.Lambda, ast.FunctionDef,
                               ast.AsyncFunctionDef)):
            fn = target
        elif isinstance(target, ast.Name):
            for scope in reversed(scopes):
                if target.id in scope.defs:
                    fn = scope.defs[target.id]
                    break
        if fn is None or id(fn) in self._seen_jit:
            return
        self._seen_jit.add(id(fn))
        self._jit_targets.append((fn, scopes, label))

    def _check_jit_targets(self) -> None:
        for fn, scopes, label in self._jit_targets:
            bound = _bound_names(fn)
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            flagged: Set[str] = set()
            for stmt in body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Name) and \
                            isinstance(sub.ctx, ast.Load):
                        name = sub.id
                        if name in bound or name in _BUILTINS or \
                                name in flagged:
                            continue
                        for scope in reversed(scopes):
                            if name in scope.defs:
                                break
                            kind = scope.bindings.get(name)
                            if kind == "array":
                                flagged.add(name)
                                self._emit(
                                    "closure-captured-array-into-jit",
                                    sub,
                                    f"'{name}' is an array from an "
                                    f"enclosing scope captured by a "
                                    f"jitted function ({label}) — jit "
                                    "bakes it in as a constant (the "
                                    "PR 6 int8 bench constant-folded "
                                    "its closed-over weight to full "
                                    "width); pass it as an argument",
                                    scope=self._fn_scope(fn))
                                break
                            if kind is not None:
                                break
                    elif isinstance(sub, ast.Attribute) and \
                            isinstance(sub.value, ast.Name) and \
                            sub.value.id == "self" and \
                            "self" not in bound and \
                            isinstance(sub.ctx, ast.Load) and \
                            inv.ARRAY_ATTR_RE.fullmatch(sub.attr):
                        key = f"self.{sub.attr}"
                        if key in flagged:
                            continue
                        flagged.add(key)
                        self._emit(
                            "closure-captured-array-into-jit", sub,
                            f"'{key}' captured by a jitted function "
                            f"({label}) — instance arrays referenced "
                            "through a closed-over self become jit "
                            "constants; pass the array as an argument",
                            scope=self._fn_scope(fn))
            del flagged

    @staticmethod
    def _fn_scope(fn) -> str:
        return getattr(fn, "name", "<lambda>")

    # ------------------------------------------------- donation tracking

    def _check_donation_then_read(self, fn) -> None:
        """Within ONE function: track names passed at donated positions
        of a locally-bound donating jit; later reads without a rebind
        are findings."""
        donated_fns: Dict[str, Tuple[int, ...]] = {}
        for stmt in fn.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                idxs = self._donate_indices_in(stmt.value)
                if idxs:
                    donated_fns[stmt.targets[0].id] = idxs
            elif isinstance(stmt, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for dec in stmt.decorator_list:
                    idxs = self._donate_indices_in(dec)
                    if idxs:
                        donated_fns[stmt.name] = idxs
        if not donated_fns:
            return
        pending: Dict[str, int] = {}  # dotted arg -> donation line

        def clear(name: str) -> None:
            for k in list(pending):
                if k == name or k.startswith(name + "."):
                    del pending[k]

        def scan_expr(expr: ast.AST) -> None:
            """Dotted reads checked at their OUTERMOST chain (so the
            finding names 'state.params', not the inner 'state');
            donation marking happens after a call's args were read."""
            if isinstance(expr, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(expr, "ctx", ast.Load()),
                               ast.Load):
                d = _dotted(expr)
                if d is not None:
                    for k, call_line in pending.items():
                        if d == k or d.startswith(k + "."):
                            self._emit(
                                "donation-then-read", expr,
                                f"'{d}' was donated at line "
                                f"{call_line} (donate_argnums) and "
                                "read afterwards — the buffer is "
                                "freed/aliased after the call; "
                                "read results, not donated inputs")
                            del pending[k]
                            break
                    return  # the dotted chain is consumed whole
            for sub in ast.iter_child_nodes(expr):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.Lambda,
                                    ast.ClassDef)):
                    continue
                scan_expr(sub)
            if isinstance(expr, ast.Call):
                d = _dotted(expr.func)
                if d is not None and d in donated_fns:
                    for i in donated_fns[d]:
                        if i < len(expr.args):
                            an = _dotted(expr.args[i])
                            if an is not None:
                                pending[an] = expr.lineno

        def scan_stmt(stmt: ast.AST) -> None:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return
            if isinstance(stmt, ast.Assign):
                scan_expr(stmt.value)
                for tgt in stmt.targets:
                    for sub in ast.walk(tgt):
                        if isinstance(sub, (ast.Name, ast.Attribute)):
                            d = _dotted(sub)
                            if d is not None:
                                clear(d)
                return
            if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if stmt.value is not None:
                    scan_expr(stmt.value)
                d = _dotted(stmt.target)
                if d is not None:
                    clear(d)
                return
            for sub in ast.iter_child_nodes(stmt):
                if isinstance(sub, ast.stmt):
                    scan_stmt(sub)
                else:
                    scan_expr(sub)

        for stmt in fn.body:
            scan_stmt(stmt)

    @staticmethod
    def _donate_indices_in(expr: ast.AST) -> Tuple[int, ...]:
        """donate_argnums indices from any jax.jit call inside expr."""
        for sub in ast.walk(expr):
            if not isinstance(sub, ast.Call):
                continue
            d = _dotted(sub.func) or ""
            if d not in ("jax.jit", "jit") and not (
                    d.rsplit(".", 1)[-1] == "partial" and sub.args
                    and (_dotted(sub.args[0]) or "") in ("jax.jit",
                                                         "jit")):
                continue
            for kw in sub.keywords:
                if kw.arg != "donate_argnums":
                    continue
                v = kw.value
                if isinstance(v, ast.Constant) and \
                        isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    out = tuple(e.value for e in v.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, int))
                    if out:
                        return out
                return (0,)
        return ()

    # ------------------------------------------------------ hot paths

    def _check_hot_paths(self) -> None:
        roots = inv.JAX_HOT_PATH_ROOTS[self.module]
        # Intra-module call graph over bare function/method names.
        edges: Dict[str, Set[str]] = {}
        for name, fns in self._functions.items():
            outs: Set[str] = set()
            for fn in fns:
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Call):
                        d = _dotted(sub.func) or ""
                        t = d.rsplit(".", 1)[-1]
                        if t in self._functions and t != name:
                            outs.add(t)
            edges[name] = outs
        hot: Set[str] = set()
        todo = [r for r in roots if r in self._functions]
        while todo:
            cur = todo.pop()
            if cur in hot:
                continue
            hot.add(cur)
            todo.extend(edges.get(cur, ()))
        for name in sorted(hot):
            for fn in self._functions[name]:
                self._check_hot_fn(fn, name)

    def _check_hot_fn(self, fn, name: str) -> None:
        device: Set[str] = set()

        def producer_call(expr: ast.AST) -> Optional[str]:
            """'device' / 'host' / None for the calls inside expr."""
            found = None
            for sub in ast.walk(expr):
                if not isinstance(sub, ast.Call):
                    continue
                d = _dotted(sub.func) or ""
                t = d.rsplit(".", 1)[-1]
                if t in inv.HOST_FETCH_SUFFIXES:
                    return "host"
                if t in inv.DEVICE_PRODUCER_SUFFIXES or \
                        d.startswith(inv.DEVICE_PRODUCER_PREFIXES):
                    found = "device"
            return found

        def flag(node, what: str) -> None:
            self._emit(
                "host-sync-in-hot-path", node,
                f"{what} in hot-path function '{name}' — the decode/"
                "train hot path syncs the host AT MOST once per chunk "
                "through its counted fetch; route through it or "
                "allow-comment the intended sync", scope=name)

        def scan(node: ast.AST) -> None:
            """Dispatch on the node ITSELF, then recurse — statements
            are checked wherever they sit, not only as direct children
            of the body."""
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return
            if isinstance(node, ast.Assign):
                scan(node.value)
                verdict = producer_call(node.value)
                if verdict is None and _refs_name(node.value, device):
                    verdict = "device"
                flat: List[ast.AST] = []
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Tuple, ast.List)):
                        flat.extend(tgt.elts)
                    else:
                        flat.append(tgt)
                for tgt in flat:
                    if isinstance(tgt, ast.Starred):
                        tgt = tgt.value
                    if isinstance(tgt, (ast.Name, ast.Attribute)):
                        d = _dotted(tgt)
                        if d is None:
                            continue
                        if verdict == "device":
                            device.add(d)
                        else:
                            device.discard(d)
                return
            if isinstance(node, (ast.If, ast.While)):
                ref = _refs_name(node.test, device)
                if ref is not None:
                    flag(node, f"python {type(node).__name__.lower()}"
                               f" on device value '{ref}'")
            elif isinstance(node, ast.Call):
                d = _dotted(node.func) or ""
                t = d.rsplit(".", 1)[-1]
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in inv.HOST_SYNC_CALL_SUFFIXES:
                    flag(node, f".{node.func.attr}()")
                elif t in inv.HOST_SYNC_CALL_SUFFIXES:
                    flag(node, f"{d}()")
                elif d in ("float", "int") and node.args:
                    ref = _refs_name(node.args[0], device)
                    if ref is not None:
                        flag(node, f"{d}() on device value '{ref}'")
                elif d in ("np.asarray", "np.array", "numpy.asarray",
                           "numpy.array") and node.args:
                    ref = _refs_name(node.args[0], device)
                    if ref is not None:
                        flag(node, f"{d}() on device value '{ref}'")
            for child in ast.iter_child_nodes(node):
                scan(child)

        for stmt in fn.body:
            scan(stmt)

    # -------------------------------------------------------- kernels

    def _note_kernel(self, target: ast.AST,
                     scopes: Tuple[_Scope, ...]) -> None:
        fn: Optional[ast.AST] = None
        label = "pallas_call"
        if isinstance(target, ast.Call):  # functools.partial(kernel, ..)
            d = _dotted(target.func) or ""
            if d.rsplit(".", 1)[-1] == "partial" and target.args:
                target = target.args[0]
        if isinstance(target, (ast.Lambda, ast.FunctionDef)):
            fn = target
        elif isinstance(target, ast.Name):
            label = target.id
            for scope in reversed(scopes):
                if target.id in scope.defs:
                    fn = scope.defs[target.id]
                    break
        if fn is None or id(fn) in self._seen_kernels:
            return
        self._seen_kernels.add(id(fn))
        self._kernels.append((fn, label))

    def _check_kernels(self) -> None:
        for fn, label in self._kernels:
            scope = self._fn_scope(fn)
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for sub in ast.walk(stmt):
                    if not isinstance(sub, ast.Call):
                        continue
                    d = _dotted(sub.func) or ""
                    # Method calls on non-dotted receivers (x_ref[...]
                    # .reshape(...)) still name their method.
                    t = (sub.func.attr
                         if isinstance(sub.func, ast.Attribute)
                         else d.rsplit(".", 1)[-1])
                    if t == "reshape":
                        self._emit(
                            "pallas-shape-rules", sub,
                            f"reshape inside Pallas kernel '{label}' — "
                            "cross-lane relayouts fail Mosaic lowering; "
                            "restructure with BlockSpecs/broadcasting",
                            scope=scope)
                    elif t == "arange":
                        self._emit(
                            "pallas-shape-rules", sub,
                            f"1D iota (arange) inside Pallas kernel "
                            f"'{label}' — Mosaic requires >=2D; use "
                            "lax.broadcasted_iota", scope=scope)
                    elif t in inv.PALLAS_REDUCTIONS and (
                            d.startswith(("jnp.", "jax.numpy."))
                            or isinstance(sub.func, ast.Attribute)):
                        kd = next((kw for kw in sub.keywords
                                   if kw.arg == "keepdims"), None)
                        if kd is None or not (
                                isinstance(kd.value, ast.Constant)
                                and kd.value.value is True):
                            self._emit(
                                "pallas-shape-rules", sub,
                                f"reduction '{t}' without "
                                f"keepdims=True inside Pallas kernel "
                                f"'{label}' — sub-2D intermediates "
                                "fail Mosaic lowering", scope=scope)


def lint_source(source: str, module: str, path: str,
                tree: Optional[ast.AST] = None) -> List[Finding]:
    """Run the jax rule family over one module's source. ``tree``
    reuses a caller-side parse (lint_paths parses once per file for
    both families)."""
    return _JaxLinter(module, path, source).run(tree)
