"""rtpu-lint: AST-based invariant enforcement for this repo.

Stdlib-only. Run as ``python -m ray_tpu.devtools.lint`` (from the repo
root or anywhere — the default scan roots resolve relative to the
installed package). Rules live in ``invariants.py``; each finding
carries a rule id:

  lock-order            nested acquisition violating a declared chain,
                        or two locks from a never-nested group held
                        together
  blocking-under-lock   socket recv*/sendmsg, subprocess, pipe reads,
                        or a long time.sleep inside a ``with <lock>``
                        body (I/O-serialization locks exempt)
  close-without-shutdown  socket .close() with no earlier shutdown in
                        the same function (recv_into-sink modules only)
  banned-api            jax<0.5-breaking calls/imports; dashboard
                        innerHTML/document.write in JS strings
  swallowed-exception   broad except that neither raises, logs, nor
                        uses the bound exception
  daemon-no-join        a daemon Thread stored on self but never
                        joined by any method of the class
  retry-without-deadline  a ``while True:`` retry loop around
                        retrying_call / socket connect with no visible
                        deadline, attempt counter, or stop-event check —
                        chaos runs (dead peer, dropped frames) hang
                        exactly there
  span-not-closed       a ``tracing.trace/span/remote_span(...)`` call
                        not used as a context manager (directly in a
                        ``with``, via a name later with-ed, or through
                        ``stack.enter_context``) — the span never ends
                        and its ContextVar parentage leaks onto every
                        later span in the thread

A second rule family, ``jax`` (``jaxlint.py``), runs from the same CLI:
JAX/XLA tracing-safety rules (closure-captured-array-into-jit,
donation-then-read, host-sync-in-hot-path,
unclamped-dynamic-update-slice, pallas-shape-rules,
rng-reinit-per-mesh). A third, ``dist`` (``distlint.py``), enforces the
distributed RPC contract (unclassified-rpc-handler, retry-unsafe-call,
direct-notify-bypasses-outbox, serial-fanout-no-deadline,
wall-clock-deadline, missing-chaos-role). A fourth, ``res``
(``reslint.py``), enforces resource lifetimes (acquire-without-release,
begin-without-commit, unbounded-registry-growth, thread-without-stop,
fd-leak-on-error) with ``res_debug.py``'s RTPU_DEBUG_RES runtime
witness as its dynamic half. A fifth, ``chan`` (``chanlint.py``),
enforces the channel-protocol contract on the pre-negotiated data
plane (chan-cursor-publish-order, chan-spill-pin-unreleased,
chan-ack-before-consume, chan-raw-seq-send,
chan-register-without-unregister, chan-dial-without-liveness,
chan-blocking-op-no-deadline, chan-mutate-after-send) with
``chan_debug.py``'s RTPU_DEBUG_CHAN frame-stream witness as its
dynamic half.
``--family {all,concurrency,jax,dist,res,chan}`` selects which
families run (default: all).

Baseline workflow: legacy findings live in ``lint_baseline.json``,
sectioned per rule family with a per-family schema version
(fingerprint -> count). A run fails (exit 1) only when a fingerprint's
current count exceeds its baselined count — new violations fail, old
ones are tracked. Update after an intentional change with
``--write-baseline`` (``--family X --write-baseline`` rewrites ONLY
that family's section, never touching the other family's entries).
Suppress a single line with ``# rtpu-lint: disable=<rule-id>``.
"""

from __future__ import annotations

import argparse
import ast
import hashlib
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

from ray_tpu.devtools import invariants as inv

_HERE = os.path.dirname(os.path.abspath(__file__))
DEFAULT_BASELINE = os.path.join(_HERE, "lint_baseline.json")

RULES = (
    "lock-order", "blocking-under-lock", "close-without-shutdown",
    "banned-api", "swallowed-exception", "daemon-no-join",
    "retry-without-deadline", "span-not-closed",
)

#: Rule families: "concurrency" = the tables above (the original
#: rtpu-lint rule set), "jax" = the tracing-safety family in
#: ``jaxlint.py``, "dist" = the distributed RPC-contract family in
#: ``distlint.py``. Each family versions its fingerprinting scheme
#: independently (FAMILY_SCHEMA) so a rule rewrite in one family never
#: invalidates the others' baseline sections.
JAX_RULES = (
    "closure-captured-array-into-jit", "donation-then-read",
    "host-sync-in-hot-path", "unclamped-dynamic-update-slice",
    "pallas-shape-rules", "rng-reinit-per-mesh",
)
DIST_RULES = (
    "unclassified-rpc-handler", "retry-unsafe-call",
    "direct-notify-bypasses-outbox", "serial-fanout-no-deadline",
    "wall-clock-deadline", "missing-chaos-role",
    "retry-unsafe-block-rpc",
)
RES_RULES = (
    "acquire-without-release", "begin-without-commit",
    "unbounded-registry-growth", "thread-without-stop",
    "fd-leak-on-error",
)
CHAN_RULES = (
    "chan-cursor-publish-order", "chan-spill-pin-unreleased",
    "chan-ack-before-consume", "chan-raw-seq-send",
    "chan-register-without-unregister", "chan-dial-without-liveness",
    "chan-blocking-op-no-deadline", "chan-mutate-after-send",
)
FAMILIES = ("concurrency", "jax", "dist", "res", "chan")
FAMILY_RULES = {"concurrency": RULES, "jax": JAX_RULES,
                "dist": DIST_RULES, "res": RES_RULES,
                "chan": CHAN_RULES}
FAMILY_SCHEMA = {"concurrency": 1, "jax": 1, "dist": 1, "res": 1,
                 "chan": 1}
RULE_FAMILY = {rule: fam for fam, rules in FAMILY_RULES.items()
               for rule in rules}


class Finding:
    __slots__ = ("rule", "path", "line", "scope", "message")

    def __init__(self, rule: str, path: str, line: int, scope: str,
                 message: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.scope = scope
        self.message = message

    def fingerprint(self) -> str:
        # Line numbers drift with every edit: the fingerprint hashes the
        # rule + file + enclosing scope + message so baselined findings
        # survive unrelated churn. Duplicates within one scope share a
        # fingerprint and are baselined by COUNT.
        raw = "|".join((self.rule, self.path, self.scope, self.message))
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] {self.message}"
                f"  (in {self.scope or '<module>'})")


def suppressed(lines: List[str], line: int, rule: str) -> bool:
    """Is ``rule`` disabled on source ``line`` by an inline
    ``# rtpu-lint: disable=<rule>[,<rule>...]`` comment? The ONE
    implementation of the suppression protocol — both rule families
    route through it."""
    if not 1 <= line <= len(lines):
        return False
    text = lines[line - 1]
    tok = inv.SUPPRESS_TOKEN
    if tok in text:
        parts = text.split(tok, 1)[1].split()
        if parts and rule in parts[0].split(","):
            return True
    return False


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _lock_name(expr: ast.AST) -> Optional[str]:
    """The lock's short name if ``expr`` looks like a lock (self._lock,
    module_lock, conn.send_lock ...)."""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    else:
        return None
    if inv.LOCK_NAME_RE.search(name):
        return name
    return None


class _FileLinter(ast.NodeVisitor):
    def __init__(self, module: str, path: str, source: str):
        self.module = module
        self.path = path
        self.lines = source.splitlines()
        self.findings: List[Finding] = []
        self._scope: List[str] = []
        self._held: List[str] = []  # with-lock stack (short names)
        self._order = inv.LOCK_ORDER.get(module, ())
        self._never = inv.NEVER_NESTED.get(module, ())
        self._io_locks = inv.IO_LOCKS.get(module, set())
        self._is_dashboard = module in inv.DASHBOARD_MODULES
        self._check_sockets = module in inv.SOCKET_SHUTDOWN_MODULES
        self._js_counts: Dict[str, int] = {}

    # ------------------------------------------------------------ utils

    def _suppressed(self, line: int, rule: str) -> bool:
        if suppressed(self.lines, line, rule):
            return True
        if rule == "swallowed-exception" and \
                1 <= line <= len(self.lines) and \
                inv.NOQA_BROAD_EXCEPT in self.lines[line - 1]:
            return True
        return False

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        if self._suppressed(line, rule):
            return
        self.findings.append(Finding(rule, self.path, line,
                                     ".".join(self._scope), message))

    # ------------------------------------------------------------ scope

    def visit_FunctionDef(self, node):
        self._scope.append(node.name)
        if self._check_sockets:
            self._check_close_without_shutdown(node)
        self._check_span_not_closed(node)
        # A nested def's body runs LATER, on whatever thread calls it —
        # not under the with-locks lexically enclosing the def. Clear
        # the held stack for its body so closures defined inside a lock
        # block aren't falsely flagged (and restore for the remainder
        # of the enclosing block).
        held, self._held = self._held, []
        self.generic_visit(node)
        self._held = held
        self._scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self._scope.append(node.name)
        self._check_daemon_threads(node)
        self.generic_visit(node)
        self._scope.pop()

    # -------------------------------------------------- socket shutdown

    def _check_close_without_shutdown(self, fn) -> None:
        """Within one function: ``x.close()`` on a socket-looking name
        with no earlier ``x.shutdown(...)`` / ``_shutdown_socket(x)``.
        A bare close() frees the fd without waking a thread blocked in
        recv on it — which then keeps writing into freed shm."""
        events = []  # (lineno, col, kind, varname)
        # Walk THIS function only: nested defs get their own visit (a
        # shared walk would double-report every close() inside them).
        todo = list(ast.iter_child_nodes(fn))
        nodes = []
        while todo:
            sub = todo.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda, ast.ClassDef)):
                continue
            nodes.append(sub)
            todo.extend(ast.iter_child_nodes(sub))
        for sub in nodes:
            if not isinstance(sub, ast.Call):
                continue
            if isinstance(sub.func, ast.Attribute):
                var = _dotted(sub.func.value)
                if var is None or not inv.SOCKET_NAME_RE.search(var):
                    continue
                if sub.func.attr == "shutdown":
                    events.append((sub.lineno, sub.col_offset, "shut",
                                   var))
                elif sub.func.attr == "close":
                    events.append((sub.lineno, sub.col_offset, "close",
                                   var))
            elif isinstance(sub.func, ast.Name) and \
                    "shutdown" in sub.func.id and sub.args:
                var = _dotted(sub.args[0])
                if var is not None:
                    events.append((sub.lineno, sub.col_offset, "shut",
                                   var))
        shut = set()
        for lineno, _col, kind, var in sorted(events):
            if kind == "shut":
                shut.add(var)
            elif var not in shut:
                if not self._suppressed(lineno, "close-without-shutdown"):
                    self.findings.append(Finding(
                        "close-without-shutdown", self.path, lineno,
                        ".".join(self._scope),
                        f"{var}.close() without a prior shutdown() in "
                        f"'{fn.name}' — a reader blocked in recv stays "
                        "alive writing into freed buffers"))

    # -------------------------------------------------- unclosed spans

    @staticmethod
    def _is_span_call(call: ast.Call) -> Optional[str]:
        """'tracing.span'-style descriptor if this call constructs a
        tracing context manager, else None."""
        fn = call.func
        if isinstance(fn, ast.Attribute) and \
                fn.attr in inv.TRACING_SPAN_ATTRS:
            recv = _dotted(fn.value)
            if recv is not None and \
                    inv.TRACING_RECEIVER_RE.search(recv.split(".")[-1]):
                return f"{recv}.{fn.attr}"
        elif isinstance(fn, ast.Name) and fn.id in inv.TRACING_SPAN_NAMES:
            return fn.id
        return None

    def _check_span_not_closed(self, fn) -> None:
        """Within one function: a tracing.trace/span/remote_span call
        must be consumed as a context manager — directly as a ``with``
        item, assigned to a name that is later a ``with`` item, or
        passed to ``.enter_context(...)``. Anything else opens a span
        that never ends and leaks its ContextVar parentage onto every
        later span in the thread/task."""
        span_calls: List[Tuple[ast.Call, str]] = []
        ok_ids: set = set()  # id() of span calls consumed correctly
        with_names: set = set()
        assigned: Dict[str, List[ast.Call]] = {}
        # Walk THIS function only: nested defs get their own visit.
        todo = list(ast.iter_child_nodes(fn))
        nodes = []
        while todo:
            sub = todo.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda, ast.ClassDef)):
                continue
            nodes.append(sub)
            todo.extend(ast.iter_child_nodes(sub))
        for sub in nodes:
            if isinstance(sub, ast.Call):
                desc = self._is_span_call(sub)
                if desc is not None:
                    span_calls.append((sub, desc))
                fn_attr = sub.func
                if isinstance(fn_attr, ast.Attribute) and \
                        fn_attr.attr == "enter_context":
                    for arg in sub.args:
                        ok_ids.add(id(arg))
            elif isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    ok_ids.add(id(item.context_expr))
                    if isinstance(item.context_expr, ast.Name):
                        with_names.add(item.context_expr.id)
            elif isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and isinstance(sub.value, ast.Call):
                if self._is_span_call(sub.value) is not None:
                    assigned.setdefault(sub.targets[0].id,
                                        []).append(sub.value)
        for name, calls in assigned.items():
            if name in with_names:
                for c in calls:
                    ok_ids.add(id(c))
        for call, desc in span_calls:
            if id(call) in ok_ids:
                continue
            self._emit(
                "span-not-closed", call,
                f"{desc}(...) is not used as a context manager — the "
                "span never ends and its ContextVar parentage leaks "
                "onto every later span in this thread (use `with`, or "
                "stack.enter_context)")

    # ------------------------------------------------ unbounded retries

    def visit_While(self, node):
        self._check_retry_loop(node)
        self.generic_visit(node)

    def _check_retry_loop(self, node: ast.While) -> None:
        """``while True:`` around retrying_call / socket connect with no
        deadline, attempt counter, or stop-event check: under chaos
        (peer dead, frames dropped) the loop never exits. Success-path
        ``break``/``return`` do NOT bound it — the hang case is the one
        where success never comes."""
        test = node.test
        if not (isinstance(test, ast.Constant) and test.value is True
                or isinstance(test, ast.Constant) and test.value == 1):
            return
        # Walk THIS loop only; nested defs run on their own schedule.
        nodes, todo = [], list(node.body)
        while todo:
            sub = todo.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda, ast.ClassDef)):
                continue
            nodes.append(sub)
            todo.extend(ast.iter_child_nodes(sub))
        retry_call = None
        bounded = False
        for sub in nodes:
            if isinstance(sub, ast.Call):
                dotted = _dotted(sub.func) or ""
                if isinstance(sub.func, ast.Attribute):
                    attr = sub.func.attr
                    if attr in inv.RETRY_CALL_ATTRS:
                        retry_call = retry_call or f".{attr}()"
                    elif any(dotted.endswith(s)
                             for s in inv.RETRY_CONNECT_SUFFIXES):
                        retry_call = retry_call or f"{dotted}()"
                    elif attr == "connect":
                        var = _dotted(sub.func.value) or ""
                        if inv.SOCKET_NAME_RE.search(var):
                            retry_call = retry_call or f"{var}.connect()"
                    if attr in inv.RETRY_STOP_ATTRS:
                        var = _dotted(sub.func.value) or ""
                        if inv.RETRY_STOP_NAME_RE.search(var):
                            bounded = True
                if dotted in inv.RETRY_DEADLINE_CALLS:
                    bounded = True
            name = None
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Attribute):
                name = sub.attr
            if name is not None and \
                    inv.RETRY_DEADLINE_NAME_RE.search(name):
                bounded = True
        if retry_call is not None and not bounded:
            self._emit(
                "retry-without-deadline", node,
                f"while True loop retries {retry_call} with no "
                "deadline, attempt counter, or stop-event check — "
                "bound it (a chaos run hangs here when the peer "
                "never recovers)")

    # -------------------------------------------------------- lock rules

    def _check_lock_pair(self, node: ast.AST, new: str) -> None:
        for held in self._held:
            if held == new:
                continue
            for chain in self._order:
                if new in chain and held in chain and \
                        chain.index(new) < chain.index(held):
                    self._emit(
                        "lock-order", node,
                        f"acquires '{new}' while holding '{held}' — "
                        f"declared order is {' -> '.join(chain)}")
            for group in self._never:
                if new in group and held in group:
                    self._emit(
                        "lock-order", node,
                        f"acquires '{new}' while holding '{held}' — "
                        "these locks are declared never-nested")

    def visit_With(self, node):
        count = 0
        for item in node.items:
            self.visit(item.context_expr)
            name = _lock_name(item.context_expr)
            if name is not None:
                self._check_lock_pair(item.context_expr, name)
                self._held.append(name)
                count += 1
        for stmt in node.body:
            self.visit(stmt)
        if count:
            del self._held[-count:]

    visit_AsyncWith = visit_With

    def _held_non_io(self) -> List[str]:
        return [h for h in self._held if h not in self._io_locks]

    def visit_Call(self, node):
        dotted = _dotted(node.func)
        # .acquire() on another lock while inside a with-lock body.
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "acquire":
            name = _lock_name(node.func.value)
            if name is not None and self._held:
                self._check_lock_pair(node, name)
        # Blocking calls under a (non-IO) lock.
        held = self._held_non_io()
        if held:
            blocked = None
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in inv.BLOCKING_METHODS:
                blocked = f".{node.func.attr}()"
            elif dotted in inv.BLOCKING_FUNCS:
                blocked = f"{dotted}()"
            elif dotted == "time.sleep" and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, (int, float)) and \
                        arg.value > inv.SLEEP_UNDER_LOCK_MAX_S:
                    blocked = f"time.sleep({arg.value})"
            if blocked is not None:
                self._emit(
                    "blocking-under-lock", node,
                    f"{blocked} inside `with {held[-1]}` — blocking "
                    "I/O must not run while holding a state lock")
        # Banned jax calls.
        if dotted is not None:
            for suffix, hint in inv.BANNED_CALLS.items():
                if dotted == suffix or dotted.endswith("." + suffix):
                    self._emit("banned-api", node,
                               f"call to {dotted}: {hint}")
                    break
        self.generic_visit(node)

    # ---------------------------------------------------------- imports

    def _banned_import(self, node: ast.AST, path: str) -> None:
        entry = inv.BANNED_IMPORTS.get(path)
        if entry is None:
            return
        hint, exempt = entry
        if self.module in exempt:
            return
        self._emit("banned-api", node, f"import of {path}: {hint}")

    def visit_Import(self, node):
        for alias in node.names:
            self._banned_import(node, alias.name)
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        mod = node.module or ""
        self._banned_import(node, mod)
        for alias in node.names:
            self._banned_import(node, f"{mod}.{alias.name}")
        self.generic_visit(node)

    # ------------------------------------------------------- JS strings

    def visit_Constant(self, node):
        if self._is_dashboard and isinstance(node.value, str):
            for sub, hint in inv.BANNED_JS_SUBSTRINGS.items():
                start = 0
                while True:
                    idx = node.value.find(sub, start)
                    if idx < 0:
                        break
                    line = node.lineno + node.value.count("\n", 0, idx)
                    # Fingerprint by per-file occurrence INDEX, not char
                    # offset: edits elsewhere in the JS must not churn
                    # the baseline.
                    n = self._js_counts.get(sub, 0)
                    self._js_counts[sub] = n + 1
                    if not self._suppressed(line, "banned-api"):
                        self.findings.append(Finding(
                            "banned-api", self.path, line,
                            ".".join(self._scope) + f"+{sub}#{n}",
                            f"'{sub}' in dashboard JS: {hint}"))
                    start = idx + len(sub)
        self.generic_visit(node)

    # ------------------------------------------------------ bare excepts

    def visit_ExceptHandler(self, node):
        if self._broad(node.type) and not self._handled(node):
            self._emit(
                "swallowed-exception", node,
                "broad except neither raises, logs, nor uses the "
                "exception — log at debug minimum or narrow the type")
        self.generic_visit(node)

    @staticmethod
    def _broad(type_node) -> bool:
        if type_node is None:
            return True
        names = []
        if isinstance(type_node, ast.Tuple):
            names = [t for t in type_node.elts]
        else:
            names = [type_node]
        for t in names:
            n = t.id if isinstance(t, ast.Name) else (
                t.attr if isinstance(t, ast.Attribute) else "")
            if n in ("Exception", "BaseException"):
                return True
        return False

    @staticmethod
    def _handled(handler: ast.ExceptHandler) -> bool:
        bound = handler.name
        for sub in ast.walk(ast.Module(body=handler.body,
                                       type_ignores=[])):
            if isinstance(sub, ast.Raise):
                return True
            if bound and isinstance(sub, ast.Name) and sub.id == bound:
                return True  # exception object is inspected/reported
            if isinstance(sub, ast.Call):
                fn = sub.func
                n = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else "")
                if n in inv.LOGGING_CALL_NAMES:
                    return True
        return False

    # ------------------------------------------------- daemon-thread join

    def _check_daemon_threads(self, cls: ast.ClassDef) -> None:
        daemons: List[Tuple[str, ast.AST]] = []
        joined: set = set()
        for sub in ast.walk(cls):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                tgt = sub.targets[0]
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and isinstance(sub.value, ast.Call)):
                    fn = _dotted(sub.value.func) or ""
                    if fn.endswith("Thread"):
                        for kw in sub.value.keywords:
                            if (kw.arg == "daemon"
                                    and isinstance(kw.value, ast.Constant)
                                    and kw.value.value is True):
                                daemons.append((tgt.attr, sub))
            if (isinstance(sub, ast.Attribute) and sub.attr == "join"
                    and isinstance(sub.value, ast.Attribute)
                    and isinstance(sub.value.value, ast.Name)
                    and sub.value.value.id == "self"):
                joined.add(sub.value.attr)
        for attr, node in daemons:
            if attr not in joined:
                self._emit(
                    "daemon-no-join", node,
                    f"daemon thread self.{attr} is never joined by any "
                    "method of this class — join it on close/shutdown "
                    "so teardown is ordered")


# --------------------------------------------------------------- driver


def lint_source(source: str, module: str, path: str,
                tree: Optional[ast.AST] = None) -> List[Finding]:
    """Lint one module's source; ``module`` selects the invariant
    tables that apply (tests inject fixture snippets this way).
    ``tree`` skips the parse when the caller already has one
    (lint_paths parses each file once for both rule families)."""
    if tree is None:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            return [Finding("banned-api", path, e.lineno or 1, "",
                            f"syntax error: {e.msg}")]
    linter = _FileLinter(module, path, source)
    linter.visit(tree)
    return linter.findings


def _module_for(path: str, root: str) -> str:
    rel = os.path.relpath(path, root)
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace(os.sep, ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def default_roots() -> Tuple[str, List[str]]:
    """(repo_root, scan paths): the installed ray_tpu package plus the
    repo-root driver scripts when present."""
    pkg = os.path.dirname(_HERE)          # .../ray_tpu
    repo = os.path.dirname(pkg)           # the dir holding the package
    paths = [pkg]
    for extra in ("bench.py", "__graft_entry__.py"):
        p = os.path.join(repo, extra)
        if os.path.exists(p):
            paths.append(p)
    return repo, paths


def iter_py_files(paths: List[str]):
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for f in sorted(filenames):
                if f.endswith(".py"):
                    yield os.path.join(dirpath, f)


def lint_paths(paths: List[str], root: str,
               families: Tuple[str, ...] = FAMILIES) -> List[Finding]:
    run_jax = "jax" in families
    run_conc = "concurrency" in families
    run_dist = "dist" in families
    run_res = "res" in families
    run_chan = "chan" in families
    if run_jax:
        from ray_tpu.devtools import jaxlint  # deferred: jaxlint imports us
    if run_dist:
        from ray_tpu.devtools import distlint  # deferred: ditto
    if run_res:
        from ray_tpu.devtools import reslint  # deferred: ditto
    if run_chan:
        from ray_tpu.devtools import chanlint  # deferred: ditto
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
        except (OSError, UnicodeDecodeError):
            continue
        rel = os.path.relpath(path, root)
        module = _module_for(path, root)
        rows: List[Finding] = []
        # ONE parse per file, shared by both families.
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError as e:
            tree = None
            # Reported whichever family runs: a jax-only run must not
            # silently skip (and exit 0 on) a file it could not check.
            rows.append(Finding("banned-api", rel, e.lineno or 1,
                                "", f"syntax error: {e.msg}"))
        if tree is not None:
            if run_conc:
                rows.extend(lint_source(source, module, rel, tree=tree))
            if run_jax:
                rows.extend(jaxlint.lint_source(source, module, rel,
                                                tree=tree))
            if run_dist:
                rows.extend(distlint.lint_source(source, module, rel,
                                                 tree=tree))
            if run_res:
                rows.extend(reslint.lint_source(source, module, rel,
                                                tree=tree))
            if run_chan:
                rows.extend(chanlint.lint_source(source, module, rel,
                                                 tree=tree))
        findings.extend(rows)  # both linters already emit rel paths
    return findings


def _read_baseline_json(path: str) -> Optional[dict]:
    """The parsed baseline dict, or None when the file is missing,
    unparseable, or not a JSON object — callers must distinguish
    "nothing there" (recoverable) from "parsed fine but empty" ({})."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def load_baseline(path: str) -> Dict[str, dict]:
    """Merged fingerprint -> entry table across every family section.
    Reads both the sectioned v2 format and the flat v1 one (whose
    findings were all concurrency-family)."""
    data = _read_baseline_json(path) or {}
    if "families" in data:
        merged: Dict[str, dict] = {}
        for fam, section in data["families"].items():
            want = FAMILY_SCHEMA.get(fam)
            if want is not None and section.get("schema") != want:
                # Stale fingerprint scheme for THIS family: its entries
                # cannot match current fingerprints, so merging them
                # only hides the problem. Skipping the section makes
                # the mismatch loud (that family's debt reports as new
                # -> regenerate with --family <fam> --write-baseline)
                # while the OTHER family's section keeps working — the
                # isolation the per-family schema exists to provide.
                print(f"rtpu-lint: baseline section '{fam}' has schema "
                      f"{section.get('schema')!r}, current is {want}; "
                      f"ignoring it — regenerate with --family {fam} "
                      "--write-baseline", file=sys.stderr)
                continue
            merged.update(section.get("findings", {}))
        return merged
    return data.get("findings", {})


def write_baseline(path: str, findings: List[Finding],
                   families: Optional[Tuple[str, ...]] = None) -> None:
    """Write the sectioned (v2) baseline. With ``families`` given, ONLY
    those sections are regenerated — the other family's entries are
    carried over verbatim (the per-family analog of the partial-path
    hazard: a jax-only rewrite must never drop the concurrency debt)."""
    fams = tuple(families) if families else FAMILIES
    sections: Dict[str, dict] = {}
    existing = _read_baseline_json(path)
    if families and existing is None and os.path.exists(path):
        # The file exists but cannot be parsed: carrying "nothing" over
        # would silently drop the other family's entire debt — the same
        # truncation hazard the partial-path refusal guards. Refuse.
        # (A valid-but-empty '{}' baseline parses to a dict and is NOT
        # refused; a full rewrite never needs the old content at all.)
        raise ValueError(
            f"existing baseline {path} is unreadable/corrupt; a "
            "partial-family rewrite would drop every other family's "
            "entries — restore the file from version control (do NOT "
            "delete it: a partial write of a missing file also starts "
            "from nothing), or rerun without --family to regenerate "
            "every section")
    existing = existing or {}
    for fam, section in existing.get("families", {}).items():
        if fam not in fams:
            sections[fam] = section
    if "findings" in existing and "families" not in existing \
            and "concurrency" not in fams:
        # v1 file being partially rewritten: its flat findings ARE the
        # concurrency section.
        sections["concurrency"] = {
            "schema": FAMILY_SCHEMA["concurrency"],
            "findings": existing["findings"]}
    tables: Dict[str, Dict[str, dict]] = {fam: {} for fam in fams}
    for f in findings:
        fam = RULE_FAMILY.get(f.rule, "concurrency")
        if fam not in tables:
            continue
        entry = tables[fam].setdefault(f.fingerprint(), {
            "count": 0, "rule": f.rule, "path": f.path,
            "message": f.message})
        entry["count"] += 1
    for fam in fams:
        sections[fam] = {"schema": FAMILY_SCHEMA.get(fam, 1),
                         "findings": dict(sorted(tables[fam].items()))}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 2,
                   "note": "legacy findings tracked-not-fatal, "
                           "sectioned per rule family; regenerate with "
                           "python -m ray_tpu.devtools.lint "
                           "--write-baseline [--family X]",
                   "families": dict(sorted(sections.items()))},
                  fh, indent=1, sort_keys=False)
        fh.write("\n")


def new_findings(findings: List[Finding],
                 baseline: Dict[str, dict]) -> List[Finding]:
    """Findings whose per-fingerprint count exceeds the baseline's."""
    budget = {fp: e.get("count", 0) for fp, e in baseline.items()}
    out = []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
        else:
            out.append(f)
    return out


def run(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the ray_tpu "
                        "package + repo-root driver scripts)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline JSON (default: the packaged one)")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from this run's findings "
                        "(with --family: only that family's section)")
    p.add_argument("--family", choices=("all",) + FAMILIES,
                   default="all",
                   help="rule family to run (default: all)")
    p.add_argument("--all", action="store_true",
                   help="print baselined findings too, not just new")
    p.add_argument("--stats", action="store_true",
                   help="print per-rule finding counts")
    args = p.parse_args(argv)

    families = FAMILIES if args.family == "all" else (args.family,)
    root, roots = default_roots()
    paths = args.paths or roots
    findings = lint_paths(paths, root, families=families)

    if args.stats:
        # One table: family / rule / current findings / baselined
        # budget — the at-a-glance debt readout per family. Purely
        # informational; the exit code below is unchanged by --stats.
        counts: Dict[str, int] = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        base_counts: Dict[str, int] = {}
        data = _read_baseline_json(args.baseline) or {}
        sections = data.get("families", {})
        if not sections and "findings" in data:  # v1 flat = concurrency
            sections = {"concurrency": {"findings": data["findings"]}}
        for section in sections.values():
            for entry in section.get("findings", {}).values():
                rule = entry.get("rule", "?")
                base_counts[rule] = (base_counts.get(rule, 0)
                                     + entry.get("count", 0))
        print(f"{'family':12s} {'rule':36s} {'found':>6s} "
              f"{'baseline':>9s}")
        for fam in families:
            fam_found = fam_base = 0
            for rule in FAMILY_RULES[fam]:
                n, b = counts.get(rule, 0), base_counts.get(rule, 0)
                fam_found += n
                fam_base += b
                print(f"{fam:12s} {rule:36s} {n:6d} {b:9d}")
            print(f"{fam:12s} {'TOTAL':36s} {fam_found:6d} "
                  f"{fam_base:9d}")

    if args.write_baseline:
        if args.paths and (os.path.abspath(args.baseline)
                           == os.path.abspath(DEFAULT_BASELINE)):
            # A partial scan must never truncate the repo baseline: the
            # next full run would report every other legacy finding as
            # new and fail tier-1.
            print("refusing --write-baseline of the packaged baseline "
                  "from an explicit path list (it would drop every "
                  "finding outside those paths); rerun with no paths, "
                  "or pass --baseline <other-file>", file=sys.stderr)
            return 2
        try:
            write_baseline(args.baseline, findings,
                           families=None if args.family == "all"
                           else families)
        except ValueError as e:
            print(f"refusing --write-baseline: {e}", file=sys.stderr)
            return 2
        print(f"baseline written: {len(findings)} findings "
              f"({'+'.join(families)}) -> {args.baseline}")
        return 0

    baseline = load_baseline(args.baseline)
    fresh = new_findings(findings, baseline)
    if args.all:
        for f in findings:
            mark = "NEW " if f in fresh else "base"
            print(f"[{mark}] {f}")
    else:
        for f in fresh:
            print(f"NEW: {f}")
    n_base = len(findings) - len(fresh)
    print(f"rtpu-lint: {len(findings)} findings "
          f"({n_base} baselined, {len(fresh)} new)")
    if fresh:
        print("new findings fail the lint — fix them, suppress with "
              "'# rtpu-lint: disable=<rule>', or (for an accepted "
              "legacy-style debt) --write-baseline")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(run())
