"""Deterministic, scriptable RPC fault injection.

Parity target: the reference's scripted RPC chaos (reference:
src/ray/rpc/rpc_chaos.h — RAY_testing_rpc_failure's
``method=N:req_prob:resp_prob`` grammar plus the Node/Worker killer
actors in _private/test_utils.py), redesigned as one seeded plan every
process of a cluster parses identically from ``RTPU_CHAOS_PLAN``.

The blind ``rpc_chaos_failure_prob`` coin flip exercises retry paths but
can never *reproduce* a failure: the interesting bugs live at specific
(method, process, nth-call) points — the head dying while the 2nd actor
registration is on the wire, the holder node dying while serving chunk 2
of a pull. A ``FaultPlan`` pins faults to exactly those points.

Plan grammar (``RTPU_CHAOS_PLAN`` env var / ``chaos_plan`` config flag;
worker/head/node processes inherit the env export)::

    plan   := rule [';' rule]...
    rule   := action [':' key '=' value]...
    action := drop_request | drop_response | delay | sever | kill

    keys (all optional):
      method=<glob>   rpc method name, fnmatch glob        (default *)
      role=<glob>     receiving process's role: head, node,
                      worker, driver, client                (default *)
      peer=<glob>     remote peer "ip:port" of the connection (default *;
                      colons inside a value are fine — a ':'-piece with
                      no '=' is folded into the preceding value)
      nth=<n>         fire on the n-th matching call only (1-based,
                      counted per process per rule)
      after=<n>       fire on every matching call after the first n
      count=<k>       fire at most k times (default: 1 when nth is
                      given, else unlimited)
      prob=<p>        fire with probability p per matching call, from
                      the rule's own seeded RNG (reproducible)
      seed=<s>        per-rule RNG seed for prob (default: plan seed)
      secs=<s>        delay duration (delay action only, default 0.2)
      side=<request|response>  which half the fault hits (delay/sever/
                      kill; drop_request/drop_response imply theirs)

Actions, applied at the RECEIVING server's dispatch point (a dropped
request and a request lost in transit are indistinguishable to the
sender):

    drop_request    the request frame is lost before the handler runs
    drop_response   the handler runs; its reply frame is lost
    delay           sleep ``secs`` before the handler / reply
    sever           shutdown() the peer connection (both directions die
                    mid-call; the client sees ConnectionLost)
    kill            SIGKILL the CURRENT process — scope with ``role=``
                    (e.g. ``kill:role=head:method=register_actor:nth=2``
                    takes the head down exactly as the 2nd registration
                    arrives)

Examples::

    # Head dies receiving the 2nd actor registration; a respawned head
    # (fresh process = fresh counters) survives the retry.
    RTPU_CHAOS_PLAN='kill:role=head:method=register_actor:nth=2'

    # The holder node dies serving chunk 2 of an object pull.
    RTPU_CHAOS_PLAN='kill:role=node:method=fetch_object:nth=2'

    # Lose the first two kill_actor acks (the zombie-actor scenario).
    RTPU_CHAOS_PLAN='drop_response:role=worker:method=kill_actor:count=2'

    # Seeded 10% request loss on every idempotent control RPC at the
    # head + 300ms delay on every heartbeat.
    RTPU_CHAOS_PLAN='drop_request:role=head:prob=0.1:seed=7;delay:method=heartbeat:secs=0.3'

Counters are per (process, rule): every process parses the plan at
first use and counts its OWN matching calls, so ``nth`` is deterministic
wherever request routing is (and a respawned process re-arms the plan —
scenario plans use ``nth=2``-style rules so the respawned incarnation
survives its retry traffic).

Zero overhead when off: ``chaos_enabled()`` is one config read; nothing
else is imported into the dispatch path.
"""

from __future__ import annotations

import fnmatch
import os
import random
import signal
import threading
import time
from typing import List, Optional

from ray_tpu.core.config import GLOBAL_CONFIG as cfg

ACTIONS = ("drop_request", "drop_response", "delay", "sever", "kill")

#: decide() verdicts consumed by the protocol hook.
DROP = "drop"
SEVER = "sever"


class ChaosPlanError(ValueError):
    """Malformed RTPU_CHAOS_PLAN string."""


class FaultRule:
    __slots__ = ("action", "method", "role", "peer", "nth", "after",
                 "count", "prob", "secs", "side", "_rng", "_matched",
                 "_fired", "_lock")

    def __init__(self, action: str, method: str = "*", role: str = "*",
                 peer: str = "*", nth: Optional[int] = None,
                 after: Optional[int] = None, count: Optional[int] = None,
                 prob: Optional[float] = None, seed: Optional[int] = None,
                 secs: float = 0.2, side: Optional[str] = None):
        if action not in ACTIONS:
            raise ChaosPlanError(
                f"unknown chaos action {action!r} (want one of "
                f"{'/'.join(ACTIONS)})")
        if side not in (None, "request", "response"):
            raise ChaosPlanError(f"bad side={side!r}")
        self.action = action
        self.method = method
        self.role = role
        self.peer = peer
        self.nth = nth
        self.after = after
        if count is None and nth is not None:
            count = 1  # an nth rule is a one-shot unless told otherwise
        self.count = count
        self.prob = prob
        self.secs = secs
        if side is None:
            side = ("response" if action == "drop_response" else "request")
        self.side = side
        self._rng = random.Random(seed if seed is not None else 0)
        self._matched = 0  # matching (role, method, side) events seen
        self._fired = 0
        self._lock = threading.Lock()

    def decide(self, role: str, method: str, side: str,
               peer: str = "") -> bool:
        """Does this rule fire for this event? Advances counters."""
        if side != self.side:
            return False
        if not fnmatch.fnmatchcase(method, self.method):
            return False
        if not fnmatch.fnmatchcase(role or "", self.role):
            return False
        if self.peer != "*" and not fnmatch.fnmatchcase(peer or "",
                                                        self.peer):
            return False
        with self._lock:
            if self.count is not None and self._fired >= self.count:
                return False
            self._matched += 1
            if self.nth is not None and self._matched != self.nth:
                return False
            if self.after is not None and self._matched <= self.after:
                return False
            if self.prob is not None and self._rng.random() >= self.prob:
                return False
            self._fired += 1
            return True

    def __repr__(self):
        keys = []
        for k in ("method", "role", "peer"):
            v = getattr(self, k)
            if v != "*":
                keys.append(f"{k}={v}")
        for k in ("nth", "after", "count", "prob"):
            v = getattr(self, k)
            if v is not None:
                keys.append(f"{k}={v}")
        if self.action == "delay":
            keys.append(f"secs={self.secs}")
        return ":".join([self.action] + keys)


class FaultPlan:
    """An ordered list of FaultRules parsed from the plan string."""

    def __init__(self, rules: List[FaultRule], source: str = ""):
        self.rules = rules
        self.source = source

    @classmethod
    def parse(cls, text: str, default_seed: int = 0) -> "FaultPlan":
        rules: List[FaultRule] = []
        for i, raw in enumerate(t for t in text.split(";") if t.strip()):
            parts = raw.strip().split(":")
            action = parts[0].strip()
            # ':' separates rule parts AND appears inside values
            # (peer=127.0.0.1:9000): a split piece with no '=' belongs
            # to the preceding value.
            merged: List[str] = []
            for p in parts[1:]:
                if "=" not in p and merged:
                    merged[-1] += ":" + p
                else:
                    merged.append(p)
            kw: dict = {}
            for p in merged:
                if "=" not in p:
                    raise ChaosPlanError(
                        f"chaos rule {raw!r}: expected key=value, got "
                        f"{p!r}")
                k, v = p.split("=", 1)
                k = k.strip()
                v = v.strip()
                if k in ("nth", "after", "count", "seed"):
                    kw[k] = int(v)
                elif k in ("prob", "secs"):
                    kw[k] = float(v)
                elif k in ("method", "role", "peer", "side"):
                    kw[k] = v
                else:
                    raise ChaosPlanError(
                        f"chaos rule {raw!r}: unknown key {k!r}")
            # Distinct default seed per rule position: two prob rules
            # must not mirror each other's coin flips.
            kw.setdefault("seed", default_seed * 1000 + i)
            rules.append(FaultRule(action, **kw))
        return cls(rules, source=text)

    def actions_for(self, role: str, method: str, side: str,
                    peer: str = "") -> List[FaultRule]:
        return [r for r in self.rules
                if r.decide(role, method, side, peer)]


# ------------------------------------------------------------- process API

_plan_lock = threading.Lock()
_plan_cache: Optional[FaultPlan] = None
_plan_cache_key: Optional[str] = None


def chaos_enabled() -> bool:
    """One config read — the dispatch fast path's only cost when off."""
    return bool(cfg.chaos_plan) or cfg.rpc_chaos_failure_prob > 0


def current_plan() -> Optional[FaultPlan]:
    """The process's parsed plan (re-parsed when the config string
    changes, so tests can cfg.set a new plan mid-process; counters reset
    with it)."""
    global _plan_cache, _plan_cache_key
    text = cfg.chaos_plan
    if not text:
        if _plan_cache_key is not None:
            # Forget the parsed plan when the flag clears: re-arming the
            # SAME plan string later must start with fresh counters, not
            # the previous run's spent rules.
            with _plan_lock:
                _plan_cache = None
                _plan_cache_key = None
        return None
    if text == _plan_cache_key:
        return _plan_cache
    with _plan_lock:
        if text != _plan_cache_key:
            try:
                _plan_cache = FaultPlan.parse(
                    text, default_seed=cfg.chaos_seed)
            except ChaosPlanError as e:
                # current_plan() runs inside every server's dispatch:
                # raising here would crash EVERY RPC in every process of
                # the cluster with a cryptic error. Report loudly once
                # and run with chaos disabled instead — the scenario
                # then fails its fault assertions, which points at the
                # plan, not at a dead cluster.
                print(f"RTPU_CHAOS: invalid plan {text!r} disabled: {e}",
                      flush=True)
                _plan_cache = None
            _plan_cache_key = text
    return _plan_cache


def _kill_self() -> None:  # monkeypatched by unit tests
    os.kill(os.getpid(), signal.SIGKILL)


def apply(role: str, method: str, side: str, conn=None) -> Optional[str]:
    """Run the plan against one RPC event. Returns DROP when the frame
    should be lost, SEVER when the connection was shut down (the caller
    must stop using it), None to proceed. Side effects (sleep, socket
    shutdown, SIGKILL) happen here."""
    plan = current_plan()
    if plan is None:
        return None
    verdict = None
    for rule in plan.actions_for(role, method, side,
                                 peer=_peer_of(conn)):
        if rule.action == "kill":
            print(f"RTPU_CHAOS: kill ({rule!r}) on {method} [{side}]",
                  flush=True)
            # SIGKILL leaves no trace: dump the flight-recorder ring
            # first so the scenario's post-mortem has the seconds
            # before this death (best-effort; never blocks the kill).
            try:
                from ray_tpu.util import flight_recorder as _flight

                path = _flight.dump_to_file(reason=f"chaos-kill:{method}")
                if path:
                    print(f"RTPU_CHAOS: flight dump {path}", flush=True)
            except Exception as e:  # noqa: BLE001 — never block the kill
                print(f"RTPU_CHAOS: flight dump failed: {e!r}",
                      flush=True)
            _kill_self()
            return DROP  # only reachable under the unit-test monkeypatch
        if rule.action == "delay":
            time.sleep(rule.secs)
        elif rule.action == "sever":
            if conn is not None:
                from ray_tpu.cluster.protocol import _shutdown_socket

                _shutdown_socket(conn.sock)
            verdict = SEVER
        elif rule.action in ("drop_request", "drop_response"):
            if verdict is None:
                verdict = DROP
    return verdict


def _peer_of(conn) -> str:
    if conn is None:
        return ""
    try:
        host, port = conn.sock.getpeername()[:2]
        return f"{host}:{port}"
    except OSError:
        return ""


# --------------------------------------------------------------- scenarios
#
# Scripted multi-step failure scenarios that need ORCHESTRATION, not just
# an injected fault: the rule grammar above breaks one RPC at one point;
# a rolling upgrade is a planned sequence (drain -> snapshot -> port
# handover -> re-converge) whose acceptance criterion is measured on the
# CLIENT side. Drivers live here so tests and bench.py run the identical
# scenario.


def run_rolling_upgrade(runtime, request_fn, clients: int = 2,
                        pre_s: float = 0.5, settle_s: float = 1.0) -> dict:
    """Rolling head-upgrade scenario: continuous client load across a
    drain -> sqlite-checkpoint -> old head releases the port -> new
    incarnation binds and serves handover
    (ClusterRuntime.rolling_head_upgrade).

    ``request_fn(i)`` is one client request returning a result or
    raising; it runs in ``clients`` threads before, during, and after
    the swap. Acceptance is ZERO raised requests — elevated latency is
    expected (requests issued in the gap ride their retry loops), a
    failure is not. Returns the upgrade report plus
    requests_ok / request_failures / max_request_s."""
    stop = threading.Event()
    lock = threading.Lock()
    stats = {"ok": 0, "failures": [], "max_s": 0.0}

    def client_loop(ci: int) -> None:
        i = 0
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                request_fn(ci * 1_000_000 + i)
                with lock:
                    stats["ok"] += 1
                    stats["max_s"] = max(stats["max_s"],
                                         time.monotonic() - t0)
            except Exception as e:  # noqa: BLE001 — the scenario verdict
                with lock:
                    stats["failures"].append(repr(e)[:200])
            i += 1

    threads = [threading.Thread(target=client_loop, args=(ci,),
                                daemon=True, name=f"upgrade-load-{ci}")
               for ci in range(clients)]
    for t in threads:
        t.start()
    try:
        time.sleep(pre_s)  # load established before the swap begins
        report = dict(runtime.rolling_head_upgrade())
        time.sleep(settle_s)  # catch straggler failures after the swap
    finally:
        # A failed swap must still stop the load threads: left running
        # they hammer the (possibly torn-down) runtime forever and grow
        # stats['failures'] without bound.
        stop.set()
        for t in threads:
            t.join(timeout=60)
    with lock:
        report["requests_ok"] = stats["ok"]
        report["request_failures"] = list(stats["failures"])
        report["max_request_s"] = round(stats["max_s"], 3)
    return report
