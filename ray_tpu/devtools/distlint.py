"""dist-lint: distributed RPC-contract rules (rule family ``dist``).

Stdlib-only AST analysis riding rtpu-lint's fingerprint/baseline/
``# rtpu-lint: disable=<rule>`` machinery (``lint.py`` runs all three
rule families from one CLI). Every rule codifies a protocol bug this
repo actually shipped and found by hand in post-review:

  unclassified-rpc-handler
      a ``def rpc_<m>`` on a server class where ``<m>`` appears in
      neither ``protocol.RETRY_SAFE_RPCS`` (any recovery group) nor
      ``protocol.NON_RETRYABLE_RPCS`` — its retry/idempotency semantics
      are undeclared. PRs 8-10 each grew the hand-maintained set as a
      review afterthought ("RETRY_SAFE_RPCS += trace_tail/..."); before
      ROADMAP item 3 replays RPCs by design, forgetting to classify
      must be a lint failure, not a review catch.
  retry-unsafe-call
      ``<client>.retrying_call("<m>", ...)`` where ``<m>`` is not
      declared retry-safe: the caller re-delivers a request whose
      handler never promised at-most-once.
  direct-notify-bypasses-outbox
      a direct ``notify``/``call`` of an object-directory method
      (``object_added``/``object_removed``/``object_batch``) from a
      module that owns a batched outbox, outside its designated sender
      — the PR 4 round-2 bug: the direct frame overtakes the same
      process's still-queued add and the directory goes permanently
      stale.
  serial-fanout-no-deadline
      a loop issuing blocking per-peer RPCs with no total deadline, no
      bounded iteration, and no concurrency — the PR 8
      ``rpc_cluster_leases`` bug: N mid-death nodes x one control
      timeout each outran every caller's own deadline.
  wall-clock-deadline
      ``time.time()`` feeding deadline/timeout arithmetic or
      comparisons — an NTP step mid-wait stretches or collapses the
      window; ``time.monotonic()`` is required. Plain timestamping
      (span starts, cross-process freshness stamps, which NEED the
      epoch clock) is exempt.
  missing-chaos-role
      an RPC-handler class with no ``chaos_role`` declaration (class
      attribute or ``self.chaos_role = ...``) and no known role-setting
      base: the server silently opts out of every role-targeted chaos
      plan (``kill:role=head:...`` never fires on it).
  retry-unsafe-block-rpc
      a lease-block handler (``rpc_lease_block_*``) whose method is
      classified but NOT retry-safe. Blocks are leases: their grant/
      renew/install/revoke RPCs are retried by owners and double-
      delivered by the RTPU_DEBUG_RPC witness, so a non-idempotent
      classification means a retried grant double-installs admission
      budget and the lease census never drains to zero. Unclassified
      block handlers are caught by unclassified-rpc-handler; this rule
      closes the other gap (classified, but on the wrong side).

Classification sets are read from the linted source itself when it
declares them (fixtures), else statically from the repo's
``cluster/protocol.py`` — the linter never imports the runtime.
``lint_source(source, module, path)`` returns ``lint.Finding`` rows;
module-scoped tables live in ``invariants.py``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ray_tpu.devtools import invariants as inv
# DIST_RULES is single-sourced in lint.py (the family/baseline
# machinery keys on it); aliased here so rule code and rule registry
# can't drift.
from ray_tpu.devtools.lint import (DIST_RULES as RULES, Finding, _dotted,
                                   suppressed)

#: Names in protocol.py whose module-level set/frozenset assignments
#: contribute to the classification tables.
_SET_NAMES = {
    "READONLY_RPCS", "IDEMPOTENT_RPCS", "ACKED_RETRY_RPCS",
    "RETRY_SAFE_RPCS", "NON_RETRYABLE_RPCS",
}


def _literal_strings(node: ast.AST) -> Optional[FrozenSet[str]]:
    """A set/list/tuple literal of string constants, else None."""
    if isinstance(node, (ast.Set, ast.List, ast.Tuple)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, str)):
                return None
            out.append(e.value)
        return frozenset(out)
    return None


def extract_classification_sets(tree: ast.AST) -> Dict[str, FrozenSet[str]]:
    """Module-level RPC classification sets, resolved statically:
    ``X = frozenset({...})``, ``X = {...}``, and unions of
    already-resolved names (``A | B | C``)."""
    resolved: Dict[str, FrozenSet[str]] = {}

    def value_of(node: ast.AST) -> Optional[FrozenSet[str]]:
        lit = _literal_strings(node)
        if lit is not None:
            return lit
        if isinstance(node, ast.Call):
            fn = _dotted(node.func) or ""
            if fn.rsplit(".", 1)[-1] in ("frozenset", "set") and \
                    len(node.args) == 1:
                return _literal_strings(node.args[0])
            return None
        if isinstance(node, ast.Name):
            return resolved.get(node.id)
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
            left = value_of(node.left)
            right = value_of(node.right)
            if left is not None and right is not None:
                return left | right
        return None

    for stmt in getattr(tree, "body", []):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            name = stmt.targets[0].id
            if name in _SET_NAMES:
                val = value_of(stmt.value)
                if val is not None:
                    resolved[name] = val
    return resolved


_REPO_SETS: Optional[Tuple[FrozenSet[str], FrozenSet[str]]] = None


def _protocol_sets() -> Tuple[FrozenSet[str], FrozenSet[str]]:
    """(retry_safe, non_retryable) from the repo's protocol.py, parsed
    statically ONCE (the linter must work — and agree with itself —
    without importing the runtime)."""
    global _REPO_SETS
    if _REPO_SETS is not None:
        return _REPO_SETS
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "cluster", "protocol.py")
    retry_safe: FrozenSet[str] = frozenset()
    non_retryable: FrozenSet[str] = frozenset()
    try:
        with open(path, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=path)
        sets = extract_classification_sets(tree)
        retry_safe = sets.get("RETRY_SAFE_RPCS", frozenset())
        non_retryable = sets.get("NON_RETRYABLE_RPCS", frozenset())
    except (OSError, SyntaxError):
        pass  # no sets -> every handler reports unclassified, loudly
    _REPO_SETS = (retry_safe, non_retryable)
    return _REPO_SETS


def _reset_repo_sets_cache() -> None:
    """Test hook: forget the parsed protocol.py sets."""
    global _REPO_SETS
    _REPO_SETS = None


class _DistLinter:
    def __init__(self, module: str, path: str, source: str):
        self.module = module
        self.path = path
        self.lines = source.splitlines()
        self.findings: List[Finding] = []
        self._scope: List[str] = []
        self._fn_stack: List[ast.AST] = []

    # ------------------------------------------------------------ utils

    def _emit(self, rule: str, node: ast.AST, message: str,
              scope: Optional[str] = None) -> None:
        assert rule in RULES, f"unregistered dist rule id {rule!r}"
        line = getattr(node, "lineno", 1)
        if suppressed(self.lines, line, rule):
            return
        self.findings.append(Finding(
            rule, self.path, line,
            scope if scope is not None else ".".join(self._scope),
            message))

    # ------------------------------------------------------------- walk

    def run(self, tree: Optional[ast.AST] = None) -> List[Finding]:
        if tree is None:
            try:
                tree = ast.parse("\n".join(self.lines),
                                 filename=self.path)
            except SyntaxError:
                return []  # the concurrency family reports this
        local = extract_classification_sets(tree)
        if local:
            retry_safe = local.get("RETRY_SAFE_RPCS", frozenset())
            if not retry_safe:
                retry_safe = (local.get("READONLY_RPCS", frozenset())
                              | local.get("IDEMPOTENT_RPCS", frozenset())
                              | local.get("ACKED_RETRY_RPCS",
                                          frozenset()))
            non_retryable = local.get("NON_RETRYABLE_RPCS", frozenset())
        else:
            retry_safe, non_retryable = _protocol_sets()
        self._retry_safe = retry_safe
        self._classified = retry_safe | non_retryable
        self._walk(tree)
        return self.findings

    def _walk(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scope.append(child.name)
                self._fn_stack.append(child)
                self._check_retry_unsafe_calls(child)
                self._check_wall_clock(child)
                self._walk(child)
                self._fn_stack.pop()
                self._scope.pop()
                continue
            if isinstance(child, ast.ClassDef):
                self._scope.append(child.name)
                self._check_server_class(child)
                self._walk(child)
                self._scope.pop()
                continue
            if isinstance(child, (ast.For, ast.While)):
                self._check_serial_fanout(child)
            if isinstance(child, ast.Call):
                self._check_outbox_bypass(child)
            self._walk(child)

    # --------------------------------------------- handler classification

    def _check_server_class(self, cls: ast.ClassDef) -> None:
        handlers = [stmt for stmt in cls.body
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                    and stmt.name.startswith("rpc_")]
        if not handlers:
            return
        # Class-local declarations (servers outside the control plane —
        # test fixtures, plugin servers — declare their own methods
        # instead of growing protocol.py; the RTPU_DEBUG_RPC witness
        # honors the same attributes).
        local: Set[str] = set()
        local_safe: Set[str] = set()
        for stmt in cls.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id in (
                        "extra_retry_safe_rpcs", "extra_idempotent_rpcs",
                        "extra_non_retryable_rpcs"):
                val = stmt.value
                if isinstance(val, ast.Call) and val.args:
                    val = val.args[0]
                lit = _literal_strings(val)
                if lit:
                    local.update(lit)
                    if stmt.targets[0].id != "extra_non_retryable_rpcs":
                        local_safe.update(lit)
        for h in handlers:
            method = h.name[len("rpc_"):]
            if method not in self._classified and method not in local:
                self._emit(
                    "unclassified-rpc-handler", h,
                    f"handler '{h.name}' serves method '{method}' which "
                    "is in neither RETRY_SAFE_RPCS nor "
                    "NON_RETRYABLE_RPCS — declare its retry/idempotency "
                    "semantics in cluster/protocol.py (re-delivery and "
                    "blind chaos drops key on that contract)")
            elif (method.startswith("lease_block_")
                    and method not in self._retry_safe
                    and method not in local_safe):
                self._emit(
                    "retry-unsafe-block-rpc", h,
                    f"lease-block handler '{h.name}' is classified "
                    "non-retryable — block grant/renew/install/revoke "
                    "must be retry-safe (owners retry them and the "
                    "RTPU_DEBUG_RPC witness double-delivers them; a "
                    "non-idempotent grant double-installs admission "
                    "budget and leaks the lease census)")
        self._check_chaos_role(cls)

    def _check_chaos_role(self, cls: ast.ClassDef) -> None:
        for base in cls.bases:
            d = _dotted(base) or ""
            if d.rsplit(".", 1)[-1] in inv.CHAOS_ROLE_BASES:
                return  # base's __init__ sets the role
        for sub in ast.walk(cls):
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name) and \
                            tgt.id == "chaos_role":
                        return  # class attribute
                    if isinstance(tgt, ast.Attribute) and \
                            tgt.attr == "chaos_role" and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        return  # set in __init__
            elif isinstance(sub, ast.AnnAssign):
                tgt = sub.target
                if isinstance(tgt, ast.Name) and tgt.id == "chaos_role":
                    return
        self._emit(
            "missing-chaos-role", cls,
            f"RPC-handler class '{cls.name}' declares no chaos_role — "
            "role-targeted fault plans (kill:role=...:...) silently "
            "skip this server; set a class-level chaos_role")

    # ------------------------------------------------- retry-unsafe calls

    def _check_retry_unsafe_calls(self, fn) -> None:
        """Within one function: ``x.retrying_call("<m>", ...)`` with
        ``<m>`` not declared retry-safe. Constant method names are
        checked directly; a Name argument is resolved through simple
        same-function string bindings (including conditional ones)."""
        str_bindings: Dict[str, Set[str]] = {}
        calls: List[Tuple[ast.Call, ast.AST]] = []
        todo = list(ast.iter_child_nodes(fn))
        while todo:
            sub = todo.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                vals = self._possible_strings(sub.value)
                if vals:
                    str_bindings.setdefault(
                        sub.targets[0].id, set()).update(vals)
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "retrying_call" and sub.args:
                calls.append((sub, sub.args[0]))
            todo.extend(ast.iter_child_nodes(sub))
        for call, arg in calls:
            names: Set[str] = set()
            if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                            str):
                names = {arg.value}
            elif isinstance(arg, ast.Name):
                names = str_bindings.get(arg.id, set())
            for m in sorted(names):
                if m not in self._retry_safe:
                    self._emit(
                        "retry-unsafe-call", call,
                        f"retrying_call('{m}') but '{m}' is not in "
                        "RETRY_SAFE_RPCS — retrying re-delivers a "
                        "request whose handler never promised "
                        "at-most-once; classify the method or stop "
                        "retrying it")

    @staticmethod
    def _possible_strings(node: ast.AST) -> Set[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return {node.value}
        if isinstance(node, ast.IfExp):
            return (_DistLinter._possible_strings(node.body)
                    | _DistLinter._possible_strings(node.orelse))
        return set()

    # ------------------------------------------------- outbox discipline

    def _check_outbox_bypass(self, node: ast.Call) -> None:
        allowed = inv.OUTBOX_OWNER_MODULES.get(self.module)
        if allowed is None:
            return
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in ("notify", "call", "retrying_call",
                                       "call_async")
                and node.args):
            return
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)
                and arg.value in inv.OUTBOX_METHODS):
            return
        fn_scope = self._scope[-1] if self._scope else "<module>"
        if fn_scope in allowed:
            return
        self._emit(
            "direct-notify-bypasses-outbox", node,
            f"direct {node.func.attr}('{arg.value}') outside the "
            f"designated outbox sender ({'/'.join(sorted(allowed))}) — "
            "this frame can overtake the same process's still-queued "
            "add/remove of the same object (the PR 4 stale-directory "
            "inversion); enqueue through the outbox instead")

    # --------------------------------------------------- serial fan-outs

    def _check_serial_fanout(self, loop) -> None:
        if self.module not in inv.DIST_FANOUT_MODULES:
            return
        # Walk THIS loop only; nested defs run on their own schedule
        # (and a thread target's blocking call is the concurrency FIX,
        # not the bug). A blocking call whose enclosing try's handlers
        # all EXIT the loop (break/return/raise) is escape-on-failure —
        # the loop cannot keep paying timeouts peer after peer, which
        # is the shape this rule hunts (the PR 8 census caught, logged,
        # and CONTINUED to the next dead node).
        found: List[Tuple[str, bool]] = []  # (label, guarded)
        concurrent = [False]

        def handler_exits(t: ast.Try) -> bool:
            if not t.handlers:
                return False
            return all(any(isinstance(s, (ast.Break, ast.Return,
                                          ast.Raise))
                           for s in ast.walk(h))
                       for h in t.handlers)

        def scan(n: ast.AST, guarded: bool) -> None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                return
            if isinstance(n, ast.Try):
                g = guarded or handler_exits(n)
                for s in n.body:
                    scan(s, g)
                for h in n.handlers:
                    for s in h.body:
                        scan(s, guarded)
                for s in list(n.orelse) + list(n.finalbody):
                    scan(s, guarded)
                return
            if isinstance(n, ast.Call):
                if isinstance(n.func, ast.Attribute):
                    attr = n.func.attr
                    dotted = _dotted(n.func) or ""
                    if attr in inv.FANOUT_RPC_ATTRS:
                        found.append((f".{attr}()", guarded))
                    if attr in inv.FANOUT_CONCURRENCY_ATTRS or \
                            dotted.endswith(inv.FANOUT_THREAD_SUFFIXES):
                        concurrent[0] = True
                elif isinstance(n.func, ast.Name) and \
                        n.func.id.endswith(inv.FANOUT_THREAD_SUFFIXES):
                    concurrent[0] = True
            for c in ast.iter_child_nodes(n):
                scan(c, guarded)

        for stmt in loop.body:
            scan(stmt, False)
        unguarded = [label for label, guarded in found if not guarded]
        if not unguarded or concurrent[0]:
            return
        blocking = unguarded[0]
        if self._loop_bounded(loop):
            return
        self._emit(
            "serial-fanout-no-deadline", loop,
            f"loop issues blocking {blocking} per peer with no total "
            "deadline, bounded iteration, or concurrency — N mid-death "
            "peers x one control timeout each outruns every caller's "
            "deadline (the PR 8 rpc_cluster_leases bug); add a total "
            "deadline or fan out concurrently")

    def _loop_bounded(self, loop) -> bool:
        """Bounded-total evidence: a constant-``range`` iteration, or a
        deadline-ish name / monotonic clock read anywhere in the
        ENCLOSING function (the bound usually lives just outside the
        loop, as in _create_pg_inner)."""
        if isinstance(loop, ast.For) and isinstance(loop.iter, ast.Call):
            d = _dotted(loop.iter.func) or ""
            if d == "range" and all(
                    isinstance(a, ast.Constant) for a in loop.iter.args):
                return True
        scope = self._fn_stack[-1] if self._fn_stack else loop
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Call):
                d = _dotted(sub.func) or ""
                if d in inv.RETRY_DEADLINE_CALLS:
                    # time.time counts as a bound here: using the wrong
                    # CLOCK is the wall-clock-deadline rule's report,
                    # not a second fan-out finding on the same loop.
                    return True
            name = None
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Attribute):
                name = sub.attr
            if name is not None and \
                    inv.RETRY_DEADLINE_NAME_RE.search(name):
                return True
        return False

    # ---------------------------------------------- wall-clock deadlines

    @staticmethod
    def _has_wall_clock_call(node: ast.AST) -> Optional[ast.Call]:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                d = _dotted(sub.func) or ""
                if d in ("time.time", "_time.time") or \
                        d.endswith(".time.time"):
                    return sub
        return None

    @staticmethod
    def _has_deadline_name(node: ast.AST) -> Optional[str]:
        for sub in ast.walk(node):
            name = None
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Attribute):
                name = sub.attr
            if name is not None and \
                    inv.WALLCLOCK_DEADLINE_NAME_RE.search(name):
                return name
        return None

    def _check_wall_clock(self, fn) -> None:
        """``time.time()`` feeding deadline arithmetic: assigned to a
        deadline-ish name, or sharing a BinOp/Compare with one. Bare
        timestamping (``t0 = time.time()``, span emission) is exempt."""
        flagged: Set[int] = set()

        def flag(call: ast.Call, how: str) -> None:
            if id(call) in flagged:
                return
            flagged.add(id(call))
            self._emit(
                "wall-clock-deadline", call,
                f"time.time() {how} — wall clock jumps under NTP steps; "
                "deadline/timeout arithmetic must use time.monotonic() "
                "(epoch timestamps for cross-process stamps are exempt "
                "and unflagged)")

        todo = list(ast.iter_child_nodes(fn))
        while todo:
            sub = todo.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(sub, ast.Assign):
                call = self._has_wall_clock_call(sub.value)
                if call is not None:
                    for tgt in sub.targets:
                        d = _dotted(tgt)
                        leaf = d.rsplit(".", 1)[-1] if d else None
                        if leaf is not None and \
                                inv.WALLCLOCK_DEADLINE_NAME_RE.search(
                                    leaf):
                            flag(call, f"assigned to deadline-like "
                                       f"name '{leaf}'")
            if isinstance(sub, ast.BinOp):
                for side, other in ((sub.left, sub.right),
                                    (sub.right, sub.left)):
                    call = self._has_wall_clock_call(side)
                    if call is not None:
                        name = self._has_deadline_name(other)
                        if name is not None:
                            flag(call, f"in arithmetic with "
                                       f"deadline-like name '{name}'")
            if isinstance(sub, ast.Compare):
                operands = [sub.left] + list(sub.comparators)
                for i, side in enumerate(operands):
                    call = self._has_wall_clock_call(side)
                    if call is None:
                        continue
                    for j, other in enumerate(operands):
                        if j == i:
                            continue
                        name = self._has_deadline_name(other)
                        if name is not None:
                            flag(call, f"compared against "
                                       f"deadline-like name '{name}'")
            todo.extend(ast.iter_child_nodes(sub))


def lint_source(source: str, module: str, path: str,
                tree: Optional[ast.AST] = None) -> List[Finding]:
    """Run the dist rule family over one module's source. ``tree``
    reuses a caller-side parse (lint_paths parses once per file for
    every family)."""
    return _DistLinter(module, path, source).run(tree)
