"""Codified concurrency + compatibility invariants for rtpu-lint.

Each table below is an invariant mined from a post-review finding in an
earlier PR; the linter (``lint.py``) enforces them, the README's
"Concurrency invariants & lint" section documents them for humans. Keep
the two in sync: a new invariant lands here FIRST, then in prose.

Module keys are dotted module names (``ray_tpu.cluster.node_manager``).
Lock names are the attribute/variable names as they appear in source
(``_zygote_lock`` matches ``self._zygote_lock`` and a bare
``_zygote_lock``).
"""

from __future__ import annotations

import re

# ---------------------------------------------------------------- locks

#: What counts as "a lock" when the linter sees ``with <expr>:`` or
#: ``<expr>.acquire()``. Condition variables count too: entering one
#: acquires its underlying lock.
LOCK_NAME_RE = re.compile(r"(lock|mutex|_cv|_cond|cond)$", re.IGNORECASE)

#: Declared acquisition order per module: within one chain, a lock may
#: only be acquired while holding locks that appear EARLIER in the
#: chain. Acquiring chain[i] while holding chain[j] (j > i) is a
#: lock-order violation. (PR 2: the zygote lock split — the fork
#: round-trip's pipe I/O runs under ``_zygote_io_lock`` with
#: ``_zygote_lock`` taken briefly inside it for handle lifecycle;
#: nesting them the other way re-creates the stop()-wedged-behind-a-
#: 60s-fork hang the split fixed.)
LOCK_ORDER: dict[str, list[list[str]]] = {
    "ray_tpu.cluster.node_manager": [
        ["_zygote_io_lock", "_zygote_lock"],
    ],
}
# (protocol's send-vs-pending rule lives in NEVER_NESTED below — an
# ordering chain needs two members to enforce anything.)

#: Lock groups that must NEVER be held together (any nesting, either
#: order). The Python-side analog of shm layout v2's "no op ever holds
#: two shard locks" rule (PR 4).
NEVER_NESTED: dict[str, list[set[str]]] = {
    "ray_tpu.cluster.worker_main": [
        {"_seen_lock", "_done_lock", "_hosted_lock", "order_lock"},
    ],
    "ray_tpu.cluster.protocol": [
        {"_send_lock", "_pending_lock"},
        {"send_lock", "_pending_lock"},
    ],
    "ray_tpu.core.cluster_core": [
        # Owner-side bookkeeping locks are leaves: holding two at once
        # is how the single-flusher/outbox races of PR 4 started.
        {"_obj_loc_lock", "_inflight_lock", "_lease_lock",
         "_obj_notify_flush_lock"},
    ],
    "ray_tpu.cluster.node_manager": [
        {"_lock", "_pull_lock"},
    ],
}

#: Locks that exist to SERIALIZE blocking I/O — the blocking-under-lock
#: rule does not apply to them (holding them during recv/sendmsg is the
#: point). Everything else holding a lock across the calls in
#: BLOCKING_METHODS/BLOCKING_FUNCS is a finding.
IO_LOCKS: dict[str, set[str]] = {
    "ray_tpu.cluster.protocol": {"send_lock", "_send_lock"},
    "ray_tpu.cluster.node_manager": {"_zygote_io_lock"},
}

#: Method names whose call under a (non-IO) lock blocks on the network,
#: a pipe, or a subprocess. ``.wait``/``.join`` are deliberately absent:
#: Condition.wait releases its lock and Thread.join under a lock is a
#: separate (ordering) problem.
BLOCKING_METHODS = {
    "recv", "recv_into", "recvmsg", "recvmsg_into", "recvfrom",
    "sendmsg", "sendall", "accept", "connect", "readline", "select",
    "retrying_call",
}

#: Dotted function names that block (subprocess round-trips, fork pipe
#: I/O). Matched against the full dotted call target.
BLOCKING_FUNCS = {
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "os.fork", "os.forkpty",
}

#: ``time.sleep(x)`` with a constant ``x`` strictly greater than this
#: (seconds) inside a ``with <lock>`` body is a finding.
SLEEP_UNDER_LOCK_MAX_S = 0.05

# ------------------------------------------------------------- sockets

#: Modules whose sockets feed ``recv_into`` sinks (caller-owned shm
#: views): a bare ``close()`` leaves a blocked reader alive and writing
#: into freed/reallocated memory — ``shutdown()`` is what wakes it
#: (PR 4 review rounds 1+2). Any ``<x>.close()`` where ``x`` looks like
#: a socket and has no earlier ``shutdown``/``_shutdown_socket`` in the
#: same function is flagged in these modules.
SOCKET_SHUTDOWN_MODULES = {
    "ray_tpu.cluster.protocol",
    "ray_tpu.cluster.node_manager",
    "ray_tpu.cluster.head",
    "ray_tpu.cluster.worker_main",
}

#: Variable-name heuristic for "this is a socket".
SOCKET_NAME_RE = re.compile(r"sock", re.IGNORECASE)

# ---------------------------------------------------------- banned APIs

#: jax<0.5 compatibility (this container ships jax<0.5): these calls /
#: imports silently break it. Use the compat shims instead.
#: dotted-call-suffix -> replacement hint.
BANNED_CALLS = {
    "jax.sharding.set_mesh":
        "use ray_tpu.parallel.mesh.mesh_context() (jax<0.5 has no "
        "set_mesh)",
    "sharding.set_mesh":
        "use ray_tpu.parallel.mesh.mesh_context() (jax<0.5 has no "
        "set_mesh)",
}

#: Module paths whose import is banned (jax<0.5 moved/renamed them).
#: import-path -> (replacement hint, exempt modules). The exempt module
#: IS the compat shim — it may import the real thing inside a guarded
#: fallback.
BANNED_IMPORTS = {
    "jax.experimental.shard_map": (
        "import shard_map via the ray_tpu.ops.ring_attention compat "
        "shim (the jax.experimental path is jax<0.5-only and moves in "
        "0.5+)",
        {"ray_tpu.ops.ring_attention"},
    ),
    "jax.shard_map": (
        "import shard_map via the ray_tpu.ops.ring_attention compat "
        "shim (top-level jax.shard_map does not exist before jax 0.5)",
        {"ray_tpu.ops.ring_attention"},
    ),
}

#: Modules that embed browser JS in Python strings: every occurrence of
#: these substrings in a string constant is flagged (the dashboard XSS
#: was fixed twice — PR 1 and PR 3 — before it became a rule).
#: substring -> hint.
DASHBOARD_MODULES = {"ray_tpu.util.dashboard"}
BANNED_JS_SUBSTRINGS = {
    "innerHTML":
        "prefer textContent; innerHTML is allowed only for fully "
        "esc()-disciplined markup (tracked in the baseline)",
    "document.write": "document.write executes markup; build nodes or "
                      "use textContent",
}

# ------------------------------------------------- unbounded retry loops

#: Call attributes that mark a ``while True:`` body as a RETRY loop for
#: the retry-without-deadline rule: a chaos run (dead peer, dropped
#: frames) hangs exactly in an unbounded loop around these.
RETRY_CALL_ATTRS = {"retrying_call"}
#: Dotted-call suffixes that open connections (retried connects are the
#: other unbounded-loop shape).
RETRY_CONNECT_SUFFIXES = {"create_connection"}
#: Socket-looking ``<x>.connect()`` also counts (SOCKET_NAME_RE on x).

#: Escape hatches: ANY of these anywhere in the loop subtree makes it
#: bounded. Clock reads / deadline-ish names / attempt counters, or a
#: stop-event check (daemon loops that exit on shutdown).
RETRY_DEADLINE_CALLS = {"time.monotonic", "time.time",
                        "time.perf_counter"}
RETRY_DEADLINE_NAME_RE = re.compile(
    r"(deadline|attempt|tries|retries|budget|remaining|elapsed)",
    re.IGNORECASE)
RETRY_STOP_NAME_RE = re.compile(r"(stop|shutdown|closed|done|exit)",
                                re.IGNORECASE)
RETRY_STOP_ATTRS = {"is_set", "wait"}

# ------------------------------------------------- unclosed tracing spans

#: util/tracing context-manager constructors: calling one WITHOUT using
#: it as a context manager (``with tracing.span(...)``, a name later
#: with-ed, or ``stack.enter_context(...)``) leaks the ContextVar
#: parentage — the span never ends, and every later span in the thread/
#: task silently parents under it. Attribute calls are matched when the
#: receiver looks like the tracing module (``tracing`` / ``_tracing``);
#: ``remote_span`` is unambiguous enough to match as a bare name too.
TRACING_SPAN_ATTRS = {"trace", "span", "remote_span"}
TRACING_SPAN_NAMES = {"remote_span"}
TRACING_RECEIVER_RE = re.compile(r"(^|_)tracing$")

# --------------------------------------------------------- bare excepts

#: Logging-ish call names that make a broad except "handled".
LOGGING_CALL_NAMES = {
    "debug", "info", "warning", "warn", "error", "exception",
    "critical", "log", "print_exc", "print_exception", "print",
    "capture_exception", "zlog",
}

#: Comment tokens that suppress a finding on their line.
SUPPRESS_TOKEN = "rtpu-lint: disable="
#: Existing `# noqa: BLE001` annotations mark audited broad excepts.
NOQA_BROAD_EXCEPT = "noqa: BLE001"

# ======================================================================
# JAX/XLA tracing-safety invariants (rule family "jax", jaxlint.py).
#
# Each table encodes a bug found BY HAND in post-review: PR 6's int8
# bench closed over a weight and jit constant-folded it to full width
# (the int8 win was unmeasurable); its dryrun read a donated buffer
# after the step; PR 3's verify window needed scratch rows because XLA
# CLAMPS out-of-range dynamic_update_slice starts; and the engine's
# one-host-sync-per-chunk discipline was asserted nowhere.
# ======================================================================

#: Call targets whose result is "an array" for the closure-capture rule:
#: a local/module binding whose RHS contains one of these is array-like,
#: and referencing it FREE inside a jitted function bakes it into the
#: program as a constant (PR 6: `jax.jit(lambda s: s @ wq.astype(...))`
#: constant-folded the int8 weight to full width — pass arrays as jit
#: ARGUMENTS). Prefixes match the start of the dotted call target,
#: suffixes its last component.
ARRAY_FACTORY_PREFIXES = (
    "jnp.", "np.", "numpy.", "jax.numpy.", "jax.random.", "lax.",
    "jax.lax.",
)
ARRAY_FACTORY_CALLS = {
    "jax.device_put", "jax.device_get",
}
ARRAY_FACTORY_SUFFIXES = {
    "astype", "reshape", "init_params", "init_kv_cache",
    "quantize_params",
}

#: Attribute-name heuristic for "self.<attr> is a weight/cache" when a
#: jitted closure captures ``self`` (a class-level array referenced
#: inside jit is the same constant-folding hazard as a local one).
ARRAY_ATTR_RE = re.compile(
    r"(param|weight|cache|table|embed|scale|buf)s?", re.IGNORECASE)

#: Host-sync rule scope: module -> root functions of its device hot
#: path. Every function reachable from a root through same-module calls
#: is "hot": `.item()`, float()/int()/np.asarray on a value produced by
#: a device program, `device_get`, and python if/while branching on a
#: device value are findings there (the intended once-per-chunk syncs
#: carry an inline allow-comment).
JAX_HOT_PATH_ROOTS: dict[str, set[str]] = {
    "ray_tpu.serve.engine.core": {"_decode_tick", "_admit",
                                  "_engine_loop"},
    "ray_tpu.serve.engine.decode_loop": {"__init__"},
    "ray_tpu.parallel.spmd": {"make_train_step", "make_eval_step"},
}

#: Dotted-call suffixes whose RESULT lives on device (a jit program or
#: a jnp op) — used by the hot-path rule to track which locals are
#: device values; syncing one of them is a finding.
DEVICE_PRODUCER_SUFFIXES = {
    "decode_chunk", "verify_chunk", "prefill", "decode_step",
}
DEVICE_PRODUCER_PREFIXES = ("jnp.", "jax.numpy.")

#: Dotted-call suffixes that move device values to HOST (their results
#: are safe to float()/int()/branch on). ``_fetch`` is the engine's one
#: counted sync point.
HOST_FETCH_SUFFIXES = {"_fetch", "device_get", "block_until_ready"}

#: Call names that synchronize device->host. Flagged in hot-path
#: functions regardless of operand tracking (the single allowed site
#: carries the inline allow-comment).
HOST_SYNC_CALL_SUFFIXES = {"device_get", "item"}

#: Clamp/bound call names: a dynamic_update_slice start expression
#: containing one of these counts as "provably bounded". Anything else
#: non-constant is a finding — XLA silently CLAMPS an out-of-range
#: start, so an unbounded traced start can slide a window backwards
#: over valid rows (the PR 3 scratch-row hazard).
DUS_CLAMP_CALLS = {"clip", "minimum", "maximum", "where", "min", "max",
                   "mod", "remainder"}

#: Reductions that produce a sub-2D intermediate inside a Pallas TPU
#: kernel body unless keepdims=True — plus 1D iota and cross-lane
#: reshapes, the classic Mosaic lowering failures (use
#: lax.broadcasted_iota and >=2D intermediates; PR 6 worked around
#: each of these by hand before they became rules).
PALLAS_REDUCTIONS = {"sum", "max", "min", "mean", "prod", "any", "all"}

#: Modules whose sharded-equivalence paths must initialize RNG ONCE on
#: host and ``device_put`` the result: with jax<0.5 non-partitionable
#: threefry, jitted RNG VALUES depend on out_shardings, so a
#: ``jax.random.PRNGKey`` re-init inside a mesh context makes
#: "sharded == unsharded" comparisons vacuously flaky (PR 6 dryrun).
RNG_SINGLE_INIT_MODULES = {"__graft_entry__", "bench"}

#: With-context markers for "inside a mesh scope" (rng-reinit rule):
#: matched case-insensitively as substrings of the unparsed context
#: expression, so ``with mesh_context(m)``, ``with mesh:`` and
#: ``with use_abstract_mesh(...)`` all count.
MESH_CONTEXT_MARKERS = ("mesh",)

# ======================================================================
# Distributed RPC-contract invariants (rule family "dist", distlint.py).
#
# Each table encodes a protocol bug shipped BY HAND in an earlier PR:
# PR 4's round-2 review found a direct head notify overtaking the same
# process's still-queued batched object_added (permanent stale
# directory); PR 8's first cut of rpc_cluster_leases fanned out
# serially and outran its caller's deadline on mid-death nodes, and its
# retry windows were exhausted before a SIGKILLed head respawned; PRs
# 8-10 each appended to RETRY_SAFE_RPCS as a review afterthought — or
# forgot to.
# ======================================================================

#: Modules that own a BATCHED object-directory outbox, mapped to the
#: only functions allowed to send directory frames on the wire. Any
#: other ``notify``/``call`` of an OUTBOX_METHODS method from these
#: modules bypasses the ordered stream — the frame can overtake (or be
#: overtaken by) a still-queued add/remove of the same object.
OUTBOX_OWNER_MODULES: dict[str, set[str]] = {
    "ray_tpu.core.cluster_core": {"_flush_object_notifies"},
    "ray_tpu.cluster.node_manager": {"_head_object_batch"},
}
#: Object-directory update methods that must ride the outbox stream.
OUTBOX_METHODS = {"object_added", "object_removed", "object_batch"}

#: Modules whose loops fan RPCs out per node / replica / worker. A
#: SERIAL loop of blocking calls with only per-call timeouts has an
#: unbounded total: N mid-death peers x one control timeout each
#: outruns every caller's own deadline (the PR 8 cluster_leases bug).
DIST_FANOUT_MODULES = {
    "ray_tpu.cluster.head",
    "ray_tpu.cluster.node_manager",
    "ray_tpu.core.cluster_core",
    "ray_tpu.cluster.worker_main",
    "ray_tpu.serve._private.controller",
    "ray_tpu.autoscaler.autoscaler",
}
#: Blocking client-call attribute names the fan-out rule looks for
#: inside a loop body.
FANOUT_RPC_ATTRS = {"call", "retrying_call", "call_into"}
#: Concurrency evidence INSIDE the loop body: pipelined/async dispatch
#: or per-item threads make a serial-total bound irrelevant.
FANOUT_CONCURRENCY_ATTRS = {"call_async", "submit", "start"}
FANOUT_THREAD_SUFFIXES = ("Thread",)

#: Names that read as wall-clock deadline/timeout state for the
#: wall-clock-deadline rule: ``time.time()`` feeding arithmetic or
#: comparisons against one of these must be ``time.monotonic()`` (an
#: NTP step mid-wait stretches or collapses the deadline). Plain
#: timestamping (span starts, cross-process freshness stamps) is
#: exempt — those NEED the epoch clock.
WALLCLOCK_DEADLINE_NAME_RE = re.compile(
    r"(deadline|timeout|timeout_s|expire|expiry|expires)", re.IGNORECASE)

#: Base classes known (from their own module) to set ``chaos_role`` in
#: ``__init__`` — AST analysis is per-file, so subclasses of these are
#: exempt from missing-chaos-role.
CHAOS_ROLE_BASES = {"ClusterCore", "WorkerRuntime"}

# ======================================================================
# Resource-lifetime invariants (rule family "res", reslint.py).
#
# The single most recurring post-review bug class across PRs 1-11:
# PR 8's lease-table leak (head-driven creations' leases had no owner
# to return them), PR 2's forever-pinned borrows (the release half of
# the borrow protocol was simply missing), PR 4's dead-creator PENDING
# placeholders and the leaking _local_objects mirror, unjoined daemon
# threads re-fixed in three different PRs, and unbounded memo/registry
# dicts (the PR 11 return-lease memo needed a hand-picked 4096 cap in
# review). Each table below feeds a reslint rule; the runtime half is
# devtools/res_debug.py (RTPU_DEBUG_RES=1).
# ======================================================================

#: Constructor names whose result is a RELEASABLE handle for the
#: acquire-without-release rule (matched on the dotted call target's
#: last component). ``BufferLease`` wraps pinned shm views — dropping
#: one on an error path pins the arena slot forever (PR 2's borrow-pin
#: shape).
RES_ACQUIRE_CONSTRUCTORS = {"BufferLease"}

#: Attribute-call names that acquire a releasable resource
#: (``store.pin(...)``, ``buf.pin()``). Kept separate from the
#: constructors so fixtures can exercise both shapes.
RES_ACQUIRE_ATTRS = {"pin"}

#: Attribute-call names that release a tracked resource. ``seal`` and
#: ``abort`` resolve a store create; ``return_lease`` resolves a grant.
RES_RELEASE_ATTRS = {"release", "close", "unpin", "free", "abort",
                     "seal", "return_lease", "cancel"}

#: Failure-arm cleanup evidence for the begin-without-commit rule: a
#: handler that calls one of these attrs — or a same-class helper whose
#: NAME matches RES_CLEANUP_NAME_RE — resolves the in-flight
#: reservation (``_fail_roster`` releases every active slot, which
#: clears the pending speculation).
RES_COMMIT_ATTRS = {"commit_speculation", "release"}
RES_CLEANUP_NAME_RE = re.compile(
    r"(fail|abort|rollback|release|clean|reset|clear)", re.IGNORECASE)

#: Modules whose classes hold long-lived registries fed by RPC handlers
#: or daemon loops — the unbounded-registry-growth rule only scans
#: these (a dataclass accumulating in a batch script is not the bug
#: class; a server-side dict that grows per request forever is).
RES_REGISTRY_MODULES = {
    "ray_tpu.cluster.head",
    "ray_tpu.cluster.node_manager",
    "ray_tpu.cluster.worker_main",
    "ray_tpu.cluster.protocol",
    "ray_tpu.core.cluster_core",
    "ray_tpu.serve._private.controller",
    "ray_tpu.serve._private.router",
    "ray_tpu.serve._private.proxy",
    "ray_tpu.serve._private.slo",
    # PR 19 serving state: per-tenant WFQ lanes (idle-reaped unless
    # pinned by configure) and streaming cursor slots (settled on
    # done/error/cancel or the TTL reaper).
    "ray_tpu.serve._private.qos",
    "ray_tpu.serve._private.replica",
    "ray_tpu.serve.engine.core",
    "ray_tpu.devtools.rpc_debug",
    "ray_tpu.devtools.res_debug",
    "ray_tpu.util.tracing",
    "ray_tpu.util.metrics",
}

#: Method-name heuristics for the registry rule: growth sites are RPC
#: handlers and long-lived loops (plus same-class helpers they call);
#: a method whose name matches the reaper RE counts as eviction
#: evidence for every attr it touches.
RES_LOOP_NAME_RE = re.compile(r"(_loop$|_forever$|_main$)")
RES_REAPER_NAME_RE = re.compile(
    r"(reap|evict|prune|sweep|expire|trim|clean|drain|gc|invalidate|"
    r"remove|forget|scrub)", re.IGNORECASE)

#: Attribute-call names that shrink a container (eviction evidence),
#: checked class-wide on the same ``self.<attr>``.
RES_EVICT_ATTRS = {"pop", "popleft", "popitem", "clear", "discard",
                   "remove", "popright"}

#: Thread-lifecycle rule: a class exposing one of these methods owns
#: its threads' teardown; every daemon ``Thread``/``Timer`` attr must
#: be joined/cancelled — or a stop-event set — somewhere REACHABLE from
#: one of them through same-class helper calls (PR 5's daemon-no-join
#: only required a join *somewhere in the class*; the lease-reaper
#: regression showed the join has to be on the stop path to matter).
RES_STOP_METHOD_NAMES = {"stop", "close", "shutdown", "__exit__",
                         "__del__"}
RES_STOP_EVENT_NAME_RE = re.compile(
    r"(stop|shutdown|close|done|exit|quit)", re.IGNORECASE)

#: fd-leak-on-error: calls that open an OS-level handle. Dotted-suffix
#: match for the socket forms; exact Name match for builtins.
RES_OPEN_CALL_SUFFIXES = {"socket.socket", "socket.create_connection",
                          "socket.fromfd", "os.fdopen", "os.open"}
RES_OPEN_NAME_CALLS = {"open"}
#: Closing attrs for the fd rule (shutdown alone wakes readers but the
#: fd still needs close; either counts as "handled" here — the
#: close-without-shutdown rule owns the pairing).
RES_CLOSE_ATTRS = {"close", "shutdown", "detach"}

# ======================================================================
# Channel-protocol invariants (rule family "chan", chanlint.py).
#
# PRs 15-19 made pre-negotiated channels (shm SPSC rings, peer
# sockets, pickle-5 scatter frames) the hot data plane — and every
# recent real bug lived there: the PR 19 ``ring.py _spill_in``
# spill-reclaim race (writer close unlinked a side-file the reader was
# still opening), seq inversions on the peer socket, credit-window
# stalls, and mutate-after-send aliasing on zero-copy frames. Each
# table below feeds a chanlint rule; the runtime half is
# devtools/chan_debug.py (RTPU_DEBUG_CHAN=1).
# ======================================================================

#: Receiver-name heuristic: a call like ``X.write(v, seq)`` /
#: ``X.read(seq)`` is only treated as a CHANNEL op when the receiver
#: name looks channel-ish — bare ``.write``/``.read`` on files and
#: sockets must not light the seq/deadline rules up repo-wide.
CHAN_RECEIVER_RE = re.compile(
    r"(^|_)(chan|channel|ring|edge|lane)(nel|s)?($|_)", re.IGNORECASE)

#: Ring cursor publish evidence: storing the write cursor via the
#: ``_set_u64(_O_WPOS, ...)`` idiom (or any *pos-named helper). The
#: publish must come AFTER the payload memcpy into the mmap — a
#: publish that precedes the fill hands the reader a cursor over
#: garbage bytes.
CHAN_CURSOR_PUBLISH_RE = re.compile(r"(wpos|write_pos|_O_WPOS)")
#: The mmap/buffer objects whose subscript-store is "the payload fill".
CHAN_MM_NAME_RE = re.compile(r"(^|_)(mm|mmap|buf|shm)($|_)")

#: Spill-ledger attr names (the pin side of the PR 19 race) and the
#: evidence that a teardown path OBSERVES consumption before
#: reclaiming (settle helper, rpos check, or the reclaim grace poll).
CHAN_SPILL_ATTR_RE = re.compile(r"spill", re.IGNORECASE)
CHAN_SETTLE_EVIDENCE_RE = re.compile(
    r"(settle|rpos|_O_RPOS|reclaim_grace|\.rd\b|claim)")

#: Reader-side inbox queues for the ack-before-consume rule: the ack
#: must FOLLOW the application-side ``q.get`` (acking on socket
#: receipt re-opens the credit window before the app consumed).
CHAN_INBOX_NAME_RE = re.compile(r"(^|_)(q|queue|inbox)($|_)")

#: Modules allowed to pass raw seqs into channel write/read — the
#: auto-seq facades themselves and the transports under them. Anyone
#: else routing a literal/derived seq into ``.write(v, seq)`` can mint
#: a gap or duplicate the witness then sees as send-seq-gap.
CHAN_SEQ_EXEMPT_MODULES = {
    "ray_tpu.dag.compiled_dag",
    "ray_tpu.dag.channel",
    "ray_tpu.dag.ring",
    "ray_tpu.dag.peer",
    # CpuCommunicator keeps per-peer monotonic counters — it IS an
    # auto-seq facade (one stream per (src, dst) rank pair).
    "ray_tpu.dag.communicator",
}

#: Transport modules whose classes dial peers: every
#: ``socket.create_connection`` there needs a _GONE/liveness handling
#: branch class-wide (a dial with no death branch spins forever on a
#: torn-down reader).
CHAN_TRANSPORT_MODULES = {"ray_tpu.dag.peer"}
CHAN_LIVENESS_RE = re.compile(
    r"(gone|alive|liveness|dead|_GONE)", re.IGNORECASE)

#: Mutating attribute-calls for the mutate-after-send rule: calling
#: one of these on a buffer AFTER it was handed to a zero-copy send
#: races the reader's view of the frame.
CHAN_MUTATING_ATTRS = {"fill", "sort", "resize", "put", "setfield",
                       "partition", "byteswap", "append", "extend",
                       "insert", "update", "clear"}
