"""Accelerator managers: detection, slice topology, process isolation.

Parity target: the reference's pluggable accelerator managers
(reference: python/ray/_private/accelerators/accelerator.py ABC;
tpu.py:70 TPUAcceleratorManager — GCE/GKE metadata probing :14-47,
TPU_VISIBLE_CHIPS isolation :154, pod-type detection :197, and the
``TPU-<type>-head`` slice resources used for gang placement). TPU-first
here: the TPU manager is the real one, the ABC keeps the door open for
other vendors without multi-vendor code paths in the core.

All probing is env-mockable (the reference mocks GCE metadata the same
way in tests/accelerators/test_tpu.py): set ``RTPU_TPU_CHIPS``,
``RTPU_TPU_ACCELERATOR_TYPE`` and ``RTPU_TPU_WORKER_ID`` to simulate any
slice shape on CPU machines.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

# GCE instance metadata endpoints (reference: tpu.py:14-21).
_GCE_METADATA_URL = ("http://metadata.google.internal/computeMetadata"
                     "/v1/instance/attributes/{}")
_METADATA_HEADERS = {"Metadata-Flavor": "Google"}

# chips per host by generation (reference: tpu.py pod-shape math — v2/v3
# host = 8 cores / 4 chips; v4/v5p host = 4 chips; v5e/v6e host = up to 8
# single-core chips).
_CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5p": 4, "v5litepod": 8,
                   "v5e": 8, "v6e": 8}
# Accelerator-type chip counts count CORES for v2-v4 (v3-8 = 8 cores = 4
# chips) and CHIPS for v5e onward (reference: tpu.py:197 pod detection).
_CORES_PER_CHIP = {"v2": 2, "v3": 2, "v4": 1, "v5p": 1, "v5litepod": 1,
                   "v5e": 1, "v6e": 1}


class AcceleratorManager:
    """ABC (reference: accelerator.py): one per vendor."""

    @staticmethod
    def get_resource_name() -> str:
        raise NotImplementedError

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        raise NotImplementedError

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        raise NotImplementedError

    @staticmethod
    def set_visible_accelerators(ids: list) -> None:
        raise NotImplementedError


def _gce_metadata(key: str, timeout: float = 1.0) -> Optional[str]:
    """One GCE metadata attribute, or None off-GCE. Env overrides first —
    tests and non-GCE deployments never hit the network."""
    env = os.environ.get(f"RTPU_TPU_{key.upper().replace('-', '_')}")
    if env is not None:
        return env
    try:  # pragma: no cover — requires GCE
        import urllib.request

        req = urllib.request.Request(_GCE_METADATA_URL.format(key),
                                     headers=_METADATA_HEADERS)
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.read().decode()
    except Exception:
        return None


class TPUAcceleratorManager(AcceleratorManager):
    """TPU detection + slice topology (reference: tpu.py:70)."""

    @staticmethod
    def get_resource_name() -> str:
        return "TPU"

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        import glob

        env = os.environ.get("RTPU_TPU_CHIPS")
        if env is not None:
            try:
                return int(float(env))
            except ValueError:
                return 0
        return len(glob.glob("/dev/accel*") or glob.glob("/dev/vfio/*"))

    @staticmethod
    def get_current_node_accelerator_type() -> Optional[str]:
        """e.g. "v5p-8" — from env override or GCE metadata
        (reference: tpu.py accelerator-type probing)."""
        return _gce_metadata("accelerator-type")

    @staticmethod
    def get_current_node_tpu_worker_id() -> Optional[int]:
        """This host's index within its slice (reference: tpu.py
        agent-worker-number metadata)."""
        v = _gce_metadata("agent-worker-number")
        try:
            return int(v) if v is not None else None
        except ValueError:
            return None

    @staticmethod
    def set_visible_accelerators(ids: list) -> None:
        """Restrict this process to the given chip indices (reference:
        TPU_VISIBLE_CHIPS isolation, tpu.py:154)."""
        os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(i) for i in ids)
        os.environ.setdefault("TPU_CHIPS_PER_PROCESS_BOUNDS", "1,1,1")
        os.environ.setdefault("TPU_PROCESS_BOUNDS", "1,1,1")


def parse_slice_shape(accelerator_type: str) -> Tuple[str, int, int]:
    """"v5p-16" -> (generation, total_chips, num_hosts).

    Mirrors the reference's pod-shape math (tpu.py:197): the numeric
    suffix counts CORES for v2-v4 generations and CHIPS from v5e on;
    hosts = ceil(chips / chips_per_host(generation))."""
    try:
        gen, _, suffix = accelerator_type.partition("-")
        units = int(suffix)
    except (ValueError, AttributeError):
        raise ValueError(
            f"malformed TPU accelerator type {accelerator_type!r} "
            f"(expected e.g. 'v5p-8')") from None
    gen = gen.lower()
    if gen not in _CHIPS_PER_HOST:
        raise ValueError(f"unknown TPU generation {gen!r}")
    chips = units // _CORES_PER_CHIP[gen]
    per_host = _CHIPS_PER_HOST[gen]
    hosts = max(1, (chips + per_host - 1) // per_host)
    return gen, chips, hosts


def slice_node_resources(accelerator_type: str,
                         worker_id: int) -> Tuple[Dict[str, float],
                                                  Dict[str, str]]:
    """(resources, labels) one slice host contributes to the cluster.

    Worker 0 carries the ``TPU-<type>-head`` resource: gang-scheduled
    jobs reserve exactly one head per slice and fan per-host actors out
    with node affinity — the reference's TPU pod scheduling pattern
    (tpu.py TPU-{pod_type}-head resources)."""
    _gen, chips, hosts = parse_slice_shape(accelerator_type)
    per_host = chips // hosts if hosts else chips
    res: Dict[str, float] = {"TPU": float(per_host)}
    if worker_id == 0:
        res[f"TPU-{accelerator_type}-head"] = 1.0
    labels = {"accelerator-type": accelerator_type,
              "tpu-worker-id": str(worker_id)}
    return res, labels
