"""Lineage store: the recipe for re-creating lost objects.

Parity target: the reference's lineage-based object recovery
(reference: src/ray/core_worker/task_manager.h:212,265 ResubmitTask +
object_recovery_manager.h): the owner keeps each finished task's spec as
long as its outputs might need re-creating; when a node holding a task's
(plasma) output dies, the owner resubmits the creating task — transitively,
since the resubmitted task's own arguments may be lost too.

Records are kept in bytes-bounded FIFO (``max_lineage_bytes``); records
OUTLIVE the value (a freed value costs nothing, but its recipe still lets
descendants recover), which is the whole point of storing specs instead of
pinning data.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional, Tuple

from ray_tpu.core.ids import ObjectID


class LineageRecord:
    __slots__ = ("spec_blob", "sched_key", "resources", "strategy", "name",
                 "return_ids", "arg_ids", "nbytes", "runtime_env")

    def __init__(self, spec_blob: bytes, sched_key: tuple, resources,
                 strategy, name: str, return_ids: List[ObjectID],
                 arg_ids: List[ObjectID], runtime_env=None):
        self.runtime_env = runtime_env
        self.spec_blob = spec_blob
        self.sched_key = sched_key
        self.resources = resources
        self.strategy = strategy
        self.name = name
        self.return_ids = return_ids
        self.arg_ids = arg_ids
        self.nbytes = len(spec_blob) + 64 * (len(return_ids) + len(arg_ids))


class LineageStore:
    def __init__(self, max_bytes: int):
        self._max_bytes = max_bytes
        self._lock = threading.Lock()
        self._by_task: "collections.OrderedDict[bytes, LineageRecord]" = (
            collections.OrderedDict())
        self._by_oid: Dict[ObjectID, bytes] = {}
        self._bytes = 0
        self.evictions = 0

    def record(self, task_id_bytes: bytes, rec: LineageRecord) -> None:
        if self._max_bytes <= 0:
            return
        with self._lock:
            old = self._by_task.pop(task_id_bytes, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._by_task[task_id_bytes] = rec
            self._bytes += rec.nbytes
            for oid in rec.return_ids:
                self._by_oid[oid] = task_id_bytes
            while self._bytes > self._max_bytes and len(self._by_task) > 1:
                victim_key, victim = self._by_task.popitem(last=False)
                self._bytes -= victim.nbytes
                self.evictions += 1
                for oid in victim.return_ids:
                    if self._by_oid.get(oid) == victim_key:
                        del self._by_oid[oid]

    def for_object(self, oid: ObjectID) -> Optional[Tuple[bytes, LineageRecord]]:
        with self._lock:
            key = self._by_oid.get(oid)
            if key is None:
                return None
            rec = self._by_task.get(key)
            return (key, rec) if rec is not None else None

    def size_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def num_records(self) -> int:
        with self._lock:
            return len(self._by_task)
