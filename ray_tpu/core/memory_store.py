"""In-process object store for small / inlined results.

Equivalent of the reference's CoreWorkerMemoryStore
(reference: src/ray/core_worker/store_provider/memory_store/memory_store.h):
holds deserialized values keyed by ObjectID, wakes blocked getters, and fires
async callbacks registered before the value arrived.  Values larger than the
inline threshold never land here — they go to the node's shared-memory store
(ray_tpu/core/object_store.py) and this store holds only a location stub.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set

from ray_tpu.core.ids import ObjectID
from ray_tpu.exceptions import GetTimeoutError


class _Record:
    __slots__ = ("value", "is_exception", "in_plasma")

    def __init__(self, value: Any, is_exception: bool = False, in_plasma: bool = False):
        self.value = value
        self.is_exception = is_exception
        self.in_plasma = in_plasma


class PlasmaStub:
    """Marker stored here when the real bytes live in the shm store."""

    __slots__ = ("object_id",)

    def __init__(self, object_id: ObjectID):
        self.object_id = object_id


class MemoryStore:
    def __init__(self):
        from ray_tpu.devtools.lock_debug import make_lock

        self._lock = make_lock("memory_store._lock")
        self._cv = threading.Condition(self._lock)
        self._objects: Dict[ObjectID, _Record] = {}
        self._callbacks: Dict[ObjectID, List[Callable[[_Record], None]]] = {}

    def put(self, object_id: ObjectID, value: Any, is_exception: bool = False) -> None:
        with self._cv:
            if object_id in self._objects:
                return  # idempotent: retries may double-store
            rec = _Record(value, is_exception, isinstance(value, PlasmaStub))
            self._objects[object_id] = rec
            callbacks = self._callbacks.pop(object_id, [])
            self._cv.notify_all()
        for cb in callbacks:
            try:
                cb(rec)
            except Exception:
                # One broken callback (e.g. a cancelled future) must not
                # crash the delivery thread or strand later callbacks.
                pass

    def put_batch(self, items) -> None:
        """items: [(object_id, value, is_exception)]. One lock acquisition
        and one notify_all for a whole completion batch — per-put wakeups
        were a measurable tax at high completion rates."""
        fire: List[tuple] = []
        with self._cv:
            for object_id, value, is_exception in items:
                if object_id in self._objects:
                    continue  # idempotent: retries may double-store
                rec = _Record(value, is_exception,
                              isinstance(value, PlasmaStub))
                self._objects[object_id] = rec
                cbs = self._callbacks.pop(object_id, None)
                if cbs:
                    fire.append((cbs, rec))
            self._cv.notify_all()
        for cbs, rec in fire:
            for cb in cbs:
                try:
                    cb(rec)
                except Exception:
                    # A failing callback must not abort the rest of the
                    # batch — unrelated waiters would hang forever.
                    pass

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def get_async(self, object_id: ObjectID, callback: Callable[[_Record], None]) -> None:
        with self._lock:
            rec = self._objects.get(object_id)
            if rec is None:
                self._callbacks.setdefault(object_id, []).append(callback)
                return
        callback(rec)

    def remove_callback(self, object_id: ObjectID,
                        callback: Callable[[_Record], None]) -> None:
        """Deregister a pending get_async callback (e.g. wait() timed out):
        without this, poll-style wait loops would accumulate one closure per
        call until the object finally arrives."""
        with self._lock:
            cbs = self._callbacks.get(object_id)
            if cbs is not None:
                try:
                    cbs.remove(callback)
                except ValueError:
                    pass
                if not cbs:
                    del self._callbacks[object_id]

    def get(
        self,
        object_ids: List[ObjectID],
        timeout: Optional[float] = None,
    ) -> List[_Record]:
        deadline = None if timeout is None else time.monotonic() + timeout
        records: List[_Record] = []
        with self._cv:
            for oid in object_ids:
                while oid not in self._objects:
                    remaining = None if deadline is None else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        raise GetTimeoutError(f"timed out waiting for {oid}")
                    self._cv.wait(timeout=remaining)
                records.append(self._objects[oid])
        return records

    def wait(
        self,
        object_ids: List[ObjectID],
        num_returns: int,
        timeout: Optional[float],
        return_all: bool = False,
    ) -> Set[ObjectID]:
        """Returns the set of ready ids (>= num_returns unless timeout).
        With ``return_all``, once the threshold is met the whole list is
        scored (batch long-poll servers want every ready id per wake)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                # Early-exit scan: a wake only needs to find num_returns
                # ready ids, not score the whole list (pop-1-of-1k wait
                # loops re-scan on every put_batch wake otherwise).
                ready = set()
                objs = self._objects
                for oid in object_ids:
                    if oid in objs:
                        ready.add(oid)
                        if len(ready) >= num_returns and not return_all:
                            return ready
                if len(ready) >= num_returns:
                    return ready
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return ready
                self._cv.wait(timeout=remaining)

    def objects_view(self):
        """The live id->record dict for GIL-atomic membership probes (the
        wait() hot path fuses readiness into its validation pass; callers
        must only do `in` checks, never read values or iterate)."""
        return self._objects

    def delete(self, object_ids: List[ObjectID]) -> List[ObjectID]:
        """Returns the subset whose record was MEMORY-RESIDENT (present
        and not a plasma stub): a released small result needs no shm-store
        delete / unlink syscalls — the caller can skip them (hot on the
        task-release path: every small task return pays this)."""
        memory_only: List[ObjectID] = []
        with self._lock:
            for oid in object_ids:
                rec = self._objects.pop(oid, None)
                self._callbacks.pop(oid, None)
                if rec is not None and not rec.in_plasma:
                    memory_only.append(oid)
        return memory_only

    def size(self) -> int:
        with self._lock:
            return len(self._objects)
