"""Child-process lifetime binding without preexec_fn.

``preexec_fn`` forces subprocess down the raw fork() path and runs Python
between fork and exec — with JAX's (or any) background threads in the
parent this is the documented fork-deadlock class (the suite printed
RuntimeWarnings for every spawn; reference analog: the raylet passes
death-signal setup to workers via their OWN startup, not the parent's
fork hook). Instead:

- the SPAWNER sets ``RTPU_PARENT_PID`` in the child env and uses a plain
  Popen (CPython can then use its vfork/posix_spawn fast paths),
- the CHILD calls :func:`bind_to_parent` first thing in main(): arms
  PR_SET_PDEATHSIG and closes the fork->arm race by checking that its
  parent is still the spawner (a parent that died in between leaves the
  child re-parented, typically to pid 1 — exit immediately).
"""

from __future__ import annotations

import os
from typing import Optional

PARENT_PID_VAR = "RTPU_PARENT_PID"


def spawn_env(env: Optional[dict] = None) -> dict:
    """Environment for a child whose lifetime should track this process."""
    out = dict(os.environ if env is None else env)
    out[PARENT_PID_VAR] = str(os.getpid())
    return out


def bind_to_parent() -> None:
    """Arm SIGTERM-on-parent-death; exit if the spawner already died."""
    try:
        import ctypes

        ctypes.CDLL("libc.so.6").prctl(1, 15)  # PR_SET_PDEATHSIG, SIGTERM
    except Exception:
        return
    expected = os.environ.get(PARENT_PID_VAR)
    if expected is not None:
        try:
            if os.getppid() != int(expected):
                os._exit(0)  # spawner died before the signal was armed
        except ValueError:
            pass
