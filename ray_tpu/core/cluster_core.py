"""Shared cluster-mode runtime core: embedded in the driver AND every worker.

Parity target: the reference's CoreWorker (reference:
src/ray/core_worker/core_worker.h:166 — SubmitTask :853, CreateActor :878,
SubmitActorTask :935, Put :466, Get :642, Wait :682 — plus
transport/normal_task_submitter.h:74 lease-based submission with lease reuse,
transport/actor_task_submitter.h:75 ordered per-actor queues, and the
ownership model of reference_count.h). Re-designed over the framed RPC plane:

- every process runs an RPC server: it is the OWNER endpoint for objects it
  creates (serves gets, receives task_done pushes) and, for workers, the
  task-execution endpoint
- normal tasks: head picks a node (hybrid policy + spillback), the node
  leases a worker, the task is pushed DIRECTLY to the worker; leases are
  cached per scheduling key and reused while tasks are in flight (the
  OnWorkerIdle pattern), released after an idle linger
- small results ride the task_done push (owner memory store); large results
  are sealed into the executing node's shm store and pulled on demand
- actor calls go direct to the actor's worker with sequence numbers; on
  connection loss the submitter consults the head: RESTARTING -> wait and
  resubmit pending calls to the new address, DEAD -> fail with
  ActorDiedError
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core import runtime_context
from ray_tpu.core.config import GLOBAL_CONFIG as cfg
from ray_tpu.core.ids import (ActorID, JobID, NodeID, ObjectID,
                              PlacementGroupID, TaskID, WorkerID)
from ray_tpu.core.memory_store import MemoryStore, PlasmaStub
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.refcount import ReferenceCounter
from ray_tpu.core.serialization import SERIALIZER, capture_exception
from ray_tpu.core.shm_store import ShmObjectExistsError, ShmStore
from ray_tpu.core.task_spec import PlacementGroupSpec, pg_key_from_strategy
from ray_tpu.devtools import res_debug as _resdbg
from ray_tpu.devtools import rpc_debug as _rpcdbg
from ray_tpu.devtools.lock_debug import make_lock
from ray_tpu.cluster.protocol import (ClientPool, ConnectionLost, RpcClient,
                                      RpcServer, blocking_rpc)
from ray_tpu.exceptions import (ActorDiedError, GetTimeoutError, TaskError,
                                WorkerCrashedError)
from ray_tpu.core.lineage import LineageRecord as _LineageRecord
from ray_tpu.util import metrics as _metrics

logger = logging.getLogger(__name__)


class _SubmitTemplate:
    """Constant-per-function submission state (see make_submit_template)."""

    __slots__ = ("func", "num_returns", "resources", "strategy", "name",
                 "sched_key", "spread", "effective_retries", "runtime_env",
                 "env_hash", "spec_proto", "streaming")

    def __init__(self, func, num_returns, resources, strategy, name,
                 sched_key, spread, effective_retries, runtime_env,
                 env_hash, spec_proto, streaming=False):
        self.func = func
        self.num_returns = num_returns
        self.resources = resources
        self.strategy = strategy
        self.name = name
        self.sched_key = sched_key
        self.spread = spread
        self.effective_retries = effective_retries
        self.runtime_env = runtime_env
        self.env_hash = env_hash
        self.spec_proto = spec_proto
        self.streaming = streaming


class _Lease:
    __slots__ = ("worker_addr", "lease_id", "node_addr", "node_id",
                 "inflight", "release_at", "broken")

    def __init__(self, worker_addr: str, lease_id: str, node_addr: str,
                 node_id: Optional[str] = None):
        self.worker_addr = worker_addr
        self.lease_id = lease_id
        self.node_addr = node_addr
        # Which node granted this lease: the dispatch-side locality match
        # pairs queued tasks with leases on their inputs' holder node.
        self.node_id = node_id
        self.inflight = 0
        # A lease is born with a linger deadline: a grant that lands AFTER
        # the queue drained (slow worker spawn raced the burst) must still be
        # returned to its node — release_at=0 here used to mean "never",
        # permanently leaking the lease's CPUs and starving the cluster.
        self.release_at = time.monotonic() + cfg.lease_linger_ms / 1000.0
        self.broken = False


class _LeaseBlock:
    """Owner-held admission budget for one scheduling key: the head
    pre-negotiated `size` lease admissions at one node, so dispatch for
    this key goes node-direct (no pick_node round trip) until the budget
    or TTL runs out. Guarded by ClusterCore._lease_lock."""

    __slots__ = ("block_id", "node_id", "node_addr", "remaining", "size",
                 "expires_at", "renewing")

    def __init__(self, block_id: str, node_id: str, node_addr: str,
                 size: int, ttl_ms: int):
        self.block_id = block_id
        self.node_id = node_id
        self.node_addr = node_addr
        self.remaining = int(size)
        self.size = int(size)
        self.expires_at = time.monotonic() + ttl_ms / 1000.0
        # True while a low-water renewal is in flight (one renewer at a
        # time; the flag rides the BLOCK so a replaced block can't leave
        # a stale "renewing" latch on the key).
        self.renewing = False


class _InflightTask:
    __slots__ = ("spec_blob", "return_ids", "worker_addr", "retries_left",
                 "sched_key", "resources", "strategy", "name", "sys_retries",
                 "runtime_env", "streaming", "arg_ids", "enqueued_at",
                 "pref_node", "trace_ctx", "submit_t")

    def __init__(self, spec_blob, return_ids, worker_addr, retries_left,
                 sched_key, resources, strategy, name, runtime_env=None,
                 streaming=False):
        self.spec_blob = spec_blob
        self.return_ids = return_ids
        self.worker_addr = worker_addr
        self.retries_left = retries_left
        self.sched_key = sched_key
        self.resources = resources
        self.strategy = strategy
        self.name = name
        self.sys_retries = None  # lazily set from config on first failure
        self.runtime_env = runtime_env  # validated dict or None
        self.streaming = streaming
        # ObjectIDs passed as args: the locality signal — lease requests
        # ship them as the pick_node hint, and dispatch pairs the task
        # with a lease on the node holding most of their bytes.
        self.arg_ids: List[ObjectID] = []
        self.enqueued_at = 0.0  # stamped by _enqueue_task (defer aging)
        # Memoized _preferred_node result (False = not yet resolved):
        # the dispatch match consults it per lease per round, and the
        # answer only depends on arg_ids + the slow-changing locality
        # cache. Re-resolved while unknown (locations may arrive late).
        self.pref_node: Any = False
        # Distributed tracing: the submitter's wire span context (None
        # when tracing is off — the dispatcher-side span emits are gated
        # on it, so the untraced hot path allocates nothing) and the
        # wall-clock submit time the dispatch span starts from.
        self.trace_ctx: Optional[Dict[str, str]] = None
        self.submit_t = 0.0


class _StreamState:
    """Owner-side record of one streaming-generator task (reference: the
    streaming-generator ref bookkeeping in task_manager.h:212)."""

    __slots__ = ("received", "consumed", "total", "error", "cv")

    def __init__(self):
        self.received = 0          # items delivered so far (contiguous)
        self.consumed = 0          # items handed to the consumer
        self.total = None          # set at stream end
        self.error = None          # terminal error (raised at consume point)
        self.cv = threading.Condition()


class ObjectRefGenerator:
    """Iterator over a streaming task's yielded refs, in yield order.
    Each __next__ blocks until the next item's object has ARRIVED at the
    owner (the ref is immediately gettable). Dropping the generator
    without draining it cancels the stream: the producer stops and
    undelivered items are released."""

    def __init__(self, core: "ClusterCore", task_id: TaskID):
        self._core = core
        self._task_id = task_id
        self._index = 0
        self._exhausted = False

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        try:
            ref = self._core._next_stream_ref(
                self._task_id, self._index,
                timeout=cfg.streaming_item_timeout_s)
        except StopIteration:
            self._exhausted = True
            raise
        self._index += 1
        return ref

    def task_id(self) -> TaskID:
        return self._task_id

    def close(self) -> None:
        if not self._exhausted:
            self._exhausted = True
            try:
                self._core._abandon_stream(self._task_id)
            except Exception:
                pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _KeyQueue:
    """Per-scheduling-key submission state: pending tasks + leased workers."""

    __slots__ = ("key", "queue", "leases", "dispatcher_running",
                 "pending_lease_requests", "wake", "lease_fail_deadline",
                 "lease_backoff", "next_lease_attempt", "avg_task_s",
                 "block", "block_pending")

    def __init__(self, key: tuple):
        import collections

        self.key = key
        self.queue = collections.deque()
        self.leases: List[_Lease] = []
        self.dispatcher_running = False
        self.pending_lease_requests = 0
        self.wake = threading.Event()
        self.lease_fail_deadline = None
        # Owner-routed lease block for this key (steady-state head
        # bypass): None until the first head-mediated grant succeeds and
        # the background block negotiation lands. block_pending latches
        # while a grant request is in flight (one per key).
        self.block: Optional[_LeaseBlock] = None
        self.block_pending = False
        # Declined-lease backoff: a saturated cluster must not cost a
        # pick_node RPC + requester thread every 50ms per scheduling key.
        self.lease_backoff = 0.0
        self.next_lease_attempt = 0.0
        # EWMA of observed execution seconds for this key: decides
        # whether dispatch pipelines (short tasks) or holds one-per-lease
        # (long tasks). None until the first completion reports.
        self.avg_task_s = None


class _ActorConn:
    """Submitter-side state for one remote actor.

    Ordering contract (reference: sequential_actor_submit_queue.h): calls
    from one submitter execute in submission order. Seq numbers are assigned
    synchronously in submit_actor_task, and ONE sender thread per actor
    drains the outbound queue in seq order over a single TCP connection —
    frame order on the socket IS execution-submission order on the worker."""

    __slots__ = ("actor_id", "address", "next_seq", "outbound", "unacked",
                 "pending", "lock", "sender_running", "dead", "death_reason",
                 "loss_handling", "incarnation", "replays")

    def __init__(self, actor_id: ActorID):
        import collections

        self.actor_id = actor_id
        self.address: Optional[str] = None
        self.next_seq = 0
        self.outbound = collections.deque()  # (seq, task_id_bytes, blob, rids)
        self.unacked = collections.deque()   # [seq, tid, blob, waiter, tries, deadline]
        self.pending: Dict[int, tuple] = {}  # seq -> (tid, blob, return_ids)
        self.lock = make_lock("cluster_core.actor_conn.lock")
        self.sender_running = False
        self.dead = False
        self.death_reason = ""
        # True while ONE conn-loss handler owns this conn's recovery
        # (concurrent loss reports — sender inline + pool on_close
        # threads — must not double-replay or double-fail).
        self.loss_handling = False
        # Last head-reported restart count this submitter replayed
        # against; purely observational (the worker's per-caller seq
        # horizon is what makes a cross-incarnation replay safe).
        self.incarnation = 0
        # seq -> cross-incarnation replay count (entries leave with
        # pending): a poison call stops after max_task_retries replays
        # instead of riding every future incarnation.
        self.replays: Dict[int, int] = {}

    def min_pending(self) -> int:
        """Smallest seq still awaiting completion — the ordered-execution
        horizon shipped with every push (see worker _OrderState)."""
        with self.lock:
            return min(self.pending) if self.pending else self.next_seq


class ClusterCore:
    """Runtime-interface implementation for cluster mode."""

    is_cluster = True

    def __init__(self, head_addr: str, node_addr: str, node_id: str,
                 store_name: str, job_id: JobID, is_driver: bool = True):
        self.job_id = job_id
        self.node_id = node_id
        self.worker_id = WorkerID.from_random()
        self.is_driver = is_driver
        self.head_addr = head_addr
        self.node_addr = node_addr

        self.memory_store = MemoryStore()
        self.refcount = ReferenceCounter(
            on_release=self._release_object,
            on_borrow_release=self._release_borrow)
        self.store = ShmStore.open(store_name)
        self._driver_task_id = TaskID.for_driver(job_id)
        self._nil_actor = ActorID.nil_for_job(job_id)
        self._put_counter = itertools.count(1)

        self._pool = ClientPool()
        self.head = RpcClient(head_addr)
        self.node = RpcClient(node_addr)
        # Fault-injection scope (devtools/chaos.py): chaos-plan rules
        # target this process's RPC server by role.
        self.chaos_role = "driver" if is_driver else "worker"
        from ray_tpu.util import flight_recorder as _fl

        _fl.set_role(self.chaos_role, node_id=node_id)
        self._server = RpcServer(self).start()
        self.owner_addr = self._server.address

        self._key_queues: Dict[tuple, _KeyQueue] = {}
        self._lease_lock = make_lock("cluster_core._lease_lock")
        # Steady-state dispatch accounting (bench.py --scale reads this):
        # head_picks counts pick_node/pick_nodes FRAMES, block_dispatches
        # counts leases admitted node-direct against a block,
        # block_fallbacks counts block attempts that fell back to the
        # head path. Guarded by _lease_lock.
        self.dispatch_stats: Dict[str, int] = {
            "head_picks": 0, "block_grants": 0,
            "block_dispatches": 0, "block_fallbacks": 0}
        # Owner-side object locality cache: oid bytes -> (node_id, size).
        # Populated for free from task completions ("in_store" results
        # carry the sealing node) and local plasma puts; consulted by the
        # dispatch-side locality match and shipped as pick_node hints
        # (reference: the owner's LocalityData feeding the lease policy).
        import collections as _coll

        self._obj_locality: "_coll.OrderedDict" = _coll.OrderedDict()
        self._obj_loc_lock = make_lock("cluster_core._obj_loc_lock")
        self._inflight: Dict[bytes, _InflightTask] = {}  # task_id -> info
        self._inflight_lock = make_lock("cluster_core._inflight_lock")
        # task_id -> ObjectIDs passed as args: each holds a submitted-task
        # ref until the task reaches a TERMINAL state (done or failed), so
        # the caller dropping its local ObjectRef right after `.remote(ref)`
        # cannot free an argument out from under the executing worker
        # (reference: ReferenceCounter's submitted_task_ref_count).
        self._submitted_args: Dict[bytes, List[ObjectID]] = {}
        # task_id -> _StreamState for in-flight streaming generators.
        self._streams: Dict[bytes, _StreamState] = {}
        self._streams_lock = make_lock("cluster_core._streams_lock")
        # (expiry, oid) transfer pins for owned refs serialized outbound;
        # swept by the push-ack loop.
        import collections as _collections

        self._transfer_pins: "_collections.deque" = _collections.deque()
        # Completed-task events awaiting the periodic flush to the head.
        self._task_event_outbox: "_collections.deque" = _collections.deque(
            maxlen=cfg.task_event_outbox_max)
        # Lineage-based recovery: creating-task specs per owned object
        # (reference: task_manager.h:265 ResubmitTask).
        from ray_tpu.core.lineage import LineageStore

        self.lineage = LineageStore(cfg.max_lineage_bytes)
        self._recovering: Dict[bytes, float] = {}  # task_id -> last attempt
        self._recover_lock = make_lock("cluster_core._recover_lock")
        # Observability: recent completions ring (util.state.list_tasks).
        self._recent_tasks: "_collections.deque" = _collections.deque(
            maxlen=cfg.recent_tasks_ring)
        self._actors: Dict[ActorID, _ActorConn] = {}
        self._actors_lock = make_lock("cluster_core._actors_lock")
        # Bounded memo of RETIRED actors (dead conns dropped from
        # _actors — which otherwise grew one _ActorConn per actor ever
        # called, for the life of the driver): actor_id -> death
        # reason, so a late call on a retired actor still fails fast
        # with the real cause. Same shape/cap as the node's
        # return-lease memo.
        self._dead_actor_reasons: "_collections.OrderedDict" = \
            _collections.OrderedDict()
        self._actor_classes: Dict[ActorID, Any] = {}
        self._pgs: Dict[PlacementGroupID, PlacementGroupSpec] = {}
        # Cancelled task ids: consulted at (re)dispatch so a cancel issued
        # while the task was in flight sticks across worker-crash
        # re-enqueues. FIFO-bounded.
        import collections as _c

        self._cancelled: set = set()
        self._cancelled_order: "_c.deque" = _c.deque()
        self._shutdown_flag = False
        # Push-ack tracking: every push_task is an acked call collected off
        # the dispatch hot path; unacked pushes are retried (worker-side
        # task-id dedup makes retries exactly-once per worker).
        import collections

        self._push_acks = collections.deque()
        self._push_ack_event = threading.Event()
        self._borrow_buf: Dict[str, list] = {}
        self._borrow_buf_lock = make_lock("cluster_core._borrow_buf_lock")
        #: oid bytes -> owner addr for refs this process BORROWS; consulted
        #: when the borrowed ref goes out of scope so the owner can be
        #: told to drop us from its borrower set (the release half of the
        #: borrow protocol).
        self._borrowed_owners: Dict[bytes, str] = {}
        #: owner_addr -> (retry-not-before deadline, consecutive failures);
        #: keeps a dead owner from being retried inline on every ref
        #: deserialization (flushes go through the periodic sweep instead).
        self._borrow_flush_backoff: Dict[str, tuple] = {}
        # key -> generation: a re-borrow after release bumps the gen, so
        # the FIFO trim below only forgets an entry if it is still the
        # CURRENT one (a stale trim must not delete a live re-borrow's
        # tracking and silently skip its owner-side release).
        self._borrows_sent: Dict[bytes, int] = {}
        self._borrows_sent_order = _collections.deque()  # (key, gen)
        self._borrow_gen = itertools.count(1)
        # Function table (reference: _private/function_manager.py exports a
        # function ONCE to the GCS function table; tasks carry only its
        # digest). Pickling the function per submit was the tasks_async
        # bottleneck: a by-value cloudpickle both sides of every task.
        import weakref

        self._fn_exports: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary())
        self._fn_exports_lock = make_lock("cluster_core._fn_exports_lock")
        # digest -> fn, LRU-bounded: unique-lambda loops must not grow it
        # without bound; an evicted digest re-fetches from the head KV.
        import collections

        self._fn_cache: "collections.OrderedDict" = collections.OrderedDict()
        self._fn_cache_max = 4096
        # Dedicated cache lock: _fn_exports_lock spans a head kv_put RPC in
        # _export_function; cache mutation must never wait on network I/O.
        self._fn_cache_lock = make_lock("cluster_core._fn_cache_lock")
        # Object-directory notify outbox: per-put/per-release head frames
        # coalesce into one object_batch frame per flush window — N
        # concurrent writers were paying N head frames (+ head dispatch +
        # lock) per object, which serialized multi-client put throughput.
        self._obj_notify_outbox: "_collections.deque" = _collections.deque()
        self._obj_notify_event = threading.Event()
        # Single-flusher guard: shutdown's last-gasp flush racing the
        # daemon's would split an ordered add/rm pair across two frames
        # whose send order is unconstrained.
        self._obj_notify_flush_lock = make_lock("cluster_core._obj_notify_flush_lock")
        threading.Thread(target=self._obj_notify_loop, daemon=True,
                         name="obj-notify").start()
        threading.Thread(target=self._push_ack_loop, daemon=True,
                         name="push-acks").start()
        self._lease_reaper = _resdbg.track_thread(threading.Thread(
            target=self._lease_reaper_loop, daemon=True,
            name="lease-reaper"), owner=self)
        self._lease_reaper.start()

    # ------------------------------------------------------------------ refs

    def _blocked_scope(self):
        """Context manager: while a WORKER task blocks in get()/wait(), its
        lease's resources are handed back to the node so nested tasks can
        schedule (reference: CoreWorker's NotifyDirectCallTaskBlocked —
        without it, N blocked parents over N CPUs deadlock their children).
        No-op on drivers and outside task context."""
        import contextlib

        @contextlib.contextmanager
        def scope():
            active = (not self.is_driver and runtime_context
                      .current_worker_context().get("task_id") is not None)
            if active:
                try:
                    self.node.notify("worker_blocked", self.owner_addr)
                except Exception:
                    active = False
            # Worker-side execution slot: a blocked task yields its slot so
            # the next pipelined task can run (mirrors the node-side
            # resource release above; WorkerRuntime installs the hooks).
            hook = getattr(self, "_on_task_blocked", None) if active else None
            if hook is not None:
                hook()
            try:
                yield
            finally:
                if hook is not None:
                    self._on_task_unblocked()
                if active:
                    try:
                        self.node.notify("worker_unblocked", self.owner_addr)
                    except Exception:
                        pass

        return scope()

    def resolve_record(self, rec) -> Any:
        if rec.is_exception:
            raise rec.value
        if rec.in_plasma:
            return self._read_plasma(rec.value.object_id, timeout=None)
        return rec.value

    def register_ready_callback(self, oid: ObjectID, cb: Callable) -> None:
        self.memory_store.get_async(oid, cb)

    def on_ref_deserialized(self, oid: ObjectID, owner_addr: Optional[str]) -> None:
        # Borrow registration: tell the owner we hold a reference. Buffered
        # and flushed as one frame per owner (an object containing 10k refs
        # must not cost 10k notify syscalls per get); the owner-side
        # transfer pin covers the sub-second flush latency.
        if owner_addr and owner_addr != self.owner_addr:
            key = oid.binary()
            flush = None
            with self._borrow_buf_lock:
                if key in self._borrows_sent:
                    return  # owner already knows; re-gets of the same
                            # ref-bearing object must not re-notify
                gen = next(self._borrow_gen)
                self._borrows_sent[key] = gen
                self._borrows_sent_order.append((key, gen))
                self._borrowed_owners[key] = owner_addr
                while len(self._borrows_sent_order) > 200_000:
                    old, old_gen = self._borrows_sent_order.popleft()
                    if self._borrows_sent.get(old) == old_gen:
                        self._borrows_sent.pop(old, None)
                        self._borrowed_owners.pop(old, None)
                self._borrow_buf.setdefault(owner_addr, []).append(key)
                if (len(self._borrow_buf[owner_addr])
                        >= cfg.borrow_flush_batch_size
                        and not self._in_borrow_backoff(owner_addr)):
                    flush = self._borrow_buf.pop(owner_addr)
            if flush is not None:
                self._flush_borrows(owner_addr, flush)

    def _release_borrow(self, oid: ObjectID) -> None:
        """A borrowed ref went out of scope locally: tell the owner to
        drop this process from the object's borrower set (the release
        half of the borrow protocol — without it the owner pins every
        borrowed object until this process exits). Best-effort: a lost
        removal pins until then, never frees early."""
        key = oid.binary()
        with self._borrow_buf_lock:
            # Re-borrow race: a concurrent deserialization may have
            # re-acquired this oid AND dedup-skipped re-registration
            # (our maps were still populated). In that case the existing
            # registration is exactly right — keep it and send nothing,
            # or the owner would drop us while a live ref exists here.
            if self.refcount.is_in_scope(oid):
                return
            owner = self._borrowed_owners.pop(key, None)
            # Forget the dedup entry: a future re-borrow of the same
            # object must RE-register (the owner just dropped us).
            self._borrows_sent.pop(key, None)
            if owner is not None:
                buf = self._borrow_buf.get(owner)
                if buf is not None and key in buf:
                    # The registration never left this process: cancel it
                    # locally; the owner was never told.
                    buf.remove(key)
                    return
        if owner is None or self._shutdown_flag:
            return
        # Respect the per-owner backoff the registration path maintains:
        # releases to a DEAD owner must not pay an inline TCP connect
        # attempt per ref from refcount hot paths. While backed off the
        # removal is skipped (same best-effort contract: pins until this
        # process exits, never frees early).
        if self._in_borrow_backoff(owner):
            return
        try:
            self._pool.get(owner).notify("remove_borrower", key,
                                         self.owner_addr)
        except Exception:
            _prev, fails = self._borrow_flush_backoff.get(owner, (0, 0))
            fails = min(fails + 1, 10)
            self._borrow_flush_backoff[owner] = (
                time.monotonic() + min(60.0, 2.0 ** fails), fails)

    def _in_borrow_backoff(self, owner_addr: str) -> bool:
        ent = self._borrow_flush_backoff.get(owner_addr)
        return ent is not None and time.monotonic() < ent[0]

    def _flush_borrows(self, owner_addr: str, oid_blobs: list) -> None:
        try:
            self._pool.get(owner_addr).notify(
                "add_borrowers", oid_blobs, self.owner_addr)
            self._borrow_flush_backoff.pop(owner_addr, None)
        except Exception:
            # A dropped notify must not permanently skip registration (the
            # key is already in _borrows_sent, so nothing would ever retry
            # and the owner could free an object we still hold once the
            # transfer pin expires). Re-enqueue so the next sweep retries —
            # with exponential backoff and a bounded buffer, so a dead
            # owner costs neither inline RPC stalls nor unbounded memory.
            _prev, fails = self._borrow_flush_backoff.get(owner_addr, (0, 0))
            fails = min(fails + 1, 10)
            self._borrow_flush_backoff[owner_addr] = (
                time.monotonic() + min(60.0, 2.0 ** fails), fails)
            with self._borrow_buf_lock:
                buf = self._borrow_buf.setdefault(owner_addr, [])
                buf.extend(oid_blobs)
                cap = cfg.borrow_buffer_max
                if len(buf) > cap:
                    # Dropped keys must leave _borrows_sent too, else a
                    # later deserialization of the same ref would be
                    # dedup-skipped and the borrow never registered —
                    # and _borrowed_owners, else the dropped (never
                    # delivered) registration leaks its owner mapping
                    # and later sends a spurious removal.
                    for k in buf[:-cap]:
                        self._borrows_sent.pop(k, None)
                        self._borrowed_owners.pop(k, None)
                    del buf[:-cap]

    def _flush_all_borrows(self) -> None:
        with self._borrow_buf_lock:
            bufs = {a: b for a, b in self._borrow_buf.items()
                    if not self._in_borrow_backoff(a)}
            for a in bufs:
                del self._borrow_buf[a]
        for owner_addr, oid_blobs in bufs.items():
            self._flush_borrows(owner_addr, oid_blobs)

    def pin_for_transfer(self, oid: ObjectID,
                         owner_addr: Optional[str]) -> None:
        """Owner-side: an owned ref is being serialized into an outbound
        message. Hold a local ref for `transfer_pin_ttl_s` so the value
        survives until the receiver's add_borrower registration lands
        (simplified form of the reference's in-flight borrow accounting;
        the TTL bounds the leak if the message or registration is lost)."""
        if owner_addr is not None and owner_addr != self.owner_addr:
            return
        self.refcount.add_local_ref(oid)
        self._transfer_pins.append(
            (time.monotonic() + cfg.transfer_pin_ttl_s, oid))

    def _sweep_transfer_pins(self) -> None:
        if self._borrow_buf:
            self._flush_all_borrows()
        now = time.monotonic()
        while self._transfer_pins and self._transfer_pins[0][0] <= now:
            _, oid = self._transfer_pins.popleft()
            self.refcount.remove_local_ref(oid)
        # Finalizer-queued decrements apply here even when the process is
        # otherwise idle (ObjectRef.__del__ can only enqueue).
        self.refcount.flush_deferred()

    # ---------------------------------------------- object notify batching

    def _queue_object_notify(self, kind: str, oid_bytes: bytes,
                             size=None) -> None:
        """Queue an object_added/object_removed for the batched flush.
        Order within the outbox is preserved, so an add followed by a
        remove of the same object lands in the right order at the head."""
        self._obj_notify_outbox.append((kind, oid_bytes, size))
        self._obj_notify_event.set()

    def _obj_notify_loop(self) -> None:
        window = cfg.object_notify_flush_ms / 1000.0
        while not self._shutdown_flag:
            self._obj_notify_event.wait(0.5)
            # Clear BEFORE the emptiness check: an append that raced the
            # previous flush re-set the event with an already-drained
            # outbox, and clearing only on the non-empty path would turn
            # this loop into a busy spin. An append after this clear
            # re-sets the event, so nothing is lost.
            self._obj_notify_event.clear()
            if not self._obj_notify_outbox:
                continue
            if window > 0:
                time.sleep(window)  # coalesce the burst behind one frame
            self._flush_object_notifies()

    def _flush_object_notifies(self) -> None:
        # One flusher at a time: drain AND send under the lock so two
        # racing flushes can't send an oid's add and rm out of order.
        with self._obj_notify_flush_lock:
            outbox = self._obj_notify_outbox
            while outbox:
                batch = []
                while outbox and len(batch) < 4096:
                    try:
                        batch.append(outbox.popleft())
                    except IndexError:
                        break
                if not batch:
                    return
                try:
                    # Via the LOCAL node manager, not the head directly:
                    # the node mirrors its own holder set from these
                    # frames and forwards them, so a restarted head can
                    # be rehydrated by the node (see NodeManager.
                    # _on_head_reregistered). Same best-effort contract.
                    if _rpcdbg.enabled():
                        # RTPU_DEBUG_RPC: per-sender sequence stamp so
                        # the node can assert no frame reordering /
                        # re-delivery (add/rm inversion witness).
                        batch = _rpcdbg.stamp_outbox(self.owner_addr,
                                                     batch)
                    self.node.notify("object_batch", batch)
                except Exception:
                    return  # best-effort, like the old per-object notifies

    # ------------------------------------------------------ object locality

    def _note_object_location(self, oid_bytes: bytes, node_id: Optional[str],
                              size) -> None:
        if not node_id:
            return
        with self._obj_loc_lock:
            self._obj_locality[oid_bytes] = (node_id, int(size or 0))
            self._obj_locality.move_to_end(oid_bytes)
            while len(self._obj_locality) > cfg.object_locality_cache_max:
                self._obj_locality.popitem(last=False)

    def _preferred_node(self, info: "_InflightTask") -> Optional[str]:
        """The node holding the plurality of this task's input bytes per
        the local locality cache; None when no input location is known.
        Memoized on the task once resolved (a None answer is retried —
        completions may land locations after the first dispatch look)."""
        arg_ids = info.arg_ids
        if not arg_ids:
            return None
        if info.pref_node is not False:
            return info.pref_node
        best_node = None
        best_bytes = 0
        per_node: Dict[str, int] = {}
        with self._obj_loc_lock:
            for oid in arg_ids:
                ent = self._obj_locality.get(oid.binary())
                if ent is None:
                    continue
                node_id, size = ent
                b = per_node.get(node_id, 0) + (size or 1)
                per_node[node_id] = b
                if b > best_bytes:
                    best_node, best_bytes = node_id, b
        if best_node is not None:
            info.pref_node = best_node
        return best_node

    def _release_object(self, oid: ObjectID) -> None:
        memory_only = self.memory_store.delete([oid])
        if memory_only:
            # Small inlined result: it never touched the shm store — skip
            # the C delete + spill-unlink syscalls (per-task-return hot
            # path; the shm attempt was ~1/4 of release cost).
            return
        with self._obj_loc_lock:
            self._obj_locality.pop(oid.binary(), None)
        if self.store.delete(oid):
            _resdbg.note_event("store_delete")
            self._queue_object_notify("rm", oid.binary())

    # ------------------------------------------------------------------ put/get

    def put(self, value: Any, _owner=None, inline_ok: bool = True
            ) -> ObjectRef:
        """``inline_ok=False`` forces the shm store even for small
        values: inlined objects live in the OWNER's memory store and die
        with it, while store-backed objects survive on the node — the
        contract long-lived data-plane producers (streaming Dataset
        operator actors) need so their outputs outlive the actor."""
        oid = ObjectID.for_put(self.current_task_id(), next(self._put_counter))
        self.refcount.add_owned_object(oid)
        if isinstance(value, TaskError):
            self.memory_store.put(oid, value, is_exception=True)
            return ObjectRef(oid, self.owner_addr)
        header, buffers = SERIALIZER.serialize(value)
        total = SERIALIZER.encode_total_size(header, buffers)
        if inline_ok and total <= cfg.object_store_inline_max_bytes:
            self.memory_store.put(oid, value)
        else:
            self._put_plasma(oid, header, buffers)
            self.memory_store.put(oid, PlasmaStub(oid))
            self._note_object_location(oid.binary(), self.node_id, total)
        from ray_tpu.util import metrics

        metrics.OBJECTS_PUT.inc()
        metrics.PUT_BYTES.inc(total)
        return ObjectRef(oid, self.owner_addr)

    def _put_plasma(self, oid: ObjectID, header: bytes, buffers) -> None:
        total = SERIALIZER.encode_total_size(header, buffers)
        deadline = time.monotonic() + cfg.put_create_retry_deadline_s
        takeover_at = time.monotonic() + 5.0
        while True:
            try:
                mv = self.store.create_buffer(oid, total)
                break
            except ShmObjectExistsError:
                # A concurrent writer (a re-routed duplicate execution on
                # another worker) holds the slot. Returning immediately
                # here minted GHOST objects: if that writer later ABORTS
                # (store pressure, crash), its unsealed copy vanishes
                # while our completion already told the owner "in_store".
                # Wait for the other copy to SEAL; if it disappears
                # instead, take over and write it ourselves.
                buf = self.store.get(oid, timeout_ms=200)
                if buf is not None:
                    buf.release()
                    return  # sealed by the other writer — done
                if not self.store.contains(oid):
                    if time.monotonic() > takeover_at:
                        # Unsealed for seconds: if the slot is a PENDING
                        # placeholder, its creator died mid-create (a
                        # live create's pending window is milliseconds)
                        # and nothing else can ever clear it. Reclaim
                        # touches only pending slots — a live writer
                        # mid-write keeps its buffer and we keep waiting.
                        self.store.reclaim_pending(oid)
                        takeover_at = time.monotonic() + 5.0
                    continue  # aborted/reclaimed: retry the create
                if time.monotonic() > deadline:
                    raise
        try:
            SERIALIZER.encode_into(mv, header, buffers)
        except BaseException:
            self.store.abort(oid)
            raise
        self.store.seal(oid)
        _resdbg.note_event("store_seal")
        self._queue_object_notify("add", oid.binary(), total)

    def _read_plasma(self, oid: ObjectID, timeout: Optional[float],
                     owner: Optional[str] = None) -> Any:
        buf = self.store.get(oid, timeout_ms=0)
        if buf is None:
            # Not local: ask the node manager to pull it here. Short pull
            # rounds (idempotent) rather than one long blocking RPC, so a
            # chaos-dropped request costs seconds, not the whole timeout.
            deadline = time.monotonic() + (timeout if timeout is not None
                                           else 600.0)
            ok = False
            failed_pulls = 0
            pull_trace = None
            if cfg.tracing_enabled:
                # Parent the node-side pull (and its per-holder fetch
                # spans) to the requesting task's span.
                from ray_tpu.util import tracing as _tr

                pull_trace = _tr.current()
            with self._blocked_scope():
                while not ok and time.monotonic() < deadline:
                    try:
                        ok = bool(self.node.call("pull_object", oid.binary(),
                                                 5000, pull_trace,
                                                 timeout=8))
                    except ConnectionLost:
                        # Dead socket fails instantly — back off + reconnect
                        # or this loop becomes a hot spin for the full
                        # deadline.
                        time.sleep(0.2)
                        try:
                            self.node.reconnect()
                        except OSError:
                            pass
                        ok = False
                    except TimeoutError:
                        ok = False
                    if not ok and self.store.contains(oid):
                        ok = True
                    if not ok:
                        failed_pulls += 1
                        if failed_pulls >= 2:
                            # Every copy is likely gone (node death):
                            # lineage recovery — owner resubmits the
                            # creating task; borrowers ask the owner to.
                            self._request_recovery(oid, owner)
            if not ok:
                raise GetTimeoutError(f"object {oid.hex()} unavailable")
            buf = self.store.get(oid, timeout_ms=5000)
            while buf is None and time.monotonic() < deadline:
                # Present a moment ago but the read missed: a restore from
                # spill can fail transiently while concurrent readers pin
                # the arena (out-of-core exchanges run at exactly this
                # pressure). Back off briefly and retry within the
                # deadline instead of failing the task.
                time.sleep(cfg.object_poll_interval_s)
                buf = self.store.get(oid, timeout_ms=5000)
            if buf is None:
                raise GetTimeoutError(f"object {oid.hex()} unavailable")
        # Zero-copy decode: views are taken over memoryview(buf), whose
        # exporter is the PinnedBuffer itself — every deserialized numpy
        # array transitively keeps the pin alive, so LRU eviction can never
        # reuse the arena block under live user data. The pin drops when the
        # last view is garbage-collected (PinnedBuffer.__buffer__).
        try:
            view = memoryview(buf)
        except TypeError:
            # Python < 3.12 has no PEP 688 __buffer__ hook, so PinnedBuffer
            # cannot export: decode from a COPY and release the pin now.
            # Correctness over zero-copy — without an exporter tie, LRU
            # eviction could reuse the arena under live views.
            data = bytes(buf.buffer)
            buf.release()
            return SERIALIZER.decode(memoryview(data))
        return SERIALIZER.decode(view)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() expects ObjectRef, got {type(r).__name__}")
        # Batch fast path: every ref owned locally -> ONE memory-store wait
        # for the whole list (per-ref lock/scope round-trips dominated
        # large fan-in gets).
        if len(ref_list) > 1 and all(
                r.owner_address is None or r.owner_address == self.owner_addr
                for r in ref_list):
            oids = [r.id() for r in ref_list]
            try:
                recs = self.memory_store.get(oids, 0)
            except GetTimeoutError:
                with self._blocked_scope():
                    recs = self.memory_store.get(oids, timeout)
            return [self.resolve_record(rec) for rec in recs]
        out = []
        deadline = None if timeout is None else time.monotonic() + timeout
        for r in ref_list:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            out.append(self._get_one(r, remaining))
        return out[0] if single else out

    def _get_one(self, ref: ObjectRef, timeout: Optional[float]) -> Any:
        oid = ref.id()
        owner = ref.owner_address
        if owner is None or owner == self.owner_addr:
            if self.memory_store.contains(oid):  # fast path: no RPCs
                recs = self.memory_store.get([oid], 0)
            else:
                with self._blocked_scope():
                    recs = self.memory_store.get([oid], timeout)
            return self.resolve_record(recs[0])
        # Borrowed ref: if the bytes are already in the local shm store (or
        # pullable), prefer that; else ask the owner. Short poll rounds: a
        # chaos-dropped request/reply is retried instead of failing the get.
        if self.store.contains(oid):
            return self._read_plasma(oid, timeout)
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._blocked_scope():
            return self._get_borrowed(ref, oid, owner, deadline, timeout)

    def _get_borrowed(self, ref: ObjectRef, oid: ObjectID, owner: str,
                      deadline: Optional[float],
                      timeout: Optional[float]) -> Any:
        while True:
            t = 10.0 if deadline is None else min(
                10.0, deadline - time.monotonic())
            if t <= 0:
                raise GetTimeoutError(f"timed out waiting for {oid.hex()}")
            try:
                kind, payload = self._pool.get(owner).call(
                    "get_object", oid.binary(), t, timeout=t + 5)
            except ConnectionLost:
                raise WorkerCrashedError(
                    f"owner of {oid.hex()} died") from None
            except TimeoutError:
                continue  # dropped in transit; owner-side get is idempotent
            if kind == "timeout":
                continue  # not ready yet; loop until our own deadline
            break
        if kind == "value":
            return SERIALIZER.decode(payload)
        if kind == "error":
            raise payload
        if kind == "in_store":
            return self._read_plasma(oid, timeout, owner=owner)
        raise RuntimeError(f"unexpected get_object reply {kind}")

    def wait(self, refs: List[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True):
        """Event-driven wait: owned refs register memory-store callbacks;
        borrowed refs long-poll their owner (one `wait_object` RPC per ref,
        not a poll-per-tick storm — the reference's Wait is likewise
        subscription-based, core_worker.h:682)."""
        # ONE pass extracts ids, checks uniqueness, and detects borrowed
        # refs (this runs per call in pop-1-of-1k wait loops — every extra
        # pass over `refs` multiplies into O(n^2) drain cost; fusing the
        # id/uniqueness/ownership passes measurably moves the
        # wait_1k_refs benchmark row).
        my_addr = self.owner_addr
        oids = []
        seen: set = set()
        all_owned = True
        hits: List[int] = []  # indices of already-ready refs (fast path)
        objs = self.memory_store.objects_view()
        need = num_returns
        for i, r in enumerate(refs):
            oid = r._id
            oids.append(oid)
            if oid in seen:
                raise ValueError("wait() requires unique object refs")
            seen.add(oid)
            oa = r._owner_addr
            if oa is not None and oa != my_addr:
                all_owned = False
            elif len(hits) < need and oid in objs:
                # Readiness probe rides the same pass (dict membership is
                # GIL-atomic; values are never read here).
                hits.append(i)
        # Fast path: enough refs already resolved locally -> C-speed list
        # partition, zero callback registration/removal churn.
        if all_owned and len(hits) >= need:
            not_ready = list(refs)
            ready = [not_ready.pop(i) for i in reversed(hits)]
            ready.reverse()
            return ready, not_ready
        if all_owned:
            # All-local waits ride the store's condvar directly (the
            # put_batch wakeup) — zero per-ref callback churn.
            with self._blocked_scope():
                ready_now = self.memory_store.wait(
                    oids, num_returns, timeout)
            ready, not_ready = [], []
            n_ready = 0
            for r, oid in zip(refs, oids):
                if oid in ready_now and n_ready < num_returns:
                    ready.append(r)
                    n_ready += 1
                else:
                    not_ready.append(r)
            return ready, not_ready
        deadline = None if timeout is None else time.monotonic() + timeout
        cv = threading.Condition()
        ready_ids: set = set()
        waiting = True

        def mark(oid: ObjectID) -> None:
            with cv:
                ready_ids.add(oid)
                cv.notify_all()

        registered: List[Tuple[ObjectID, Any]] = []
        remote_by_owner: Dict[str, List[ObjectID]] = {}
        for r in refs:
            oid = r.id()
            if r.owner_address in (None, self.owner_addr):
                cb = lambda rec, o=oid: mark(o)  # noqa: E731
                self.memory_store.get_async(oid, cb)
                registered.append((oid, cb))
            elif self.store.contains(oid):
                mark(oid)
            else:
                remote_by_owner.setdefault(r.owner_address, []).append(oid)
        for owner, oids in remote_by_owner.items():
            # One long-poll thread per OWNER covering all its refs (not one
            # per ref): a wait over 1k refs costs O(owners) RPCs per poll.
            threading.Thread(
                target=self._wait_remote_loop,
                args=(owner, oids, deadline, mark, lambda: waiting),
                daemon=True, name="wait-remote").start()
        try:
            with self._blocked_scope(), cv:
                while len(ready_ids) < num_returns:
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        break
                    cv.wait(remaining)
                snapshot = set(ready_ids)
        finally:
            waiting = False
            for oid, cb in registered:
                self.memory_store.remove_callback(oid, cb)
        ready, not_ready = [], []
        for r in refs:
            (ready if r.id() in snapshot and len(ready) < num_returns
             else not_ready).append(r)
        return ready, not_ready

    def _wait_remote_loop(self, owner: str, oids: List[ObjectID],
                          deadline: Optional[float], mark,
                          still_waiting) -> None:
        pending = set(oids)
        while still_waiting() and pending:
            for oid in [o for o in pending if self.store.contains(o)]:
                mark(oid)
                pending.discard(oid)
            if not pending:
                return
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                return
            # Short poll chunks keep orphaned threads (wait() returned early)
            # from pinning an owner-side handler thread for long.
            poll = 5.0 if remaining is None else min(remaining, 5.0)
            try:
                ready = self._pool.get(owner).call(
                    "wait_objects", [o.binary() for o in pending], poll,
                    timeout=poll + 5)
                for ob in ready:
                    oid = ObjectID(ob)
                    mark(oid)
                    pending.discard(oid)
            except Exception:
                time.sleep(cfg.object_poll_interval_s)

    # --------------------------------------------------------- recovery

    def _request_recovery(self, oid: ObjectID, owner: Optional[str]) -> None:
        """Trigger re-creation of a lost object: locally if we own it,
        else by asking the owner (which has the lineage)."""
        if owner is None or owner == self.owner_addr:
            self._maybe_recover_object(oid)
            return
        try:
            self._pool.get(owner).notify("recover_object", oid.binary())
        except Exception:
            pass

    def rpc_recover_object(self, conn, oid_bytes: bytes):
        """Borrower-initiated recovery request for an object I own."""
        self._maybe_recover_object(ObjectID(oid_bytes))
        return True

    def _maybe_recover_object(self, oid: ObjectID, _depth: int = 0) -> bool:
        """Resubmit the creating task of a lost owned object (transitively
        for its lost arguments). Rate-limited per task; returns True if a
        resubmission happened or is already underway."""
        if _depth > 16:
            return False
        found = self.lineage.for_object(oid)
        if found is None:
            return False
        # Confirm the object is actually LOST (no live location) before
        # re-executing: transient pull failures against a slow-but-alive
        # holder must not duplicate a side-effecting task.
        if _depth == 0 and self._object_available(oid):
            return False
        task_key, rec = found
        now = time.monotonic()
        with self._recover_lock:
            last = self._recovering.get(task_key, 0.0)
            if now - last < 30.0:
                return True  # a recovery attempt is already in flight
            self._recovering[task_key] = now
            # Bounded memory: drop stale entries opportunistically.
            if len(self._recovering) > cfg.recovering_ids_max:
                cutoff = now - 300.0
                self._recovering = {k: v for k, v in
                                    self._recovering.items() if v > cutoff}
        # Recursive step: re-create lost owned args FIRST, so the
        # resubmitted task's fetches can succeed (reference:
        # object_recovery_manager.h pinning-or-reconstruct walk).
        for arg in rec.arg_ids:
            if not self._object_available(arg):
                self._maybe_recover_object(arg, _depth + 1)
        # Fresh task id: worker-side exactly-once dedup must not swallow
        # the resubmission (the original id may have executed anywhere).
        spec = SERIALIZER.decode(rec.spec_blob)
        new_task_id = TaskID.for_task(ActorID.nil_for_job(self.job_id))
        spec["task_id"] = new_task_id.binary()
        new_blob = SERIALIZER.encode(spec)
        info = _InflightTask(new_blob, rec.return_ids, None, 0,
                             rec.sched_key, rec.resources, rec.strategy,
                             rec.name + "[recovery]",
                             getattr(rec, "runtime_env", None))
        info.arg_ids = list(rec.arg_ids)
        # Re-point the lineage mapping at the new spec so a SECOND loss
        # recovers from the resubmitted task, and re-protect the args.
        from ray_tpu.core.lineage import LineageRecord

        self.lineage.record(new_task_id.binary(), LineageRecord(
            new_blob, rec.sched_key, rec.resources, rec.strategy, rec.name,
            rec.return_ids, rec.arg_ids,
            runtime_env=getattr(rec, "runtime_env", None)))
        for arg in rec.arg_ids:
            self.refcount.add_submitted_task_ref(arg)
        with self._inflight_lock:
            self._submitted_args[new_task_id.binary()] = list(rec.arg_ids)
        self._enqueue_task(new_task_id.binary(), info)
        return True

    def _object_available(self, oid: ObjectID) -> bool:
        """Is an owned object's value still reachable somewhere?"""
        if self.store.contains(oid):
            return True
        if self.memory_store.contains(oid):
            recs = self.memory_store.get([oid], 0)
            if not recs[0].in_plasma:
                return True  # inline value lives in the owner itself
            try:
                locs = self.head.call("object_locations", oid.binary(),
                                      timeout=5)
            except Exception:
                return True  # can't tell; assume fine (pull will retry)
            return bool(locs)
        return False

    # -------------------------------------------------------------- owner RPC

    @blocking_rpc
    def rpc_get_object(self, conn, oid_bytes: bytes, timeout: float):
        """Serve a get() for an object I own. timeout=0 is a non-blocking
        readiness probe; only timeout=None blocks indefinitely."""
        oid = ObjectID(oid_bytes)
        try:
            recs = self.memory_store.get(
                [oid], None if timeout is None else timeout)
        except GetTimeoutError:
            return "timeout", None
        rec = recs[0]
        if rec.is_exception:
            return "error", rec.value
        if rec.in_plasma:
            return "in_store", None
        return "value", SERIALIZER.encode(rec.value)

    @blocking_rpc
    def rpc_wait_object(self, conn, oid_bytes: bytes, timeout: float):
        """Long-poll readiness probe for an object I own (serves remote
        wait()); never ships the value."""
        try:
            self.memory_store.get([ObjectID(oid_bytes)], timeout)
            return True
        except GetTimeoutError:
            return False

    @blocking_rpc
    def rpc_wait_objects(self, conn, oid_bytes_list: List[bytes],
                         timeout: float):
        """Batched long-poll: returns the (possibly empty) subset of the
        given owned objects that are ready, blocking until at least one is
        or the timeout lapses."""
        oids = [ObjectID(b) for b in oid_bytes_list]
        ready = self.memory_store.wait(oids, 1, timeout, return_all=True)
        return [o.binary() for o in ready]

    def rpc_add_borrowers(self, conn, oid_blobs: list, borrower: str):
        for oid_bytes in oid_blobs:
            self.refcount.add_borrower(ObjectID(oid_bytes), borrower)
        return True

    def rpc_remove_borrower(self, conn, oid_bytes: bytes, borrower: str):
        self.refcount.remove_borrower(ObjectID(oid_bytes), borrower)
        return True

    def _register_submitted_args(self, task_id_bytes: bytes, args,
                                 kwargs) -> List[ObjectID]:
        oids: List[ObjectID] = []
        _scan_object_refs((args, kwargs), oids)
        if not oids:
            return oids
        for oid in oids:
            self.refcount.add_submitted_task_ref(oid)
        with self._inflight_lock:
            self._submitted_args[task_id_bytes] = oids
        return oids

    def _release_submitted_args(self, task_id_bytes: bytes) -> None:
        with self._inflight_lock:
            oids = self._submitted_args.pop(task_id_bytes, None)
        for oid in oids or ():
            self.refcount.remove_submitted_task_ref(oid)

    def _complete_task(self, task_id_bytes: bytes,
                       results: List[Tuple[bytes, str, Any]],
                       span, puts: list) -> None:
        """Shared completion bookkeeping; value deliveries are appended to
        ``puts`` so batched completions land in ONE memory-store pass."""
        with self._inflight_lock:
            info = self._inflight.pop(task_id_bytes, None)
        self._release_submitted_args(task_id_bytes)
        status = ("error" if any(k == "error" for _o, k, _p in results)
                  else "ok")
        if span is not None:
            from ray_tpu.util import metrics, timeline

            t0, t1, name = span
            timeline.record_event(name, "task", t0, t1,
                                  args={"task_id": task_id_bytes.hex()[:12],
                                        "status": status})
            metrics.TASKS_FINISHED.inc()
            metrics.TASK_EXEC_SECONDS.observe(max(0.0, t1 - t0))
            event = {
                "task_id": task_id_bytes.hex(), "name": name,
                "duration_s": round(t1 - t0, 6), "status": status,
                "end_ts": t1}
            self._recent_tasks.append(event)
            # Cluster-wide visibility: events flush to the head in the
            # periodic sweep (reference: TaskEventBuffer -> GcsTaskManager,
            # gcs_task_manager.h:86 — list_tasks from ANY driver must see
            # EVERY owner's tasks, not just its own).
            self._task_event_outbox.append(event)
        for oid_bytes, kind, payload in results:
            oid = ObjectID(oid_bytes)
            if kind == "value":
                puts.append((oid, SERIALIZER.decode(payload), False))
            elif kind == "error":
                puts.append((oid, payload, True))
            else:
                # "in_store" payloads carry (node_id, size) of the sealed
                # copy: free locality data for downstream scheduling.
                if isinstance(payload, (tuple, list)) and len(payload) == 2:
                    self._note_object_location(oid_bytes, payload[0],
                                               payload[1])
                puts.append((oid, PlasmaStub(oid), False))
        if info is not None:
            self._lease_task_finished(
                info.sched_key, info.worker_addr,
                max(0.0, span[1] - span[0]) if span is not None else None)

    def rpc_task_done(self, conn, task_id_bytes: bytes,
                      results: List[Tuple[bytes, str, Any]],
                      span: Optional[Tuple[float, float, str]] = None):
        """Completion push from the executing worker.
        results: [(oid_bytes, kind, payload)] kind in value|error|in_store;
        span: (exec_start, exec_end, name) for timeline/metrics."""
        puts: list = []
        self._complete_task(task_id_bytes, results, span, puts)
        self.memory_store.put_batch(puts)
        return True

    def rpc_batch_done(self, conn_ctx, entries):
        """Batched completion sink: each entry is ("task"|"actor", args)
        routed to the idempotent per-completion handlers. Records per-entry
        event stats under the routed method name so state.rpc_event_stats()
        accounting stays identical to the unbatched path."""
        from ray_tpu.cluster import protocol

        stats_on = protocol._stats_on()
        puts: list = []
        notifies: list = []
        try:
            for kind, payload in entries:
                method = "actor_call_done" if kind == "actor" else "task_done"
                t0 = time.monotonic() if stats_on else 0.0
                ok = True
                try:
                    if kind == "actor":
                        (actor_id_bytes, seq, task_id_bytes,
                         results, span) = payload
                        aconn = self._actor_conn(ActorID(actor_id_bytes))
                        with aconn.lock:
                            aconn.pending.pop(seq, None)
                        self._complete_task(task_id_bytes, results, span,
                                            puts)
                    elif kind == "stream":
                        self._handle_stream_item(payload[0], payload[1],
                                                 payload[2], puts,
                                                 notifies)
                    elif kind == "stream_end":
                        self._handle_stream_end(payload[0], payload[1],
                                                payload[2], payload[3],
                                                puts, notifies)
                    else:
                        self._complete_task(payload[0], payload[1],
                                            payload[2] if len(payload) > 2
                                            else None, puts)
                except Exception:
                    ok = False
                    raise
                finally:
                    if stats_on:
                        protocol._record_event_stat(
                            method, time.monotonic() - t0, ok)
        finally:
            # A poison entry must not discard the completed entries'
            # results: their inflight/lease bookkeeping already ran, and
            # dropping the values would strand their owners in get().
            self.memory_store.put_batch(puts)
            # Stream consumers wake only after their objects are gettable.
            self._fire_stream_notifies(notifies)
        return True

    def rpc_ping(self, conn):
        return "pong"

    def rpc_clock_probe(self, conn):
        return time.time()

    def rpc_dump_flight(self, conn):
        """This process's flight-recorder ring (drivers/workers serve it
        too — trace_dump and post-mortems read any process)."""
        from ray_tpu.util import flight_recorder as _fl

        payload = _fl.dump_payload()
        payload["node_id"] = self.node_id
        return payload

    # ------------------------------------------------------------------ tasks

    def current_task_id(self) -> TaskID:
        ctx = runtime_context.current_worker_context()
        return ctx.get("task_id") or self._driver_task_id

    def current_actor_id(self) -> Optional[ActorID]:
        return runtime_context.current_worker_context().get("actor_id")

    def current_resources(self) -> Dict[str, float]:
        return runtime_context.current_worker_context().get("resources", {})

    def _export_function(self, func: Callable) -> bytes:
        """Export ``func`` to the head's function table once; return its
        digest. Subsequent submits of the same function object reuse the
        cached digest, so the per-task cost is a dict lookup instead of a
        cloudpickle round.

        Export-once semantics (matches the reference function manager,
        python/ray/_private/function_manager.py): the snapshot taken at
        first submit is what executes — mutating captured closure state
        after the first ``.remote()`` does NOT re-export. Create a new
        function object (or a fresh ``.options()``-bound task) to ship new
        state. The local digest cache is LRU-bounded (``_fn_cache``) so
        unique-lambda loops don't grow it without bound; the head-side
        ``__fn__`` KV namespace is job-scoped and dropped with the job."""
        try:
            digest = self._fn_exports.get(func)
        except TypeError:  # unhashable/unweakrefable callable
            digest = None
        if digest is not None:
            return digest
        import hashlib

        blob = SERIALIZER.encode(func)
        digest = hashlib.sha1(blob).digest()
        with self._fn_exports_lock:
            if digest not in self._fn_cache:
                # Export lock spans the kv_put BY DESIGN: it single-
                # flights concurrent exports of one function (dedup) and
                # is never taken on the dispatch/cache hot path (that is
                # what _fn_cache_lock is for).
                self.head.retrying_call("kv_put", "__fn__", digest, blob,  # rtpu-lint: disable=blocking-under-lock
                                        False, timeout=10)
                self._fn_cache_put(digest, func)
        try:
            self._fn_exports[func] = digest
        except TypeError:
            pass
        return digest

    def _fn_cache_put(self, digest: bytes, fn: Callable) -> None:
        with self._fn_cache_lock:
            self._fn_cache[digest] = fn
            self._fn_cache.move_to_end(digest)
            while len(self._fn_cache) > self._fn_cache_max:
                self._fn_cache.popitem(last=False)

    def _fetch_function(self, digest: bytes) -> Callable:
        """Resolve a task's function digest via the local cache, falling
        back to one head KV fetch per (process, function)."""
        with self._fn_cache_lock:
            fn = self._fn_cache.get(digest)
            if fn is not None:
                self._fn_cache.move_to_end(digest)
                return fn
        blob = self.head.retrying_call("kv_get", "__fn__", digest,
                                       timeout=10)
        if blob is None:
            raise RuntimeError(
                "function table entry missing (head lost its KV state?)")
        fn = SERIALIZER.decode(blob)
        self._fn_cache_put(digest, fn)
        return fn

    def submit_task(self, func: Callable, args: Sequence, kwargs: Dict,
                    num_returns: int = 1, resources=None, max_retries: int = 0,
                    retry_exceptions: bool = False, scheduling_strategy=None,
                    name: str = "", runtime_env=None) -> List[ObjectRef]:
        tmpl = self.make_submit_template(
            func, num_returns=num_returns, resources=resources,
            max_retries=max_retries, retry_exceptions=retry_exceptions,
            scheduling_strategy=scheduling_strategy, name=name,
            runtime_env=runtime_env)
        return self.submit_templated(tmpl, args, kwargs)

    def make_submit_template(self, func: Callable, *, num_returns: int = 1,
                             resources=None, max_retries: int = 0,
                             retry_exceptions: bool = False,
                             scheduling_strategy=None, name: str = "",
                             runtime_env=None,
                             generator_backpressure_num_objects=None
                             ) -> "_SubmitTemplate":
        """Precompute everything about a submission that does not vary per
        call (reference analog: the per-SchedulingKey caching inside
        NormalTaskSubmitter). ``RemoteFunction`` caches the result, so the
        ``f.remote()`` hot loop skips option normalization, strategy/
        sched-key construction and the constant spec fields entirely."""
        from ray_tpu.core.runtime_env import (runtime_env_hash,
                                              validate_runtime_env)

        runtime_env = validate_runtime_env(runtime_env)
        res = _as_resource_dict(resources)
        res.setdefault("CPU", 1.0)
        strategy = _strategy_dict(scheduling_strategy)
        task_name = name or getattr(func, "__name__", "task")
        spread = bool(strategy and strategy.get("kind") == "spread")
        streaming = num_returns == "streaming"
        if streaming:
            num_returns = 0
        sched_key = None
        if not spread:
            sched_key = _sched_key(func, res, strategy)
            if runtime_env is not None:
                # Distinct envs must never share leases/workers.
                sched_key = sched_key + (runtime_env_hash(runtime_env),)
        spec_proto = {
            "task_id": b"",
            "func_digest": self._export_function(func),
            "args": (),
            "kwargs": {},
            "return_ids": (),
            "owner_addr": self.owner_addr,
            "name": task_name,
            "resources": res,
            "retry_exceptions": retry_exceptions,
            "max_retries": max_retries,
        }
        if streaming:
            spec_proto["streaming"] = True
            if generator_backpressure_num_objects is not None:
                spec_proto["stream_ahead"] = int(
                    generator_backpressure_num_objects)
        return _SubmitTemplate(
            func, num_returns, res, strategy, task_name, sched_key, spread,
            max_retries if retry_exceptions else 0, runtime_env,
            runtime_env_hash(runtime_env) if runtime_env is not None
            else None, spec_proto, streaming)

    def submit_templated(self, tmpl: "_SubmitTemplate", args: Sequence,
                         kwargs: Dict) -> List[ObjectRef]:
        task_id = TaskID.for_task(self._nil_actor)
        task_id_bytes = task_id.binary()
        return_ids = [ObjectID.for_task_return(task_id, i)
                      for i in range(tmpl.num_returns)]
        for oid in return_ids:
            self.refcount.add_owned_object(oid)
        refs = [ObjectRef(oid, self.owner_addr) for oid in return_ids]

        spec = dict(tmpl.spec_proto)
        spec["task_id"] = task_id_bytes
        spec["args"] = tuple(args)
        spec["kwargs"] = dict(kwargs)
        spec["return_ids"] = [o.binary() for o in return_ids]
        trace_ctx = None
        t_submit = 0.0
        if cfg.tracing_enabled:
            from ray_tpu.util import tracing

            t_submit = time.time()
            ctx = tracing.current()
            if ctx is not None:
                spec["trace"] = ctx
                trace_ctx = ctx
        spec_blob = SERIALIZER.encode(spec)
        if tmpl.spread:
            sched_key = _sched_key(tmpl.func, tmpl.resources, tmpl.strategy)
            if tmpl.env_hash is not None:
                sched_key = sched_key + (tmpl.env_hash,)
        else:
            sched_key = tmpl.sched_key
        info = _InflightTask(spec_blob, return_ids, None,
                             tmpl.effective_retries, sched_key,
                             tmpl.resources, tmpl.strategy, tmpl.name,
                             tmpl.runtime_env, streaming=tmpl.streaming)
        info.trace_ctx = trace_ctx
        info.submit_t = t_submit
        _metrics.TASKS_SUBMITTED.inc()
        arg_ids = self._register_submitted_args(task_id_bytes, args, kwargs)
        info.arg_ids = arg_ids
        if tmpl.streaming:
            # No lineage for streams (v1): partial replay would duplicate
            # already-consumed items; a lost stream fails instead.
            with self._streams_lock:
                self._streams[task_id_bytes] = _StreamState()
            self._enqueue_task(task_id_bytes, info)
            self._emit_submit_span(info, t_submit)
            return ObjectRefGenerator(self, task_id)
        self.lineage.record(task_id_bytes, _LineageRecord(
            spec_blob, sched_key, tmpl.resources, tmpl.strategy, tmpl.name,
            return_ids, arg_ids, runtime_env=tmpl.runtime_env))
        self._enqueue_task(task_id_bytes, info)
        self._emit_submit_span(info, t_submit)
        return refs

    @staticmethod
    def _emit_submit_span(info: "_InflightTask", t_submit: float) -> None:
        """task.submit: spec build + arg registration + enqueue (the
        owner-side cost before the dispatcher takes over). Gated on the
        task's captured wire context so the untraced path is one None
        check."""
        if info.trace_ctx is None:
            return
        from ray_tpu.util import tracing

        tracing.emit_span("task.submit", t_submit, time.time(),
                          parent=info.trace_ctx,
                          attrs={"task": info.name,
                                 "args": len(info.arg_ids)})

    # ------------------------------------------------- streaming generators

    def _next_stream_ref(self, task_id: TaskID, index: int,
                         timeout: float) -> ObjectRef:
        """Block until yield #index has arrived (or the stream ended)."""
        task_id_bytes = task_id.binary()
        with self._streams_lock:
            st = self._streams.get(task_id_bytes)
        if st is None:
            raise StopIteration
        deadline = time.monotonic() + timeout
        with st.cv:
            while True:
                if st.received > index:
                    st.consumed = max(st.consumed, index + 1)
                    return ObjectRef(
                        ObjectID.for_stream_return(task_id, index),
                        self.owner_addr)
                if st.error is not None and st.received <= index:
                    self._drop_stream(task_id_bytes)
                    raise st.error
                if st.total is not None and index >= st.total:
                    self._drop_stream(task_id_bytes)
                    raise StopIteration
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise GetTimeoutError(
                        f"stream item {index} of task "
                        f"{task_id.hex()[:12]} not ready in {timeout}s")
                st.cv.wait(min(remaining, 1.0))

    def _drop_stream(self, task_id_bytes: bytes) -> None:
        with self._streams_lock:
            self._streams.pop(task_id_bytes, None)

    def _mark_cancelled(self, task_id: TaskID, force: bool = False) -> None:
        """Shared cancel bookkeeping: remember the id (bounded) and tell
        the executing worker, if dispatched (used by cancel() and stream
        abandonment). ``force`` rides the same (single) notify — the
        worker exits if the task is inside user code."""
        self._cancelled.add(task_id)
        self._cancelled_order.append(task_id)
        while len(self._cancelled_order) > cfg.cancelled_ids_max:
            self._cancelled.discard(self._cancelled_order.popleft())
        with self._inflight_lock:
            info = self._inflight.get(task_id.binary())
        if info is not None and info.worker_addr:
            try:
                self._pool.get(info.worker_addr).notify(
                    "cancel_task", task_id.binary(), force)
            except Exception:
                pass

    def _abandon_stream(self, task_id: TaskID) -> None:
        """The consumer dropped its generator: cancel producer-side and
        release every delivered-but-unconsumed item (consumed items'
        ObjectRefs release themselves through normal ref GC; items racing
        through rpc_batch_done are reconciled post-commit in
        _fire_stream_notifies)."""
        task_id_bytes = task_id.binary()
        with self._streams_lock:
            st = self._streams.pop(task_id_bytes, None)
        if st is None:
            return
        with st.cv:
            consumed, received = st.consumed, st.received
            st.error = TaskError(
                "StreamAbandoned", "stream abandoned by consumer")
            st.cv.notify_all()
        self._mark_cancelled(task_id)
        for idx in range(consumed, received):
            self._release_stream_item(task_id, idx)

    def _release_stream_item(self, task_id: TaskID, index: int) -> None:
        oid = ObjectID.for_stream_return(task_id, index)
        self.memory_store.delete([oid])
        try:
            self.refcount.drop_owned_object(oid)
        except Exception:
            pass

    def rpc_stream_consumed(self, conn, task_id_bytes: bytes) -> int:
        """Producer flow-control poll: how many items the consumer has
        taken (-1 = stream gone/abandoned; producer should stop)."""
        with self._streams_lock:
            st = self._streams.get(task_id_bytes)
        if st is None:
            return -1
        with st.cv:
            return st.consumed

    def _handle_stream_item(self, task_id_bytes: bytes, index: int,
                            result: Tuple[bytes, str, Any],
                            puts: list, notifies: list) -> None:
        with self._streams_lock:
            live = task_id_bytes in self._streams
        if not live:
            return  # abandoned: do not store (would pin forever)
        oid_bytes, kind, payload = result
        oid = ObjectID(oid_bytes)
        self.refcount.add_owned_object(oid)
        if kind == "value":
            puts.append((oid, SERIALIZER.decode(payload), False))
        elif kind == "error":
            puts.append((oid, payload, True))
        else:
            if isinstance(payload, (tuple, list)) and len(payload) == 2:
                self._note_object_location(oid_bytes, payload[0], payload[1])
            puts.append((oid, PlasmaStub(oid), False))
        # The consumer wakes only AFTER put_batch lands (the ref must be
        # gettable the moment __next__ returns): defer via `notifies`.
        notifies.append(("item", task_id_bytes, index))

    def _handle_stream_end(self, task_id_bytes: bytes, count: int,
                           error, span, puts: list, notifies: list) -> None:
        # Completion bookkeeping (inflight pop, lease credit, metrics).
        self._complete_task(task_id_bytes, [], span, puts)
        notifies.append(("end", task_id_bytes, count, error))

    def _fire_stream_notifies(self, notifies: list) -> None:
        for entry in notifies:
            with self._streams_lock:
                st = self._streams.get(entry[1])
            if st is None:
                # Stream abandoned while this batch was mid-commit: the
                # item landed in the store AFTER _abandon_stream's release
                # pass — reconcile here or it is owned forever with no
                # ref and no release path.
                if entry[0] == "item":
                    self._release_stream_item(TaskID(entry[1]), entry[2])
                continue
            with st.cv:
                if entry[0] == "item":
                    st.received = max(st.received, entry[2] + 1)
                else:
                    st.total = entry[2]
                    if entry[3] is not None:
                        st.error = entry[3]
                st.cv.notify_all()

    def _fail_stream(self, task_id_bytes: bytes, error) -> None:
        with self._streams_lock:
            st = self._streams.get(task_id_bytes)
        if st is not None:
            with st.cv:
                st.error = error
                st.total = st.received
                st.cv.notify_all()

    # ---- per-scheduling-key dispatch (reference: NormalTaskSubmitter's
    # per-SchedulingKey worker-lease pools + backlog, lease reuse via
    # OnWorkerIdle, rate-limited lease requests) ----

    def _enqueue_task(self, task_id_bytes: bytes, info: _InflightTask) -> None:
        key = info.sched_key
        info.enqueued_at = time.monotonic()
        with self._lease_lock:
            kq = self._key_queues.get(key)
            if kq is None:
                kq = self._key_queues[key] = _KeyQueue(key)
            if not kq.queue:
                # A fresh burst after quiescence starts with a clean slate:
                # stale saturation backoff must not delay its first lease.
                kq.lease_backoff = 0.0
                kq.next_lease_attempt = 0.0
            kq.queue.append((task_id_bytes, info))
            if not kq.dispatcher_running:
                kq.dispatcher_running = True
                threading.Thread(target=self._dispatch_loop, args=(kq,),
                                 daemon=True,
                                 name=f"dispatch-{key[0][:24]}").start()
            else:
                kq.wake.set()

    def _dispatch_loop(self, kq: "_KeyQueue") -> None:
        """One dispatcher per scheduling key while work exists: drains the
        queue onto leased workers in bursts (pipelined up to 4/worker).
        Lease acquisition runs on BACKGROUND threads (bounded by
        `max_pending_lease_requests_per_scheduling_key`) so slow lease
        grants / worker spawns never stall the push path. After draining,
        the dispatcher lingers briefly: a sync submit-get loop would
        otherwise pay a thread spawn per call."""
        idle_deadline = None
        while True:
            batch: List[Tuple[tuple, _Lease]] = []
            with self._lease_lock:
                depth = cfg.max_tasks_in_flight_per_worker
                # The per-worker pipeline hides push RTT for short tasks —
                # it is NOT parallel capacity. Duration-gated: once this
                # key's observed exec-time EWMA says tasks are SHORT,
                # pipeline to full depth (frame/wake amortization is the
                # single-core throughput ceiling); while tasks are long —
                # or unmeasured — hold one per lease, because a long task
                # queued behind another serializes (pushed tasks never
                # migrate) and a queued task goes to the FIRST lease that
                # frees, which no fixed assignment beats.
                short = (kq.avg_task_s is not None
                         and kq.avg_task_s < cfg.pipeline_short_task_s)
                cap = depth if short else 1
                locality_on = cfg.scheduler_locality_enabled
                # Live-lease census per node: the locality match defers a
                # task whose home node has a live lease here (bounded —
                # see _match_queued_task) instead of migrating its input.
                live_count: Dict[str, int] = {}
                if locality_on:
                    for l in kq.leases:
                        if not l.broken and l.node_id:
                            live_count[l.node_id] = \
                                live_count.get(l.node_id, 0) + 1
                made_progress = True
                while kq.queue and made_progress:
                    made_progress = False
                    free = sorted(
                        (l for l in kq.leases
                         if not l.broken and l.inflight < cap),
                        key=lambda l: l.inflight)
                    for lease in free:
                        if not kq.queue or lease.inflight >= cap:
                            continue
                        match = self._match_queued_task(
                            kq, lease, live_count, locality_on, cap)
                        if match is None:
                            continue
                        idx, pref = match
                        if idx:
                            kq.queue.rotate(-idx)
                            entry = kq.queue.popleft()
                            kq.queue.rotate(idx)
                        else:
                            entry = kq.queue.popleft()
                        if locality_on and pref is not None:
                            (_metrics.SCHEDULER_LOCALITY_HITS
                             if pref == lease.node_id
                             else _metrics.SCHEDULER_LOCALITY_MISSES).inc()
                        lease.inflight += 1
                        batch.append((entry, lease))
                        made_progress = True
                queue_len = len(kq.queue)
                sample = kq.queue[0][1] if kq.queue else None
            if batch:
                # One push frame per lease per round (the per-task frame +
                # ack + wakeup tax was the single-core throughput ceiling).
                by_lease: Dict[Any, list] = {}
                for (task_id_bytes, info), lease in batch:
                    by_lease.setdefault(id(lease), (lease, []))[1].append(
                        (task_id_bytes, info))
                for lease, items in by_lease.values():
                    self._push_group_to_lease(items, lease, kq)
            if sample is not None:
                self._maybe_request_leases(kq, sample, queue_len)
            if not batch:
                with self._lease_lock:
                    # Quiescent when nothing is queued and no HEALTHY lease
                    # has work in flight (a broken lease's stuck counters
                    # must not keep the dispatcher spinning — its tasks were
                    # already re-enqueued or failed by the conn-lost hook).
                    done = (not kq.queue
                            and not kq.pending_lease_requests
                            and all(l.inflight <= 0 or l.broken
                                    for l in kq.leases))
                    if done and idle_deadline is not None \
                            and time.monotonic() > idle_deadline:
                        kq.dispatcher_running = False
                        return
                if done and idle_deadline is None:
                    idle_deadline = (time.monotonic()
                                     + cfg.dispatcher_idle_linger_s)
                elif not done:
                    idle_deadline = None
                kq.wake.wait(0.25)
                kq.wake.clear()
            else:
                idle_deadline = None

    def _match_queued_task(self, kq: "_KeyQueue", lease: _Lease,
                           live_count: Dict[str, int], locality_on: bool,
                           cap: int) -> Optional[Tuple[int, Optional[str]]]:
        """(index into kq.queue, that task's preferred node) of the task
        to hand this lease, or None to leave the lease idle this round
        (it lingers briefly, then returns to its node). Preference
        order, scanned over a bounded window:

        1. a task whose inputs live on the lease's node (locality hit);
        2. a task with no known input locations;
        3. a task whose preferred node has no live lease under this key —
           it has to run SOMEWHERE, and a miss now beats waiting for a
           lease that may never come.

        A task whose preferred node DOES have live leases here is
        DEFERRED — its home lease frees within one task, or leaves
        kq.leases entirely, which lifts the deferral next round — but
        only up to 4 x (live leases x pipeline cap) tasks per node, so a
        skewed workload (every input on one hot node) still fans out
        instead of serializing behind one worker. Caller holds
        _lease_lock."""
        if not kq.queue:
            return None
        if not locality_on:
            return 0, None  # FIFO; hit/miss accounting is off anyway
        fallback = None
        deferred: Dict[str, int] = {}
        stale_cutoff = time.monotonic() - cfg.scheduler_locality_defer_max_s
        for i, (_tid, info) in enumerate(kq.queue):
            if i >= 64:
                break
            pref = self._preferred_node(info)
            if pref is not None and pref == lease.node_id:
                return i, pref
            if (pref is None or pref not in live_count
                    or info.enqueued_at < stale_cutoff):
                # No locality data, no live home lease, or deferred past
                # the age cap (home lease wedged on one long task): run
                # anywhere rather than wait longer.
                if fallback is None:
                    fallback = (i, pref)
                continue
            d = deferred.get(pref, 0)
            if d >= 4 * cap * live_count[pref]:
                if fallback is None:
                    fallback = (i, pref)
            else:
                deferred[pref] = d + 1
        return fallback

    def _maybe_request_leases(self, kq: "_KeyQueue", sample: _InflightTask,
                              queue_len: int) -> None:
        """Spawn background lease requesters if the queue outruns capacity."""
        with self._lease_lock:
            if time.monotonic() < kq.next_lease_attempt:
                return
            # Parallelism-first sizing: one WORKER per runnable task (the
            # per-worker pipeline is an RTT-hiding optimization, not
            # parallel capacity — sizing by pipeline depth left 4 sleeping
            # tasks sharing one worker). Tasks already pipelined beyond
            # one-per-lease count as backlog too. A saturated node
            # declines the extras and the declined-lease backoff bounds
            # the request rate.
            healthy = [l for l in kq.leases if not l.broken]
            idle = sum(1 for l in healthy if l.inflight == 0)
            excess = sum(max(0, l.inflight - 1) for l in healthy)
            shortfall = (queue_len + excess - idle
                         - kq.pending_lease_requests)
            want = min(max(0, shortfall),
                       cfg.max_pending_lease_requests_per_scheduling_key
                       - kq.pending_lease_requests)
            kq.pending_lease_requests += want
            if sample.strategy is None and kq.lease_fail_deadline is None:
                kq.lease_fail_deadline = (
                    time.monotonic() + cfg.lease_timeout_ms / 1000.0 * 6)
            # DISTINCT samples: the i-th new request hints the i-th queued
            # task's inputs, so granted leases land where the backlog's
            # data actually lives — `want` copies of the head task's hint
            # would pile every lease onto one holder node.
            qlist = list(kq.queue)
            samples = [qlist[i][1] if i < len(qlist) else sample
                       for i in range(want)]
        if len(samples) == 1:
            threading.Thread(target=self._lease_requester,
                             args=(kq, samples[0]), daemon=True).start()
        elif samples:
            # One batched pick_nodes frame covers the whole round; the
            # per-node lease requests still fan out on their own threads.
            threading.Thread(target=self._batch_lease_requests,
                             args=(kq, samples), daemon=True).start()

    def _locality_hint_for(self, sample: _InflightTask):
        if (cfg.scheduler_locality_enabled and sample.arg_ids
                and sample.strategy is None):
            return [o.binary() for o in
                    sample.arg_ids[:cfg.scheduler_locality_max_hint_objects]]
        return None

    def _batch_lease_requests(self, kq: "_KeyQueue",
                              samples: List[_InflightTask]) -> None:
        """Resolve a round of head picks in ONE pick_nodes frame, then run
        the standard per-sample lease requester with the pick pre-filled.
        A failed batch call degrades to per-sample picks (first_pick=None).
        Each requester decrements kq.pending_lease_requests exactly as in
        the unbatched path."""
        demand_key = None
        picks: List[Any] = [None] * len(samples)
        try:
            reqs = []
            for s in samples:
                demand_key = (self.worker_id.hex(),
                              tuple(sorted(s.resources.items())))
                reqs.append((s.resources, s.strategy, [], demand_key,
                             self._locality_hint_for(s)))
            with self._lease_lock:
                self.dispatch_stats["head_picks"] += 1
            got = self.head.retrying_call("pick_nodes", reqs, timeout=10)
            if isinstance(got, list) and len(got) == len(samples):
                picks = got
        except Exception:
            pass  # per-sample requesters fall back to their own picks
        for s, pick in zip(samples, picks):
            threading.Thread(target=self._lease_requester,
                             args=(kq, s, pick), daemon=True).start()

    def _lease_requester(self, kq: "_KeyQueue", sample: _InflightTask,
                         first_pick=None) -> None:
        from ray_tpu.exceptions import RuntimeEnvSetupError

        env_err = None
        lease = None
        via_block = False
        hint = self._locality_hint_for(sample)
        t_lease0 = time.time() if sample.trace_ctx is not None else 0.0
        try:
            # Steady state: admit against the key's lease block
            # node-direct; only a missing/dead block pays the
            # head-mediated pick below.
            lease = self._request_lease_via_block(kq, sample)
            via_block = lease is not None
            if lease is None:
                lease = self._request_new_lease(sample.resources,
                                                sample.strategy,
                                                sample.runtime_env, hint,
                                                first_pick=first_pick)
        except RuntimeEnvSetupError as e:
            env_err = e
        finally:
            with self._lease_lock:
                kq.pending_lease_requests -= 1
        if sample.trace_ctx is not None:
            # task.lease: pick_node + request_lease round-trip for the
            # sampled task's scheduling key (grants are shared by the
            # key's whole queue; the span is parented to the task whose
            # shape/locality hint drove the request).
            from ray_tpu.util import tracing as _tr

            _tr.emit_span(
                "task.lease", t_lease0, time.time(),
                parent=sample.trace_ctx,
                attrs={"task": sample.name,
                       "granted": lease is not None,
                       "node": (lease.node_id or "") if lease else "",
                       "worker": lease.worker_addr if lease else ""},
                ok=env_err is None)
        if env_err is not None:
            # The env can never materialize: every queued task of this key
            # fails NOW with the real install error (not a hang).
            self._fail_queued(kq, env_err)
            return
        if lease is not None:
            with self._lease_lock:
                if self._key_queues.get(kq.key) is not kq:
                    # The kq was reaped while this grant was in flight:
                    # nobody will ever dispatch on (or return) this lease —
                    # hand the worker straight back to its node.
                    orphaned = True
                elif not kq.queue and any(not l.broken for l in kq.leases):
                    # SURPLUS straggler: the backlog drained onto existing
                    # leases while this grant was queued at its node.
                    # Return it NOW instead of letting it linger — a chain
                    # of trailing grants each holding the node's resources
                    # for a linger period starves other submitters' (and
                    # other keys') locality-hinted requests at that node.
                    orphaned = True
                else:
                    orphaned = False
                    kq.leases.append(lease)
                    kq.lease_fail_deadline = None
                    kq.lease_backoff = 0.0
                    kq.next_lease_attempt = 0.0
            if orphaned:
                try:
                    self._pool.get(lease.node_addr).retrying_call(
                        "return_lease", lease.lease_id,
                        timeout=cfg.rpc_control_timeout_s)
                except Exception:
                    pass
                return
            if (not via_block and cfg.lease_block_enabled
                    and sample.strategy is None):
                # First head-mediated grant for this key succeeded:
                # negotiate the block in the background so the NEXT
                # dispatch round goes node-direct.
                with self._lease_lock:
                    start = kq.block is None and not kq.block_pending
                    if start:
                        kq.block_pending = True
                if start:
                    threading.Thread(target=self._negotiate_block,
                                     args=(kq, sample), daemon=True).start()
            kq.wake.set()
            return
        # Infeasible right now. If nothing is making progress for too long,
        # fail what's queued instead of spinning forever.
        with self._lease_lock:
            has_live = any(not l.broken for l in kq.leases)
            deadline = kq.lease_fail_deadline
        if (not has_live and deadline is not None
                and time.monotonic() > deadline):
            self._fail_queued(kq, TimeoutError(
                f"no feasible node for {sample.resources}"))
        else:
            with self._lease_lock:
                kq.lease_backoff = min(max(kq.lease_backoff * 2,
                               cfg.lease_backoff_base_s),
                           cfg.lease_backoff_max_s)
                kq.next_lease_attempt = time.monotonic() + kq.lease_backoff
            time.sleep(0.05)
            kq.wake.set()

    def _push_group_to_lease(self, items: List[Tuple[bytes, _InflightTask]],
                             lease: _Lease, kq: "_KeyQueue") -> None:
        survivors: List[Tuple[bytes, _InflightTask]] = []
        for task_id_bytes, info in items:
            # A cancel must survive re-dispatch (worker-crash re-enqueue)
            # and the queue-pop -> inflight-insert window: last check
            # before push.
            if TaskID(task_id_bytes) in self._cancelled:
                from ray_tpu.exceptions import TaskCancelledError

                err = TaskCancelledError(f"task {info.name} cancelled")
                for oid in info.return_ids:
                    self.memory_store.put(oid, err, is_exception=True)
                self._release_submitted_args(task_id_bytes)
                # Undo this dispatch round's inflight++ (handles linger too).
                self._lease_task_finished(info.sched_key, lease.worker_addr)
                continue
            info.worker_addr = lease.worker_addr
            with self._inflight_lock:
                self._inflight[task_id_bytes] = info
            survivors.append((task_id_bytes, info))
        if not survivors:
            return
        try:
            worker = self._pool.get(lease.worker_addr,
                                    on_close=self._on_worker_conn_lost)
            waiter = worker.call_async(
                "push_tasks",
                [(tid, info.spec_blob) for tid, info in survivors])
            for _tid, info in survivors:
                if info.trace_ctx is not None:
                    # task.dispatch: submit -> lease pairing -> push
                    # frame on the wire (one span per push ATTEMPT —
                    # emitted only after the frame actually sent, so a
                    # dead-worker failure below records nothing; a
                    # chaos re-dispatch legitimately emits another).
                    from ray_tpu.util import tracing as _tr

                    _tr.emit_span(
                        "task.dispatch", info.submit_t or time.time(),
                        time.time(), parent=info.trace_ctx,
                        attrs={"task": info.name,
                               "worker": lease.worker_addr,
                               "node": lease.node_id or ""})
            self._push_acks.append(
                [waiter, survivors, lease, kq, 0,
                 time.monotonic() + cfg.push_ack_timeout_s])
            self._push_ack_event.set()
        except BaseException:
            with self._inflight_lock:
                for tid, _ in survivors:
                    self._inflight.pop(tid, None)
            lease.broken = True
            with self._lease_lock:
                for tid, info in reversed(survivors):
                    kq.queue.appendleft((tid, info))

    def _push_ack_loop(self) -> None:
        """Collects push acks asynchronously (pipelining stays intact) and
        retries unacked pushes: an ack or request lost to chaos must not
        strand the task."""
        import collections

        while not self._shutdown_flag:
            try:
                # Every iteration — a continuously-busy dispatch queue must
                # not stall pin expiry (pins would accumulate unboundedly).
                self._sweep_transfer_pins()
                if not self._push_acks:
                    self._push_ack_event.wait(0.2)
                    self._push_ack_event.clear()
                    continue
                entry = self._push_acks.popleft()
                waiter, items, lease, kq, attempts, deadline = entry
                if not waiter._event.is_set():
                    if time.monotonic() < deadline:
                        self._push_acks.append(entry)
                        # Snapshot: dispatchers append concurrently, and
                        # iterating the live deque would raise and kill this
                        # thread (stranding every future unacked push).
                        if all(not e[0]._event.is_set()
                               for e in list(self._push_acks)):
                            time.sleep(cfg.push_ack_idle_poll_s)
                        continue
                    self._retry_push(entry)
                    continue
                try:
                    waiter.wait(0)
                except BaseException:
                    self._retry_push(entry)
            except BaseException:  # noqa: BLE001 — ack loop must survive
                time.sleep(0.05)

    def _retry_push(self, entry) -> None:
        waiter, items, lease, kq, attempts, deadline = entry
        with self._inflight_lock:
            live = [(tid, info) for tid, info in items
                    if tid in self._inflight]
        if not live:
            return  # all completed or already handled by conn-loss hook
        if attempts < 8 and not lease.broken:
            try:
                worker = self._pool.get(lease.worker_addr,
                                        on_close=self._on_worker_conn_lost)
                w2 = worker.call_async(
                    "push_tasks",
                    [(tid, info.spec_blob) for tid, info in live])
                self._push_acks.append(
                    [w2, live, lease, kq, attempts + 1,
                     time.monotonic() + 5.0])
                return
            except BaseException:
                pass
        # Give up on this worker: re-route through the queue.
        lease.broken = True
        for tid, info in live:
            with self._inflight_lock:
                if self._inflight.pop(tid, None) is None:
                    continue
            self._enqueue_task(tid, info)

    def _fail_queued(self, kq: "_KeyQueue", exc: Exception) -> None:
        err = capture_exception(exc)
        with self._lease_lock:
            tasks = list(kq.queue)
            kq.queue.clear()
        for tid, info in tasks:
            for oid in info.return_ids:
                self.memory_store.put(oid, err, is_exception=True)
            self._release_submitted_args(tid)

    def _request_new_lease(self, resources: Dict[str, float],
                           strategy,
                           runtime_env=None,
                           locality_hint: Optional[List[bytes]] = None,
                           first_pick=None,
                           ) -> Optional[_Lease]:
        """One head pick + node lease round trip; None if infeasible now.
        Both RPCs are retry-safe: pick_node is read-only, request_lease is
        idempotent via the per-attempt req_id (the node caches the grant).
        ``locality_hint`` ships the requesting task's input-object ids so
        the head can score candidates by locally-resident bytes.
        ``first_pick`` (from a batched pick_nodes) skips the first
        pick_node round trip; spillback hops re-pick individually."""
        exclude: List[str] = []
        # Demand identity for the head's unmet-demand ring: this
        # submitter + shape. Retries of one starved key stay one demand;
        # distinct submitters register separately.
        demand_key = (self.worker_id.hex(),
                      tuple(sorted(resources.items())))
        for hop in range(4):  # a few spillback hops per attempt
            if hop == 0 and first_pick is not None:
                picked = first_pick
            else:
                try:
                    with self._lease_lock:
                        self.dispatch_stats["head_picks"] += 1
                    picked = self.head.retrying_call(
                        "pick_node", resources, strategy, exclude,
                        demand_key, locality_hint, timeout=10)
                except (ConnectionLost, TimeoutError):
                    return None
            if picked is None:
                return None
            node_id, node_addr, _ = picked
            pg = pg_key_from_strategy(strategy)
            req_id = uuid.uuid4().hex
            # The short locality wait applies ONLY when the picked node
            # actually holds input bytes (a locality gamble): queue
            # briefly, declined -> exclude -> repick is the spillback. A
            # plain hybrid pick keeps the full default queue window —
            # shortening it for every data task would cost the whole
            # cluster 3x its queue patience under saturation.
            block_ms = None
            if locality_hint:
                with self._obj_loc_lock:
                    holders = {self._obj_locality[k][0]
                               for k in locality_hint
                               if k in self._obj_locality}
                if node_id in holders:
                    block_ms = cfg.scheduler_locality_wait_ms
            try:
                granted = self._pool.get(node_addr).retrying_call(
                    "request_lease", resources, True, pg, req_id,
                    self.owner_addr, runtime_env, block_ms,
                    timeout=cfg.lease_timeout_ms / 1000.0 + 5)
            except (ConnectionLost, TimeoutError):
                exclude.append(node_id)
                continue
            if granted is None:
                exclude.append(node_id)
                continue
            if isinstance(granted, dict) and "env_error" in granted:
                # Permanent per-node env failure: spilling back would just
                # reinstall-and-fail elsewhere forever.
                from ray_tpu.exceptions import RuntimeEnvSetupError

                raise RuntimeEnvSetupError(granted["env_error"])
            worker_addr, lease_id = granted
            return _Lease(worker_addr, lease_id, node_addr, node_id)
        return None

    # ------------------------------------------------------------ lease blocks

    def _request_lease_via_block(self, kq: "_KeyQueue",
                                 sample: _InflightTask) -> Optional[_Lease]:
        """Steady-state node-direct dispatch: admit against the key's
        head-granted lease block, skipping the pick_node round trip.
        None = no usable block — the caller falls back to the normal
        head-mediated path, so a revoked/expired/exhausted block degrades
        gracefully, never wrongly."""
        if not cfg.lease_block_enabled or sample.strategy is not None:
            return None
        renew = False
        with self._lease_lock:
            blk = kq.block
            if blk is None:
                return None
            if blk.remaining <= 0 or time.monotonic() > blk.expires_at:
                # Spent or expired: next head-mediated grant renegotiates.
                kq.block = None
                dead_id = blk.block_id
            else:
                dead_id = None
                blk.remaining -= 1
                if (blk.remaining
                        <= blk.size * cfg.lease_block_renew_lowwater
                        and not blk.renewing):
                    blk.renewing = True
                    renew = True
        if dead_id is not None:
            self._revoke_block_async(dead_id)
            return None
        if renew:
            # Ahead-of-exhaustion renewal OFF the dispatch path: dispatch
            # keeps draining the old budget while this round-trips.
            threading.Thread(target=self._negotiate_block,
                             args=(kq, sample, blk), daemon=True).start()
        pg = pg_key_from_strategy(sample.strategy)
        req_id = uuid.uuid4().hex
        try:
            granted = self._pool.get(blk.node_addr).retrying_call(
                "request_lease", sample.resources, True, pg, req_id,
                self.owner_addr, sample.runtime_env, None, blk.block_id,
                timeout=cfg.lease_timeout_ms / 1000.0 + 5)
        except (ConnectionLost, TimeoutError):
            # Node unreachable (died under the block): drop it and fall
            # back to a head pick — the head's death path revokes.
            with self._lease_lock:
                if kq.block is blk:
                    kq.block = None
                self.dispatch_stats["block_fallbacks"] += 1
            return None
        if isinstance(granted, dict):
            if "env_error" in granted:
                from ray_tpu.exceptions import RuntimeEnvSetupError

                raise RuntimeEnvSetupError(granted["env_error"])
            # {"block_revoked": True}: the node no longer honors the
            # block (head revoked it / TTL beat the owner's clock).
            with self._lease_lock:
                if kq.block is blk:
                    kq.block = None
                self.dispatch_stats["block_fallbacks"] += 1
            return None
        if granted is None:
            # Saturated node declined; the node credited the admission
            # unit back — mirror that locally and spill back to the head.
            with self._lease_lock:
                blk.remaining += 1
                self.dispatch_stats["block_fallbacks"] += 1
            return None
        with self._lease_lock:
            self.dispatch_stats["block_dispatches"] += 1
        worker_addr, lease_id = granted
        return _Lease(worker_addr, lease_id, blk.node_addr, blk.node_id)

    def _negotiate_block(self, kq: "_KeyQueue", sample: _InflightTask,
                         prev: Optional[_LeaseBlock] = None) -> None:
        """Background block grant (prev=None, after the first successful
        head-mediated lease for the key) or low-water renewal (prev =
        the draining block, placement stays sticky to its node). Never
        called on the dispatch path."""
        block_id = uuid.uuid4().hex
        got = None
        try:
            if prev is None:
                got = self.head.retrying_call(
                    "lease_block_grant", block_id, self.owner_addr,
                    sample.resources, sample.strategy,
                    self._locality_hint_for(sample), timeout=10)
            else:
                got = self.head.retrying_call(
                    "lease_block_renew", block_id, self.owner_addr,
                    sample.resources, prev.node_id, sample.strategy,
                    timeout=10)
        except Exception as e:
            logger.debug("lease block negotiation for %r failed: %r",
                         kq.key, e)
            got = None
        stale_id = None
        with self._lease_lock:
            if prev is None:
                kq.block_pending = False
            else:
                prev.renewing = False
            if got is not None:
                node_id, node_addr, size, ttl_ms = got
                if self._key_queues.get(kq.key) is not kq:
                    # The kq was reaped while the grant was in flight:
                    # nobody will ever dispatch against this block.
                    stale_id = block_id
                else:
                    stale = kq.block
                    kq.block = _LeaseBlock(block_id, node_id, node_addr,
                                           size, ttl_ms)
                    self.dispatch_stats["block_grants"] += 1
                    if stale is not None:
                        stale_id = stale.block_id
        if stale_id is not None:
            self._revoke_block_async(stale_id)

    def _revoke_block_async(self, block_id: str) -> None:
        """Best-effort head-routed release of a block this owner no
        longer uses (replaced, expired, key reaped) — keeps the node's
        admission budget and the census honest without waiting out the
        TTL backstop."""
        def _go():
            try:
                self.head.retrying_call("lease_block_revoke", block_id,
                                        timeout=5)
            except Exception:  # rtpu-lint: disable=swallowed-exception — best-effort: TTL expiry at head and node is the backstop
                pass

        threading.Thread(target=_go, daemon=True).start()

    def _on_worker_conn_lost(self, client: RpcClient) -> None:
        """A worker connection died: fail/retry its inflight tasks, mark its
        actors dead-pending-head-confirmation."""
        addr = client.address
        victims = []
        with self._inflight_lock:
            for tid, info in list(self._inflight.items()):
                if info.worker_addr == addr:
                    victims.append((tid, info))
                    del self._inflight[tid]
        with self._lease_lock:
            for kq in self._key_queues.values():
                for l in kq.leases:
                    if l.worker_addr == addr:
                        l.broken = True
        # System failure: normal tasks are resubmitted through the queue
        # (bounded by their per-task sys_retries counter).
        for tid, info in victims:
            if info.sched_key and info.sched_key[0] == "actor":
                continue  # actor calls handled by _handle_actor_conn_lost
            if info.streaming:
                # Replaying a partially-consumed stream would duplicate
                # delivered items: fail it (documented v1 semantics).
                self._fail_stream(tid, WorkerCrashedError(
                    f"worker at {addr} died mid-stream in {info.name}"))
                self._release_submitted_args(tid)
                continue
            if info.sys_retries is None:
                info.sys_retries = cfg.task_max_retries_default
            info.sys_retries -= 1
            if info.sys_retries < 0:
                err = capture_exception(WorkerCrashedError(
                    f"worker at {addr} died executing {info.name}"))
                for oid in info.return_ids:
                    self.memory_store.put(oid, err, is_exception=True)
                self._release_submitted_args(tid)
            else:
                self._enqueue_task(tid, info)
        with self._actors_lock:
            conns = [c for c in self._actors.values() if c.address == addr]
        for c in conns:
            threading.Thread(target=self._handle_actor_conn_lost, args=(c,),
                             daemon=True).start()

    # ------------------------------------------------------------------ leases

    def _lease_task_finished(self, sched_key: tuple, worker_addr: str,
                             exec_s: Optional[float] = None) -> None:
        with self._lease_lock:
            kq = self._key_queues.get(sched_key)
            if kq is None:
                return
            if exec_s is not None:
                kq.avg_task_s = (exec_s if kq.avg_task_s is None
                                 else 0.8 * kq.avg_task_s + 0.2 * exec_s)
            for l in kq.leases:
                if l.worker_addr == worker_addr and l.inflight > 0:
                    l.inflight -= 1
                    if l.inflight <= 0:
                        l.release_at = time.monotonic() + cfg.lease_linger_ms / 1000.0
                    break
            kq.wake.set()

    def _lease_reaper_loop(self) -> None:
        """Returns idle leases to their node managers after the linger.
        Also reports per-key queued backlog to the head every ~2s — the
        autoscaler's demand signal (reference: backlog_size rides lease
        requests, raylet forwards demand to the autoscaler)."""
        last_backlog_report = 0.0
        while not self._shutdown_flag:
            time.sleep(0.05)
            now = time.monotonic()
            if now - last_backlog_report >= 2.0:
                last_backlog_report = now
                try:
                    self._report_backlog()
                except Exception:
                    pass
            to_release = []
            doomed_blocks: List[str] = []
            with self._lease_lock:
                for key, kq in list(self._key_queues.items()):
                    keep = []
                    for l in kq.leases:
                        if l.broken or (l.inflight <= 0 and l.release_at
                                        and now >= l.release_at):
                            to_release.append(l)
                        else:
                            keep.append(l)
                    kq.leases[:] = keep
                    if (not kq.leases and not kq.queue
                            and not kq.dispatcher_running
                            and not kq.pending_lease_requests):
                        # pending_lease_requests guard: a slow worker-spawn
                        # grant landing on a popped (orphaned) kq would
                        # leak the lease's resources on its node forever.
                        self._key_queues.pop(key, None)
                        if kq.block is not None:
                            # The key went idle: hand the admission
                            # budget back instead of pinning it at the
                            # node until TTL.
                            doomed_blocks.append(kq.block.block_id)
                            kq.block = None
            for bid in doomed_blocks:
                self._revoke_block_async(bid)
            for l in to_release:
                # BROKEN leases are returned too: "broken" only means OUR
                # connection to the worker died — if the worker is actually
                # alive (transient conn loss), skipping the return would
                # leave its resources debited on the node forever.
                # pool_worker=False for broken ones: the worker may still
                # be executing the re-routed tasks' original copies, so the
                # node terminates it instead of pooling it (double-dispatch).
                try:
                    # Acked + retried: a lost return would leak the
                    # lease's resources on the node forever.
                    self._pool.get(l.node_addr).retrying_call(
                        "return_lease", l.lease_id, not l.broken,
                        timeout=cfg.rpc_control_timeout_s)
                except Exception:
                    pass

    def _report_backlog(self) -> None:
        entries = []
        with self._lease_lock:
            for kq in self._key_queues.values():
                # Demand = undispatched queue + tasks PIPELINED onto leases
                # beyond what they can run (1 task per lease executes; the
                # rest wait in the worker's slot queue).
                pipelined_waiting = sum(max(0, l.inflight - 1)
                                        for l in kq.leases if not l.broken)
                backlog = len(kq.queue) + pipelined_waiting
                if backlog > 0:
                    resources = dict(kq.key[1]) if len(kq.key) > 1 else {}
                    strat = None
                    if kq.queue:
                        info = kq.queue[0][1]
                        resources = dict(info.resources)
                        strat = info.strategy
                    # Label-constrained backlogs carry the constraint:
                    # the autoscaler must not satisfy them with capacity
                    # that can never match (see Autoscaler._labels_match).
                    if strat and strat.get("kind") == "node_label" \
                            and strat.get("hard"):
                        resources["_labels"] = tuple(
                            sorted(tuple(p) for p in strat["hard"]))
                    entries.append((resources, backlog))
        if entries or getattr(self, "_backlog_was_nonempty", False):
            self._backlog_was_nonempty = bool(entries)
            self.head.notify("report_backlog",
                             self.worker_id.hex(), entries)
        # Ship completed-task events to the head (cluster-wide list_tasks;
        # reference: TaskEventBuffer periodic flush to GcsTaskManager).
        if self._task_event_outbox:
            events = []
            while self._task_event_outbox and len(events) < 2000:
                try:
                    events.append(self._task_event_outbox.popleft())
                except IndexError:
                    break
            try:
                self.head.notify("report_task_events",
                                 self.owner_addr, events)
            except Exception:
                pass  # best-effort observability; next sweep retries new ones

    def cancel(self, ref: ObjectRef, force: bool = False,
               recursive: bool = True):
        """Cancel the task that produces `ref`: queued tasks are failed
        with TaskCancelledError immediately; dispatched ones get a
        cancel RPC to their worker — cooperative by default (skipped if
        not yet started; running user code is never preempted), while
        ``force=True`` kills the executing worker the way the reference's
        ray.cancel(force=True) does (core_worker Cancel path +
        force_kill): the conn-lost re-enqueue then converts the task to
        TaskCancelledError at re-dispatch."""
        from ray_tpu.exceptions import TaskCancelledError

        task_id = ref.id().task_id()
        tid_bytes = task_id.binary()
        # Mark FIRST (closes the race with a concurrent dispatch: the
        # push path re-checks _cancelled right before pushing), then
        # remove from queues. _mark_cancelled notifies the dispatched
        # worker exactly once (pending there -> skipped; running + force
        # -> worker exits and the re-dispatch converts the task to
        # TaskCancelledError).
        self._mark_cancelled(task_id, force=force)
        # Still queued? Remove + fail its returns.
        with self._lease_lock:
            for kq in self._key_queues.values():
                for entry in list(kq.queue):
                    if entry[0] == tid_bytes:
                        kq.queue.remove(entry)
                        err = TaskCancelledError(
                            f"task {entry[1].name} cancelled")
                        for oid in entry[1].return_ids:
                            self.memory_store.put(oid, err,
                                                  is_exception=True)
                        self._release_submitted_args(tid_bytes)
                        return

    # ------------------------------------------------------------------ actors

    def create_actor(self, cls, args, kwargs, *, name: Optional[str] = None,
                     namespace: str = "default", max_concurrency: int = 1,
                     max_restarts: int = 0, max_task_retries: int = 0,
                     resources=None, lifetime=None,
                     scheduling_strategy=None, get_if_exists: bool = False,
                     runtime_env=None, release_resources: bool = False,
                     concurrency_groups: Optional[Dict[str, int]] = None,
                     allow_out_of_order_execution: bool = False,
                     ) -> ActorID:
        from ray_tpu.core.runtime_env import validate_runtime_env

        runtime_env = validate_runtime_env(runtime_env)
        resources = _as_resource_dict(resources)
        # Only a DEFAULTED actor (no explicit resources) costs 1 CPU to
        # schedule (released at mark_actor_host). An explicit num_cpus=0
        # actor schedules with zero demand (reference: ray_option_utils —
        # actors default num_cpus=1 for scheduling, 0 for running, but an
        # explicit 0 is honored as 0).
        if release_resources:
            resources.setdefault("CPU", 1.0)
        actor_id = ActorID.of(self.job_id)
        spec_blob = SERIALIZER.encode({
            "cls": cls, "args": tuple(args), "kwargs": dict(kwargs),
            "max_concurrency": max_concurrency,
            "concurrency_groups": dict(concurrency_groups or {}),
            "owner_addr": self.owner_addr,
            "release_resources": release_resources,
            "out_of_order": bool(allow_out_of_order_execution),
        })
        # Constructor-arg refs must outlive this call: the head re-ships
        # spec_blob on every actor RESTART, long after the caller's local
        # refs are gone. Held until the actor is terminally dead.
        self._register_submitted_args(b"actor-args:" + actor_id.binary(),
                                      args, kwargs)
        try:
            status, existing = self.head.retrying_call(
                "register_actor", actor_id.binary(), name, namespace,
                spec_blob, max_restarts, resources, get_if_exists,
                _strategy_dict(scheduling_strategy), runtime_env,
                max_task_retries,
                timeout=cfg.actor_connect_timeout_s)
        except BaseException:
            self._release_submitted_args(b"actor-args:" + actor_id.binary())
            raise
        if status == "exists":
            self._release_submitted_args(b"actor-args:" + actor_id.binary())
            return ActorID(existing)
        self._actor_classes[actor_id] = cls
        return actor_id

    def _actor_conn(self, actor_id: ActorID) -> _ActorConn:
        with self._actors_lock:
            conn = self._actors.get(actor_id)
            if conn is None:
                reason = self._dead_actor_reasons.get(actor_id)
                if reason is not None:
                    # Retired actor: hand back an EPHEMERAL dead conn
                    # (not registered — registering would re-leak the
                    # entry retirement just reclaimed). Callers fail
                    # fast on conn.dead exactly as before.
                    conn = _ActorConn(actor_id)
                    conn.dead = True
                    conn.death_reason = reason
                    return conn
                conn = _ActorConn(actor_id)
                self._actors[actor_id] = conn
            return conn

    def _retire_actor_conn(self, conn: _ActorConn) -> None:
        """Drop a DEAD actor's conn from the registry. The _actors dict
        held one _ActorConn (pending map, sender state, address) per
        actor ever called, forever — the PR 8 lease-table shape on the
        driver side. The bounded memo preserves the death reason for
        late callers; beyond the cap the oldest retirement is forgotten
        and a late call re-resolves against the head (which also
        answers DEAD)."""
        with self._actors_lock:
            self._actors.pop(conn.actor_id, None)
            memo = self._dead_actor_reasons
            memo[conn.actor_id] = conn.death_reason or "actor died"
            memo.move_to_end(conn.actor_id)
            while len(memo) > 4096:
                memo.popitem(last=False)

    def _resolve_actor_address(self, conn: _ActorConn,
                               timeout: Optional[float] = None
                               ) -> Optional[str]:
        """Blocks until the head reports the actor ALIVE (the restart-
        pending QUEUE window: callers park here while a max_restarts
        re-creation is in flight, bounded by
        actor_restart_queue_timeout_s)."""
        if conn.address is not None:
            return conn.address
        if timeout is None:
            timeout = cfg.actor_restart_queue_timeout_s
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            # Short long-poll rounds (read-only, retry-safe under chaos);
            # round length clipped to the remaining window so a short
            # restart-pending timeout is honored at ~its own granularity.
            poll = max(0.5, min(10.0, deadline - time.monotonic()))
            try:
                state, payload = self.head.call(
                    "wait_actor_address", conn.actor_id.binary(), poll,
                    timeout=poll + 5)
            except ConnectionLost:
                time.sleep(0.2)  # dead socket fails instantly: no hot spin
                try:
                    self.head.reconnect()
                except OSError:
                    pass
                continue
            except TimeoutError:
                continue
            if state == "ALIVE":
                conn.address = payload
                return payload
            if state == "DEAD":
                conn.dead = True
                conn.death_reason = payload
                # Retire here too: an actor first discovered dead at
                # resolution (worker died before any conn existed, or a
                # memo-evicted late call re-resolving) would otherwise
                # park its conn in _actors forever — the exact leak
                # retirement exists to close. The conn object stays
                # valid for the caller failing its pending entries.
                self._retire_actor_conn(conn)
                return None
            # PENDING: keep waiting until our own deadline.
        return None

    def submit_actor_task(self, actor_id: ActorID, method_name: str, args,
                          kwargs, num_returns: int = 1) -> List[ObjectRef]:
        task_id = TaskID.for_task(actor_id)
        return_ids = [ObjectID.for_task_return(task_id, i)
                      for i in range(num_returns)]
        for oid in return_ids:
            self.refcount.add_owned_object(oid)
        refs = [ObjectRef(oid, self.owner_addr) for oid in return_ids]
        conn = self._actor_conn(actor_id)

        if method_name == "__ray_terminate__":
            self.kill_actor(actor_id, no_restart=True)
            for oid in return_ids:
                self.memory_store.put(oid, None)
            return refs

        # Positional tuple spec (decoded into a dict worker-side): control
        # frames are encode-bound at high call rates, and a 7-tuple pickles
        # materially cheaper/smaller than a 7-key dict.
        blob = SERIALIZER.encode((
            task_id.binary(), actor_id.binary(), method_name,
            tuple(args), dict(kwargs),
            [o.binary() for o in return_ids], self.owner_addr))
        self._register_submitted_args(task_id.binary(), args, kwargs)
        from ray_tpu.util import metrics

        metrics.ACTOR_CALLS.inc()
        # Seq assignment + enqueue are synchronous with the caller: two
        # sequential .remote() calls CANNOT be reordered (the sender thread
        # drains in seq order).
        with conn.lock:
            seq = conn.next_seq
            conn.next_seq += 1
            conn.pending[seq] = (task_id.binary(), blob, return_ids)
            conn.outbound.append((seq, task_id.binary(), blob, return_ids))
            start_sender = not conn.sender_running
            if start_sender:
                conn.sender_running = True
        if start_sender:
            threading.Thread(target=self._actor_sender_loop, args=(conn,),
                             daemon=True,
                             name=f"actor-send-{actor_id.hex()[:8]}").start()
        return refs

    def _actor_sender_loop(self, conn: _ActorConn) -> None:
        """Single per-actor sender: drains queued calls in seq order as
        BATCHES — one `push_actor_batch` frame per burst (pipelined, acked)
        over one pooled connection — then services unacked batches: a batch
        ack lost to chaos is retried (the worker dedups and re-orders via
        the min_pending horizon). Any failure fails the affected calls and
        moves on — the sender thread itself must never die with
        sender_running stuck True (that would wedge the actor)."""
        while True:
            batch: List[tuple] = []
            with conn.lock:
                if not conn.outbound and not conn.unacked:
                    conn.sender_running = False
                    return
                # A conn-loss handler may have failed a seq while it was
                # still queued (actor died/restarted before we sent it):
                # failed-then-executed would duplicate side effects on the
                # new incarnation, so never send a seq no longer pending.
                while conn.outbound and len(batch) < cfg.actor_send_batch_max:
                    item = conn.outbound.popleft()
                    if item[0] in conn.pending:
                        batch.append(item)
            try:
                if batch:
                    self._send_actor_batch(conn, batch, 0)
                    # Opportunistically reap acked heads to bound unacked.
                    # Pops ride conn.lock (and never span the settle,
                    # which may resend = block): a replay handler
                    # snapshots this deque from another thread, and a
                    # bare mutation mid-snapshot raises RuntimeError in
                    # exactly the recovery path that must not die.
                    while True:
                        with conn.lock:
                            if not (conn.unacked
                                    and conn.unacked[0][1]._event.is_set()):
                                break
                            entry = conn.unacked.popleft()
                        self._settle_actor_ack(conn, entry)
                    continue
                entry = conn.unacked[0]
                if entry[1]._event.wait(0.05):
                    with conn.lock:
                        conn.unacked.popleft()
                    self._settle_actor_ack(conn, entry)
                elif time.monotonic() > entry[3]:
                    with conn.lock:
                        conn.unacked.popleft()
                    self._resend_actor_batch(conn, entry)
            except BaseException:  # noqa: BLE001 — keep the sender alive
                for it in batch:
                    self._fail_actor_call(conn, it[0])

    def _send_actor_batch(self, conn: _ActorConn, items: List[tuple],
                          tries: int) -> None:
        """items: [(seq, task_id_bytes, blob, return_ids)]. One RPC frame
        carries the whole burst; the unacked entry tracks the batch."""
        if conn.dead:
            for it in items:
                self._fail_actor_call(conn, it[0])
            return
        try:
            addr = self._resolve_actor_address(conn)
        except Exception:
            addr = None
        if addr is None:
            reason = (None if conn.dead else
                      "actor restart still pending after "
                      f"{cfg.actor_restart_queue_timeout_s:.0f}s")
            for it in items:
                self._fail_actor_call(conn, it[0], reason=reason)
            return
        with conn.lock:
            live = [it for it in items if it[0] in conn.pending]
        if not live:
            return
        with self._inflight_lock:
            for seq, task_id_bytes, blob, rids in live:
                self._inflight[task_id_bytes] = _InflightTask(
                    blob, rids, addr, 0, ("actor", conn.actor_id),
                    {}, None, "actor_task")
        try:
            waiter = self._pool.get(
                addr, on_close=self._on_worker_conn_lost).call_async(
                    "push_actor_batch",
                    [(it[0], it[2]) for it in live], conn.min_pending())
            # 2s resend deadline: worker-side dedup makes resends free, and
            # a chaos-dropped frame must not stall the whole batch 10s.
            with conn.lock:
                conn.unacked.append([live, waiter, tries,
                                     time.monotonic() + 2.0])
        except (ConnectionLost, OSError):
            self._handle_actor_conn_lost(conn)

    def _settle_actor_ack(self, conn: _ActorConn, entry) -> None:
        try:
            entry[1].wait(0)
        except BaseException:
            self._resend_actor_batch(conn, entry)

    def _resend_actor_batch(self, conn: _ActorConn, entry) -> None:
        items, _, tries, _ = entry
        with conn.lock:
            live = [it for it in items if it[0] in conn.pending]
        if not live:
            return
        if tries >= 10:
            for it in live:
                self._fail_actor_call(conn, it[0])
            return
        self._send_actor_batch(conn, live, tries + 1)

    def _fail_actor_call(self, conn: _ActorConn, seq: int,
                         reason: Optional[str] = None) -> None:
        with conn.lock:
            entry = conn.pending.pop(seq, None)
            conn.replays.pop(seq, None)
        if entry is None:
            return
        task_id_bytes, _, return_ids = entry
        with self._inflight_lock:
            self._inflight.pop(task_id_bytes, None)
        self._release_submitted_args(task_id_bytes)
        err = ActorDiedError(conn.actor_id,
                             reason or conn.death_reason or "actor died")
        for oid in return_ids:
            self.memory_store.put(oid, err, is_exception=True)

    def rpc_actor_call_done(self, conn_ctx, actor_id_bytes: bytes, seq: int,
                            task_id_bytes: bytes,
                            results: List[Tuple[bytes, str, Any]],
                            span: Optional[Tuple[float, float, str]] = None):
        aconn = self._actor_conn(ActorID(actor_id_bytes))
        with aconn.lock:
            aconn.pending.pop(seq, None)
            aconn.replays.pop(seq, None)
        return self.rpc_task_done(conn_ctx, task_id_bytes, results, span)

    def _handle_actor_conn_lost(self, conn: _ActorConn) -> None:
        """Connection to the actor's worker died: consult the head.

        Two policies, switched by the actor's ``max_restarts`` (the head
        reports it as ``at_least_once``):

        - max_restarts == 0 (default): in-flight calls FAIL — a call
          that may already have executed is never replayed (reference
          semantics, max_task_retries=0).
        - max_restarts > 0: the actor is declared restartable, so its
          callers opted into at-least-once calls — every still-pending
          seq REPLAYS against the restarted incarnation, in seq order,
          through the same sender machinery. The worker-side
          (caller, seq) horizon + reply memo turn the at-least-once
          wire into exactly-once execution per incarnation; only calls
          whose execution-and-results were lost WITH the old
          incarnation run again.

        Restart-pending windows QUEUE, not fail: while the head reports
        PENDING/RESTARTING this handler keeps waiting (and new submits
        keep queueing in outbound) until actor_restart_queue_timeout_s.
        """
        with conn.lock:
            if conn.loss_handling:
                return  # another thread owns this conn's recovery
            conn.loss_handling = True
            stale_addr = conn.address
            conn.address = None
        try:
            self._handle_actor_conn_lost_inner(conn, stale_addr)
        finally:
            with conn.lock:
                conn.loss_handling = False

    def _handle_actor_conn_lost_inner(self, conn: _ActorConn,
                                      stale_addr: Optional[str]) -> None:
        # Same window as the sibling loss path (_send_actor_batch ->
        # _resolve_actor_address): both must honor the configured
        # restart-pending queueing timeout EXACTLY, or the two paths
        # fail identical calls at different times with a reason naming
        # a wait that never happened.
        deadline = time.monotonic() + cfg.actor_restart_queue_timeout_s
        while time.monotonic() < deadline:
            try:
                info = self.head.retrying_call("get_actor_info",
                                               conn.actor_id.binary(), timeout=10)
            except Exception as e:
                # Head unreachable (mid-restart/upgrade): keep polling
                # until our own deadline — the restart-pending window.
                logger.debug("actor info poll failed (head down?): %r", e)
                time.sleep(0.5)
                continue
            if info is None:
                conn.dead = True
                conn.death_reason = "unknown actor"
                self._release_submitted_args(
                    b"actor-args:" + conn.actor_id.binary())
                break
            if info["state"] == "ALIVE" and info["address"]:
                if info["address"] == stale_addr:
                    # Head hasn't noticed the death yet; keep polling.
                    time.sleep(0.2)
                    continue
                conn.address = info["address"]
                if info.get("at_least_once"):
                    conn.incarnation = int(info.get("restarts", 0))
                    self._replay_actor_calls(
                        conn, int(info.get("max_task_retries", 0)))
                    return
                conn.death_reason = ("actor restarted; in-flight calls "
                                     "failed (max_task_retries=0)")
                with conn.lock:
                    seqs = list(conn.pending)
                for seq in seqs:
                    self._fail_actor_call(conn, seq)
                return
            if info["state"] == "DEAD":
                conn.dead = True
                conn.death_reason = info["reason"] or "actor died"
                self._release_submitted_args(
                    b"actor-args:" + conn.actor_id.binary())
                break
            time.sleep(0.2)  # PENDING/RESTARTING: wait (queued callers)
        with conn.lock:
            seqs = list(conn.pending)
        for seq in seqs:
            self._fail_actor_call(
                conn, seq,
                reason=None if conn.dead else
                "actor restart still pending after "
                f"{cfg.actor_restart_queue_timeout_s:.0f}s")
        if conn.dead:
            self._retire_actor_conn(conn)

    def _replay_actor_calls(self, conn: _ActorConn,
                            max_task_retries: int = -1) -> None:
        """Re-enqueue every still-pending call for the actor's new
        incarnation. Seqs already queued in outbound (new submits that
        parked during the restart) merge in — the rebuilt outbound is
        sorted so the wire carries one ascending stream. Seqs riding an
        unacked batch are NOT re-enqueued here: their resend deadline
        re-drives them through _send_actor_batch against the new
        address, and a duplicate send is dedup'd by the worker's
        (caller, seq) horizon anyway. Each seq replays at most
        max_task_retries times across incarnations (<0 = unlimited) —
        the poison-call bound."""
        exhausted: List[int] = []
        with conn.lock:
            # Snapshot under the lock the sender's unacked mutations
            # also hold: a bare deque iteration racing an append/pop
            # raises RuntimeError in exactly this recovery path.
            inflight: set = set()
            for entry in conn.unacked:
                for it in entry[0]:
                    inflight.add(it[0])
            items = {it[0]: it for it in conn.outbound}
            for seq, (tid, blob, rids) in conn.pending.items():
                if seq in items or seq in inflight:
                    continue
                n = conn.replays.get(seq, 0) + 1
                if max_task_retries >= 0 and n > max_task_retries:
                    exhausted.append(seq)
                    continue
                conn.replays[seq] = n
                items[seq] = (seq, tid, blob, rids)
            conn.outbound.clear()
            for seq in sorted(items):
                conn.outbound.append(items[seq])
            replayed = len(items)
            start = (not conn.sender_running
                     and bool(conn.outbound or conn.unacked))
            if start:
                conn.sender_running = True
        for seq in exhausted:
            self._fail_actor_call(
                conn, seq,
                reason=f"call replayed {max_task_retries}x across actor "
                       "restarts without completing (max_task_retries)")
        if replayed or inflight:
            from ray_tpu.util import flight_recorder as _fl

            _fl.record("actor_replay", actor=conn.actor_id.hex()[:12],
                       queued=replayed, inflight=len(inflight),
                       incarnation=conn.incarnation)
        if start:
            threading.Thread(
                target=self._actor_sender_loop, args=(conn,), daemon=True,
                name=f"actor-send-{conn.actor_id.hex()[:8]}").start()

    def get_actor(self, name: str, namespace: str = "default") -> ActorID:
        found = self.head.retrying_call("get_named_actor", name, namespace, timeout=10)
        if found is None:
            raise ValueError(f"no actor named '{name}' in namespace "
                             f"'{namespace}'")
        aid, spec_blob = found
        actor_id = ActorID(aid)
        if actor_id not in self._actor_classes:
            self._actor_classes[actor_id] = SERIALIZER.decode(spec_blob)["cls"]
        return actor_id

    def actor_class_of(self, actor_id: ActorID):
        return self._actor_classes.get(actor_id)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        try:
            self.head.retrying_call("kill_actor", actor_id.binary(), no_restart,
                                     timeout=10)
        except Exception:
            pass
        conn = self._actor_conn(actor_id)
        conn.dead = True
        conn.death_reason = "killed via ray_tpu.kill"
        conn.address = None
        self._release_submitted_args(b"actor-args:" + actor_id.binary())
        with conn.lock:
            seqs = list(conn.pending)
        for seq in seqs:
            self._fail_actor_call(conn, seq)
        self._retire_actor_conn(conn)

    def list_actors(self):
        return self.head.retrying_call("list_actors", timeout=10)

    # ------------------------------------------------------------------ pgs

    def create_placement_group(self, spec: PlacementGroupSpec) -> None:
        ok = self.head.retrying_call(
            "create_pg", spec.pg_id.binary(),
            [b.resources.to_dict() for b in spec.bundles],
            spec.strategy, spec.name, timeout=30)
        if not ok:
            raise RuntimeError(
                f"placement group creation failed: {spec.strategy}")
        self._pgs[spec.pg_id] = spec

    def placement_group_ready(self, pg_id: PlacementGroupID,
                              timeout=None) -> bool:
        return bool(self.head.retrying_call("pg_ready", pg_id.binary(), timeout=10))

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        self.head.retrying_call("remove_pg", pg_id.binary(), timeout=10)
        self._pgs.pop(pg_id, None)

    def placement_group_table(self):
        return self.head.retrying_call("pg_table", timeout=10)

    # ------------------------------------------------------------------ misc

    def nodes(self):
        return self.head.retrying_call("list_nodes", timeout=10)

    def cluster_resources(self) -> Dict[str, float]:
        total, _ = self.head.retrying_call("cluster_resources", timeout=10)
        return total

    def available_resources(self) -> Dict[str, float]:
        _, avail = self.head.retrying_call("cluster_resources", timeout=10)
        return avail

    def shutdown(self) -> None:
        if self._shutdown_flag:
            return
        self._shutdown_flag = True
        try:
            # Last-gasp directory sync: queued adds/removes still flush so
            # the head's view doesn't miss this owner's final objects.
            self._flush_object_notifies()
        except Exception:
            pass
        # Hand lease blocks back: a dead owner's blocks would otherwise
        # pin admission budget at their nodes until the TTL backstop.
        with self._lease_lock:
            final_blocks = [kq.block.block_id
                            for kq in self._key_queues.values()
                            if kq.block is not None]
            for kq in self._key_queues.values():
                kq.block = None
        revoke_deadline = time.monotonic() + 5.0
        for bid in final_blocks:
            left = revoke_deadline - time.monotonic()
            if left <= 0:
                break  # TTL expiry reclaims the rest; don't stall exit
            try:
                self.head.retrying_call("lease_block_revoke", bid,
                                        timeout=min(2.0, left))
            except Exception:  # rtpu-lint: disable=swallowed-exception — best-effort: TTL expiry is the backstop at head and node
                pass
        self._server.stop()
        self._pool.close_all()
        # _shutdown_flag is set above: the reaper's next 50ms lap exits.
        self._lease_reaper.join(timeout=2.0)
        for c in (self.head, self.node):
            try:
                c.close()
            except Exception:
                pass
        try:
            self.store.close()
        except Exception:
            pass
        # RTPU_DEBUG_RES balance assertion: this core's tracked threads
        # must have exited by now (the reaper was joined above). The
        # check reports (RTPU_DEBUG_RES: line + violations registry) and
        # never blocks teardown; witness off = one env read.
        _resdbg.check_balanced("cluster_core.shutdown", kinds=("thread",),
                               owner=self)
        runtime_context.set_runtime(None)


def _scan_object_refs(obj, out: List[ObjectID], depth: int = 0) -> None:
    """Collect ObjectIDs of every ObjectRef reachable through plain
    containers in task args (bounded depth: refs buried deeper inside
    arbitrary user objects are covered by borrower registration instead)."""
    if depth > 6:
        return
    if isinstance(obj, ObjectRef):
        out.append(obj.id())
        return
    if isinstance(obj, (list, tuple, set, frozenset)):
        for v in obj:
            _scan_object_refs(v, out, depth + 1)
    elif isinstance(obj, dict):
        for k, v in obj.items():
            _scan_object_refs(k, out, depth + 1)
            _scan_object_refs(v, out, depth + 1)


def _as_resource_dict(resources) -> Dict[str, float]:
    if resources is None:
        return {}
    if hasattr(resources, "to_dict"):
        return dict(resources.to_dict())
    return dict(resources)


def _strategy_dict(strategy) -> Optional[Dict[str, Any]]:
    """Normalize a scheduling strategy object/string to the wire dict."""
    if strategy is None:
        return None
    if isinstance(strategy, dict):
        return strategy
    if isinstance(strategy, str):
        if strategy == "SPREAD":
            return {"kind": "spread"}
        if strategy == "DEFAULT":
            return None
        raise ValueError(f"unknown scheduling strategy {strategy!r}")
    kind = type(strategy).__name__
    if kind == "PlacementGroupSchedulingStrategy":
        return {"kind": "placement_group",
                "pg_id": strategy.placement_group.id.binary(),
                "bundle_index":
                    getattr(strategy, "placement_group_bundle_index", -1)}
    if kind == "NodeAffinitySchedulingStrategy":
        return {"kind": "node_affinity", "node_id": strategy.node_id,
                "soft": getattr(strategy, "soft", False)}
    if kind == "NodeLabelSchedulingStrategy":
        return {"kind": "node_label",
                "hard": tuple(dict(strategy.hard).items()
                              if not isinstance(strategy.hard, tuple)
                              else strategy.hard),
                "soft": tuple(dict(strategy.soft).items()
                              if not isinstance(strategy.soft, tuple)
                              else strategy.soft)}
    if kind == "SliceAffinitySchedulingStrategy":
        # TPU-native sugar: hard label match on the slice name (the GCE
        # provider labels every slice host with tpu-slice=<name>), plus
        # the per-host pin when host_index is given (tpu-worker-id label,
        # core/accelerators.py slice_node_resources) — SPMD gangs place
        # one process per specific slice host.
        hard = [("tpu-slice", strategy.slice_name)]
        if strategy.host_index is not None:
            hard.append(("tpu-worker-id", str(strategy.host_index)))
        return {"kind": "node_label", "hard": tuple(hard), "soft": ()}
    raise ValueError(f"unknown scheduling strategy {strategy!r}")


_spread_rr_counter = itertools.count()


def _sched_key(func, resources: Dict[str, float], strategy) -> tuple:
    fid = getattr(func, "__qualname__", repr(func))
    strat_part = (tuple(sorted((strategy or {}).items(),
                               key=lambda kv: str(kv[0])))
                  if strategy else None)
    if strategy and strategy.get("kind") == "spread":
        # Spread tasks must NOT share worker leases (lease reuse would pack
        # them); rotate across a few keys so each requests its own lease.
        strat_part = strat_part + (("rr", next(_spread_rr_counter) % 8),)
    return (fid, tuple(sorted(resources.items())), strat_part)
