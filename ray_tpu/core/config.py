"""Runtime configuration flag table.

Equivalent of the reference's RAY_CONFIG macro table (reference:
src/ray/common/ray_config_def.h — 221 entries, env-overridable), redesigned as
a typed Python registry: every flag is declared once with a type and a default,
is overridable via ``RTPU_<NAME>`` environment variables and via the
``_system_config`` dict handed to ``ray_tpu.init``, and is serialized to
workers at connect time (mirroring GetSystemConfig in node_manager.proto:438).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Dict

_ENV_PREFIX = "RTPU_"


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


class _Flag:
    __slots__ = ("name", "type", "default", "doc")

    def __init__(self, name: str, typ: type, default: Any, doc: str):
        self.name = name
        self.type = typ
        self.default = default
        self.doc = doc

    def parse(self, raw: str) -> Any:
        if self.type is bool:
            return _parse_bool(raw)
        return self.type(raw)


class Config:
    """Process-wide flag registry. Thread-safe writes; lock-free reads."""

    def __init__(self):
        self._flags: Dict[str, _Flag] = {}
        self._values: Dict[str, Any] = {}
        self._lock = threading.Lock()
        # env overrides we exported: env_key -> value seen before the
        # export (None if the key was absent) so shutdown can restore it.
        self._exported_env: dict = {}

    def define(self, name: str, typ: type, default: Any, doc: str = "") -> None:
        flag = _Flag(name, typ, default, doc)
        self._flags[name] = flag
        env = os.environ.get(_ENV_PREFIX + name.upper())
        self._values[name] = flag.parse(env) if env is not None else default

    def __getattr__(self, name: str) -> Any:
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(f"unknown config flag: {name}") from None

    def get(self, name: str) -> Any:
        return self._values[name]

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            if name not in self._flags:
                raise KeyError(f"unknown config flag: {name}")
            self._values[name] = value

    def apply_system_config(self, overrides: Dict[str, Any]) -> None:
        """Driver-side _system_config: applied locally AND exported as
        RTPU_* env vars so every process this one spawns (head, nodes,
        workers) inherits the overrides — the docstring's "serialized to
        workers" contract; without the export only the driver saw them."""
        for k, v in overrides.items():
            self.set(k, v)
            if v is True or v is False:
                raw = "1" if v else "0"
            else:
                raw = str(v)
            env_key = _ENV_PREFIX + k.upper()
            if env_key not in self._exported_env:
                self._exported_env[env_key] = os.environ.get(env_key)
            os.environ[env_key] = raw

    def clear_exported_env(self) -> None:
        """Drop env exports this process's apply_system_config created
        (called by shutdown so a later init — or unrelated subprocesses —
        start from defaults, not a previous cluster's overrides). Values
        the USER set in the environment before init are restored."""
        for env_key, prior in self._exported_env.items():
            if prior is None:
                os.environ.pop(env_key, None)
            else:
                os.environ[env_key] = prior
        self._exported_env.clear()

    def snapshot(self) -> Dict[str, Any]:
        """Serializable view shipped to spawned workers."""
        return dict(self._values)

    def restore(self, snap: Dict[str, Any]) -> None:
        with self._lock:
            self._values.update(snap)

    def dump_json(self) -> str:
        return json.dumps(self._values, default=str, sort_keys=True)


GLOBAL_CONFIG = Config()
_d = GLOBAL_CONFIG.define

# --- core object plane ---
_d("object_store_memory_bytes", int, 2 * 1024**3, "per-node shm store size")
_d("object_store_inline_max_bytes", int, 100 * 1024,
   "results <= this are inlined in RPC replies / memory store instead of shm")
_d("object_spilling_enabled", bool, True, "spill shm objects to disk under pressure")
_d("object_spilling_dir", str, "/tmp/ray_tpu_spill", "spill directory")
_d("object_transfer_chunk_bytes", int, 4 * 1024**2, "node-to-node object push chunk")
_d("object_store_eviction_fraction", float, 0.2, "fraction evicted per LRU pass")
_d("object_store_prefault", bool, False,
   "madvise(POPULATE_WRITE) the store at creation from a background thread "
   "(costs ~1 cpu-s/GB once; enable on dedicated hosts for full put speed)")

# --- scheduling ---
_d("lease_timeout_ms", int, 10_000, "worker lease validity")
_d("scheduler_locality_enabled", bool, True,
   "score candidate nodes by locally-resident input bytes when picking a "
   "node for a task (reference: the raylet's locality-aware lease policy); "
   "disable to fall back to pure pack-then-spread")
_d("scheduler_locality_spill_threshold", float, 0.8,
   "holder-node utilization above which locality yields to the hybrid "
   "policy — the spillback guard: a loaded holder must not starve tasks "
   "that could run elsewhere")
_d("scheduler_locality_max_hint_objects", int, 16,
   "max input-object ids shipped with a pick_node lease request as the "
   "locality hint (largest inputs dominate; a long tail adds only bytes)")
_d("scheduler_locality_wait_ms", int, 1000,
   "how long a locality-hinted lease request queues at a momentarily-full "
   "holder node before declining (the requester then excludes it and "
   "spills back) — waiting briefly beats migrating the input bytes")
_d("scheduler_locality_defer_max_s", float, 3.0,
   "max age a queued task is deferred waiting for a lease on its inputs' "
   "holder node; past it the task dispatches to any free lease (a holder "
   "wedged on one long task must not indefinitely delay its queue)")
_d("object_notify_flush_ms", int, 5,
   "flush window for batched object_added/object_removed notifies to the "
   "head: puts coalesce a burst's directory updates into one object_batch "
   "frame (0 flushes immediately, still batched per sweep)")
_d("object_locality_cache_max", int, 65_536,
   "owner-side oid -> (node, size) locality cache entries (populated from "
   "task completions and local puts; consulted at dispatch)")
_d("lease_queue_block_ms", int, 3_000,
   "how long a saturated node queues a lease request before declining "
   "(spillback); reference: tasks queue at the raylet")
_d("scheduler_spread_threshold", float, 0.5,
   "hybrid policy: pack onto a node until utilization crosses this, then spread")
_d("max_pending_lease_requests_per_scheduling_key", int, 10, "lease pipelining cap")
_d("lease_linger_ms", int, 100,
   "how long an idle lease is kept before returning the worker to its "
   "node (covers sync submit-get loops); long lingers serialize worker "
   "handoff between competing submitters")
_d("lease_block_enabled", bool, True,
   "owner-routed lease blocks: after the first head-mediated pick for a "
   "scheduling key the head grants the owner a pre-negotiated block "
   "(node, count, TTL) and repeat dispatch goes node-direct, skipping "
   "the head in steady state; off = every lease pays a pick_node "
   "round trip (the PR 14 path — bench.py --scale A/Bs this)")
_d("lease_block_size", int, 16,
   "lease admissions pre-negotiated per block grant: each unit lets one "
   "request_lease skip the head; bigger blocks raise the steady-state "
   "head bypass rate (1 - 1/size) but pin placement to one node longer")
_d("lease_block_ttl_ms", int, 10_000,
   "lease-block validity: the node refuses admissions against an "
   "expired block (the owner falls back to a head pick) and the expiry "
   "sweep releases it, so a dead owner's block can never pin admission "
   "state forever")
_d("lease_block_renew_lowwater", float, 0.25,
   "remaining/size fraction at which the owner renews its block in the "
   "background (ahead of exhaustion, so the dispatch path never stalls "
   "on the renew round trip)")
_d("head_index_min_nodes", int, 64,
   "node count at which the head switches its pick scoring and lease "
   "census onto the O(touched) indexed paths (util buckets, implicated-"
   "node prefilter); below it the exact full scans run — small clusters "
   "and unit tests keep byte-identical behavior")
_d("object_dir_shards", int, 16,
   "lock shards of the head object directory (oid-hash partitioned): "
   "directory churn from object_batch frames contends on shard locks, "
   "never on the scheduler-critical head lock")
_d("object_dir_journal_max", int, 8192,
   "per-node directory mutation journal entries kept for cursor-delta "
   "republish; a head further behind than the journal floor gets a "
   "full snapshot instead of a replay")
_d("worker_zygote_enabled", bool, True,
   "default-env CPU workers fork from a pre-imported zygote process "
   "(linux; ~10ms/worker instead of ~0.4s interpreter+import CPU)")
_d("pipeline_short_task_s", float, 0.05,
   "exec-time EWMA below this pipelines tasks onto busy workers (RTT "
   "amortization); above it, one task per lease (parallelism first)")
_d("max_tasks_in_flight_per_worker", int, 16,
   "pipelined task pushes per leased worker (reference: "
   "RAY_max_tasks_in_flight_per_worker); bigger batches amortize frame + "
   "ack cost for short tasks, smaller keeps load balancing tight")
_d("worker_pool_min_workers", int, 0, "prestarted workers per node")
_d("worker_pool_idle_ttl_s", float, 60.0, "idle worker reap time")
_d("worker_niceness", int, 0, "niceness applied to spawned workers")

_d("memory_usage_threshold", float, 0.95,
   "node memory fraction above which the memory monitor kills the "
   "worst worker (reference: RAY_memory_usage_threshold); 1.0 disables")
_d("memory_monitor_refresh_ms", int, 1000,
   "memory monitor sample period; 0 disables "
   "(reference: RAY_memory_monitor_refresh_ms)")

# --- core worker internals ---
_d("borrow_flush_batch_size", int, 512,
   "borrow registrations buffered per owner before an inline flush "
   "(between flushes the periodic sweep delivers)")
_d("borrow_buffer_max", int, 100_000,
   "cap on re-enqueued borrow notifications per unreachable owner")
_d("cancelled_ids_max", int, 8192,
   "FIFO-bounded remembered cancelled task ids (dedup for re-dispatch)")
_d("actor_send_batch_max", int, 256,
   "max actor calls coalesced into one push_actor_batch frame")
_d("recent_tasks_ring", int, 512,
   "per-owner recent task completions kept for the local state API")
_d("task_event_outbox_max", int, 10_000,
   "completed-task events buffered between flushes to the head")
_d("dispatcher_idle_linger_s", float, 2.0,
   "how long an idle per-key dispatcher thread lingers before exiting "
   "(covers sync submit-get loops without a thread spawn per call)")
_d("worker_seen_tasks_max", int, 20_000,
   "executed-task dedup window per worker (at-least-once pushes)")
_d("worker_exec_pool_size", int, 64,
   "worker task-execution thread pool (tasks beyond the lease slot "
   "queue; blocked tasks yield the slot)")
_d("done_flusher_idle_ttl_s", float, 60.0,
   "per-owner completion flusher thread exits after this idle time")

# --- fault tolerance ---
_d("transfer_pin_ttl_s", float, 30.0,
   "owner-side lifetime extension for refs serialized into messages "
   "(bridges the serialize -> add_borrower registration gap)")
_d("task_max_retries_default", int, 3, "default retries for retriable tasks")
_d("task_retry_delay_ms", int, 100, "backoff between task retries")
_d("actor_max_restarts_default", int, 0, "default actor restarts")
_d("health_check_period_ms", int, 1000, "controller -> nodelet ping period")
_d("health_check_failure_threshold", int, 5, "missed pings before node is dead")
_d("max_lineage_bytes", int, 64 * 1024**2, "lineage table cap before eviction")

# --- rpc / control plane ---
_d("rpc_connect_timeout_s", float, 10.0, "TCP connect timeout")
_d("rpc_retry_max_attempts", int, 5, "retryable RPC attempts")
_d("rpc_retry_delay_ms", int, 100, "base retry backoff")
_d("rpc_chaos_failure_prob", float, 0.0,
   "fault-injection: probability an RPC is dropped (request or reply). "
   "Equivalent of the reference's RAY_testing_rpc_failure chaos flag "
   "(src/ray/rpc/rpc_chaos.h). Blind drops fire only on RETRY_SAFE_RPCS "
   "(cluster/protocol.py) — methods whose callers retry/dedup; targeted "
   "drops of anything else go through chaos_plan rules")
_d("chaos_plan", str, "",
   "deterministic fault-injection plan (devtools/chaos.py grammar): "
   "';'-separated rules targeting (rpc method, role, peer, nth call) "
   "with drop_request/drop_response/delay/sever/kill actions. Set via "
   "RTPU_CHAOS_PLAN so every spawned head/node/worker process inherits "
   "the same plan; counters are per process, so nth-rules are "
   "reproducible wherever request routing is")
_d("chaos_seed", int, 0,
   "default RNG seed for chaos_plan prob= rules (per-rule seed= "
   "overrides); fixed seed + fixed plan => identical fault sequences")
_d("rpc_retry_min_window_s", float, 8.0,
   "retrying_call keeps retrying INSTANT connection failures at least "
   "this long before giving up (attempt counting alone exhausts in "
   "~3s of backoff — less than a head/node respawn under chaos); slow "
   "failures (timeouts) still stop after rpc_retry_max_attempts")
_d("pubsub_poll_timeout_s", float, 30.0, "long-poll timeout")

# --- streaming generators ---
_d("streaming_item_timeout_s", float, 600.0,
   "how long ObjectRefGenerator.__next__ waits for the next yield before "
   "raising GetTimeoutError (slow-but-healthy producers need headroom)")
_d("streaming_ahead_max", int, 64,
   "default producer window: items delivered ahead of the consumer before "
   "the streaming-generator producer pauses (reference: "
   "_generator_backpressure_num_objects); per-task override via the "
   "generator_backpressure_num_objects task option")

# --- data ---
_d("data_memory_budget_bytes", int, 512 * 1024**2,
   "streaming execution: target cap on bytes of blocks in flight across "
   "all operators of one pipeline (reference: ReservationOpResourceAllocator "
   "budgets in streaming_executor_state.py); 0 disables byte backpressure "
   "and only the per-operator concurrency caps apply")
_d("data_block_size_estimate", int, 8 * 1024**2,
   "assumed block size before the first real block lands (seeds the "
   "memory-budget admission until running averages exist)")
_d("data_executor", str, "streaming",
   "physical executor: 'streaming' runs map stages on long-lived operator "
   "actors connected by bounded channel queues (falls back to 'pull' off a "
   "cluster runtime or inside worker processes); 'pull' forces the "
   "task-per-block generator chain")
_d("data_streaming_lanes", int, 2,
   "lanes (operator-actor replicas) per task-pool map stage under the "
   "streaming executor; actor-pool stages use their own pool bounds")
_d("data_queue_capacity", int, 8,
   "bounded inter-operator queue depth in FRAMES per lane edge (rides "
   "dag ring/peer channel backpressure; blocks stay in the object store, "
   "frames carry refs)")
_d("data_exchange_transport", str, "channel",
   "shuffle partition traffic: 'channel' streams partition pieces over "
   "mapper->reducer channel meshes (falls back to 'tasks' off-cluster, on "
   "failure, or when the exchange would exceed the in-memory working-set "
   "bound); 'tasks' forces the per-task-RPC two-stage exchange")
_d("data_exchange_mappers", int, 2,
   "mapper actors in a channel-backed exchange")
_d("data_exchange_reducers", int, 2,
   "reducer actors in a channel-backed exchange (each owns "
   "num_outputs/reducers partitions)")

# --- TPU / accelerator ---
_d("tpu_chips_per_host", int, 4, "chips per TPU VM host (v5e/v5p default 4)")
_d("tpu_slice_exclusive", bool, True,
   "enforce one-process-per-host TPU ownership when leasing TPU resources")
_d("device_prefetch_depth", int, 2, "host->HBM prefetch pipeline depth for data")

# --- serve ---
_d("serve_reconcile_period_s", float, 1.0,
   "controller reconciliation loop period (target-vs-running diff)")
_d("serve_router_refresh_s", float, 2.0,
   "router fallback replica-set poll period (long-poll push is primary)")
_d("serve_handle_timeout_s", float, 60.0,
   "deployment-handle call timeout (handle.remote().result() default)")
_d("serve_router_policy", str, "scored",
   "replica selection policy: 'scored' (prefix-affinity + queue depth + "
   "KV headroom over controller-pushed load snapshots, pow-2 when "
   "snapshots are missing/stale), 'pow2' (local-inflight "
   "power-of-two-choices only), 'random' (uniform; bench baseline)")
_d("serve_router_score_all_max", int, 8,
   "scored routing considers EVERY replica when the set is at most this "
   "large; beyond it, falls back to scoring a pow-2 sample (O(1) "
   "routing at large fan-out, full information when small)")
_d("serve_router_prefix_blocks", int, 8,
   "leading prompt blocks hashed for prefix-affinity scoring (deeper "
   "matches than this add no routing signal, only hashing cost)")
_d("serve_router_prefix_weight", float, 1.5,
   "scored routing: weight of the prefix-affinity term (fraction of "
   "the prompt already resident on the candidate). Calibrated above "
   "queue_weight: a full-prefix miss re-prefills the whole prompt — "
   "typically several hit-request service times — so affinity should "
   "survive a one-to-two-request queue imbalance, not flip on it")
_d("serve_router_queue_weight", float, 1.0,
   "scored routing: weight of the queue-pressure penalty (snapshot "
   "queue depth + engine waiting + caller-local in-flight, normalized "
   "by the replica's slot count)")
_d("serve_router_kv_weight", float, 0.5,
   "scored routing: weight of the KV-pressure penalty (1 - free/total "
   "cache blocks on the candidate)")
_d("serve_router_ttft_weight", float, 0.0,
   "scored routing: weight of the replica's EWMA TTFT (seconds) as a "
   "pressure term — 0 (default) keeps scores byte-identical to the "
   "pre-disagg router; the disaggregated prefill pool sets it so "
   "admission pressure on a slow-prefilling replica steers arrivals "
   "away before the SLO gate has to shed them")
_d("serve_disagg_max_redirects", int, 2,
   "disaggregated serving: how many times a prefill replica re-routes "
   "one request's KV handoff after a decode-replica death before "
   "failing the request")
_d("serve_snapshot_ttl_s", float, 5.0,
   "replica load snapshots older than this are treated as absent "
   "(scored routing falls back to pow-2 rather than trust a dead "
   "controller's last word)")
_d("serve_snapshot_prefix_hashes", int, 256,
   "cap on resident prefix-block chain hashes exported per replica "
   "load snapshot")
_d("serve_kv_fleet_min_prefix_blocks", int, -1,
   "fleet KV-cache economy: minimum contiguous pullable prefix (in "
   "blocks) before an engine pulls spilled KV pages from the tiered "
   "object store instead of recomputing them. -1 (default) disables "
   "the fleet tier entirely — engines are byte-identical to "
   "per-replica caching; 0 always pulls; n>0 pulls only runs of at "
   "least n blocks (engines may also be built with 'auto' to gate on "
   "the measured pull-vs-recompute crossover)")
_d("serve_router_fleet_kv_weight", float, 0.0,
   "scored routing: weight of a replica's FLEET KV residency (spilled "
   "prefix pages it can re-install without recompute) — 0 (default) "
   "keeps scores byte-identical to per-replica prefix affinity; "
   "fleet-enabled deployments set it so multi-turn traffic lands "
   "where its evicted prefixes still live in the shm tier")
_d("serve_snapshot_fleet_hashes", int, 32,
   "cap on recently-spilled/pulled prefix-block chain hashes exported "
   "per replica load snapshot (the fleet-residency summary the "
   "router's fleet term scores on)")
_d("serve_kv_fleet_local_bytes", int, 256 << 20,
   "byte cap of the in-process fleet KV page store used when no "
   "cluster shm store is attached (store-free engines, unit tests); "
   "oldest pages evict LRU past the cap")
_d("serve_slo_ttft_budget_ms", float, 0.0,
   "admission control: p99 TTFT budget per deployment at the ingress "
   "proxy — past it, new requests queue (bounded) then shed with a "
   "503. 0 disables admission control")
_d("serve_slo_queue_depth", int, 32,
   "admission control: max requests parked per deployment while the "
   "p99 budget is breached before shedding")
_d("serve_slo_queue_timeout_s", float, 5.0,
   "admission control: max seconds a request waits in the admission "
   "queue before shedding")
_d("serve_slo_window", int, 64,
   "admission control: sliding window of recent TTFT samples the "
   "p99 estimate is computed over")
_d("serve_slo_min_samples", int, 8,
   "admission control: TTFT samples required before the p99 estimate "
   "can gate admission (cold deployments admit freely)")
_d("serve_slo_probe_inflight", int, 1,
   "admission control: in-flight requests still admitted while over "
   "budget — fresh samples must keep flowing or the p99 estimate "
   "could never recover")
_d("serve_autoscale_up_sustain_s", float, 2.0,
   "serve autoscaling: seconds load must exceed target before scaling "
   "up (one-tick spikes don't add replicas)")
_d("serve_autoscale_down_sustain_s", float, 10.0,
   "serve autoscaling: seconds load must sit below the down threshold "
   "before scaling down (idle gaps between bursts don't thrash)")
_d("serve_autoscale_down_threshold", float, 0.5,
   "serve autoscaling: scale down only while mean ongoing per replica "
   "is under this fraction of target_ongoing_requests")
_d("serve_autoscale_cooldown_s", float, 5.0,
   "serve autoscaling: min seconds between replica-count changes "
   "(hysteresis both directions)")
_d("serve_qos_tokens_per_s", float, 0.0,
   "per-tenant QoS: default token-budget refill rate (LLM tokens/s — "
   "prompt + max_new per request) for tenants without an explicit "
   "TenantConfig. 0 (default) = unlimited budget; WFQ ordering and "
   "priority classes still apply between contending tenants")
_d("serve_qos_burst_tokens", float, 0.0,
   "per-tenant QoS: default token-bucket capacity; 0 derives 4 seconds "
   "of the refill rate (a short burst rides through, sustained flood "
   "pins the tenant to its rate)")
_d("serve_qos_tenant_idle_s", float, 600.0,
   "per-tenant QoS: reap a lazily-minted tenant lane (bucket, WFQ "
   "state, TTFT window) after this many seconds with nothing queued, "
   "inflight, or recorded. Tenants installed via configure_tenant are "
   "pinned and never reaped. 0 disables reaping (the tenant map then "
   "grows with the distinct-tenant universe — bounded only by churn)")
_d("serve_qos_queue_depth", int, 0,
   "per-tenant QoS: max requests parked PER TENANT at the admission "
   "gate before that tenant sheds (isolation: one flooding tenant "
   "fills only its own queue). 0 = use serve_slo_queue_depth")
_d("serve_router_topk", int, 4,
   "scored routing at scale (> serve_router_score_all_max replicas): "
   "how many best-base-score candidates the incremental rank feeds "
   "into full scoring per decision — O(topk), not O(replicas)")
_d("serve_router_affinity_cands", int, 4,
   "scored routing at scale: cap on prefix/fleet-affinity candidates "
   "pulled from the inverted hash index per decision (joined with the "
   "top-k base candidates)")
_d("serve_router_session_affinity_max", int, 8192,
   "sticky-session routing: cap on session-key -> replica pins held "
   "per router (FIFO evict past it); multi-turn sessions re-land on "
   "the replica holding their prefix blocks")
_d("serve_snapshot_journal", int, 64,
   "controller load-snapshot delta fan-out: how many recent load "
   "generations of per-replica change sets are journaled per "
   "deployment — long-pollers within the window receive only changed "
   "snapshots (O(touched)); anyone further behind gets a full resync")

# --- client tier ---
_d("client_ref_flush_period_s", float, 0.2,
   "remote-driver clients: hold/release reconciliation sweep period")

# --- cluster lifecycle ---
_d("node_boot_timeout_s", float, 30.0,
   "seconds to wait for a spawned head/node process to print its address")
_d("head_supervisor_poll_s", float, 0.5,
   "driver-side head supervisor liveness poll period")

# --- durable control plane (at-least-once actor calls, rolling head
# upgrades, restart recovery) ---
_d("actor_restart_queue_timeout_s", float, 60.0,
   "how long queued actor calls wait for a PENDING/RESTARTING actor to "
   "come back before failing with ActorDiedError (the restart-pending "
   "queueing window: callers park, they don't error, while a "
   "max_restarts recreation is in flight)")
_d("actor_reply_memo_max", int, 1024,
   "per-(actor, caller) LRU memo of executed calls' result batches: a "
   "retried call whose results were already computed is answered from "
   "the memo instead of re-executing (the at-least-once dedup half)")
_d("actor_order_states_max", int, 4096,
   "distinct caller streams tracked per hosted actor (seq horizon + "
   "reply memo); least-recently-active streams beyond the cap are "
   "evicted — a dead driver's stream must not pin memo state forever")
_d("head_restart_actor_grace_s", float, 10.0,
   "after a head restart, how long a recovered-ALIVE actor's host node "
   "gets to re-register before the actor is declared dead and re-driven "
   "through its max_restarts policy (covers the all-holders-dead case: "
   "host node and head died together, so no worker_dead_at report ever "
   "arrives)")
_d("head_upgrade_drain_timeout_s", float, 15.0,
   "rolling head upgrade: max wait for in-flight creations to settle "
   "during prepare_upgrade before the snapshot flush proceeds anyway")

# --- compiled DAGs ---
_d("dag_channel_capacity", int, 8,
   "compiled-DAG channel slots: executions pipeline up to this depth "
   "before the driver's next execute() blocks")
_d("dag_teardown_timeout_s", float, 10.0,
   "teardown handshake: wait for each loop to consume its stop sentinel")
_d("dag_ring_bytes", int, 1 << 20,
   "same-node compiled-DAG channel ring size (data bytes of the shm "
   "mmap ring each edge maps); records bigger than dag_ring_spill_bytes "
   "spill to a side file so one huge payload never has to fit")
_d("dag_ring_spill_bytes", int, 1 << 18,
   "ring records larger than this many payload bytes spill to a side "
   "file next to the ring (the ring carries the reference); the writer "
   "pins the spill until the reader consumes it and reclaims it on "
   "teardown — a reader death can never leak the payload")
_d("dag_spill_reclaim_grace_s", float, 5.0,
   "how long a closing writer waits for the reader to consume pending "
   "spill side-files before reclaiming (unlinking) them; a reader that "
   "already closed is not waited for — the grace only covers a LIVE "
   "reader mid-read (unlinking under it was the bench.py --dag flake)")
_d("dag_channel_dir", str, "",
   "directory for same-node channel rings/spills ('' = /dev/shm when "
   "present, else the system temp dir). Both endpoints of an edge must "
   "resolve the same directory — it IS the rendezvous namespace")
_d("dag_negotiate_timeout_s", float, 30.0,
   "one-time channel negotiation budget: ring-file rendezvous attach "
   "and head-mediated cross-node endpoint lookup both give up (with "
   "peer-liveness context in the error) after this long")
_d("dag_overlap_comm", bool, False,
   "compiled DAGs: run channel writes on a dedicated sender thread so "
   "compute for step n+1 overlaps the send of step n (reference: "
   "overlap_gpu_communication, dag/context.py:78 — also opt-in there). "
   "Wins when send latency and compute can genuinely run in parallel "
   "(multi-core hosts, cross-node channels); on single-core hosts the "
   "thread hop costs more than it saves (measured 0.77x)")

# --- metrics / events ---
_d("metrics_report_period_ms", int, 5000, "metrics push period")
_d("metrics_export_port", int, 0,
   "per-node Prometheus scrape port (GET /metrics on every node manager; "
   "the bound port rides the node's 'metrics-port' label). 0 = ephemeral "
   "port, -1 disables the exporter")
_d("task_events_buffer_size", int, 10_000, "ring buffer of per-task state events")
_d("event_stats_enabled", bool, True, "per-handler latency accounting")
_d("tracing_enabled", bool, False,
   "distributed spans: task specs carry the submitter's trace context, "
   "executors open child spans, spans flush to the head trace ring "
   "(reference: the opt-in OpenTelemetry hooks in util/tracing/)")
_d("trace_ring_size", int, 20_000, "head-side retained span cap (entries)")
_d("trace_ring_max_bytes", int, 16 * 1024**2,
   "head-side retained span cap in approximate BYTES (spans carry user "
   "attrs; entry count alone lets one chatty tracer eat the head's "
   "memory); overflow drops oldest spans and counts them into "
   "rtpu_trace_spans_dropped_total")
_d("trace_attr_max_bytes", int, 1024,
   "per-attribute value size cap at the head's span sink: larger values "
   "are truncated with a '...[truncated]' marker on ingest")
_d("flight_recorder_enabled", bool, True,
   "always-on per-process ring of structured runtime events (RPC "
   "dispatch, heartbeats, lease churn, store seal/evict, engine ticks); "
   "dumped via rpc_dump_flight, SIGUSR2, chaos kills, and unhandled "
   "worker death (util/flight_recorder.py)")
_d("flight_recorder_size", int, 4096,
   "flight-recorder ring capacity (events per process)")
_d("flight_recorder_dump_dir", str, "",
   "directory for flight-recorder dump files (SIGUSR2 / chaos-kill / "
   "worker-death); empty = the log dir")
_d("clock_sync_period_beats", int, 10,
   "node managers probe the head clock every N heartbeat laps and keep "
   "an RTT-corrected EWMA offset estimate (trace_dump aligns per-node "
   "event clocks with it); 0 disables probing")

# --- logging ---
_d("log_dir", str, "/tmp/ray_tpu/logs", "per-process log files")
_d("log_to_driver", bool, True, "ship worker stdout/stderr lines to the driver")
_d("log_monitor_poll_s", float, 0.5,
   "driver log-shipper scan period over worker log files")

# --- rpc / control plane (breadth: reference ray_config_def.h RPC and
# timeout families — gcs_rpc_server_request_timeout_seconds,
# gcs_server_request_timeout_seconds, timeout knobs per subsystem) ---
_d("rpc_control_timeout_s", float, 5.0,
   "standard control-RPC deadline (lease return, bundle release, "
   "object-location queries, drains)")
_d("rpc_state_timeout_s", float, 10.0,
   "registration/report RPC deadline (node/worker register, ref "
   "bookkeeping, location publishes)")
_d("rpc_recv_chunk_bytes", int, 1 << 20,
   "max bytes per socket recv() in the frame reader")
_d("rpc_scatter_min_bytes", int, 64 * 1024,
   "payloads whose pickle-5 out-of-band buffers total at least this ride "
   "the scatter frame form: buffers go straight to sendmsg (never "
   "flattened host-side) and land via recv_into on the receiver")
_d("rpc_listen_backlog", int, 128, "server socket accept backlog")
_d("pubsub_retry_delay_s", float, 0.5,
   "subscriber reconnect backoff after a dropped long-poll")

# --- scheduling breadth ---
_d("lease_grant_push_timeout_s", float, 60.0,
   "head -> node deadline for pushing a granted actor lease spec")
_d("lease_backoff_base_s", float, 0.1,
   "declined-lease backoff floor per scheduling key")
_d("lease_backoff_max_s", float, 0.5,
   "declined-lease backoff ceiling per scheduling key")
_d("lease_grant_dedup_max", int, 4096,
   "node-side FIFO window of lease ids for duplicate-grant detection")
_d("max_concurrent_worker_spawns", int, 4,
   "cold worker spawns in flight per node (zygote forks are not "
   "bounded by this; reference: worker_maximum_startup_concurrency)")
_d("zygote_spawn_timeout_s", float, 60.0,
   "deadline for a zygote fork round-trip (first covers import warmup)")
_d("worker_graceful_shutdown_s", float, 2.0,
   "SIGTERM-to-SIGKILL grace for workers at node shutdown")
_d("pg_bundle_retry_sleep_s", float, 0.1,
   "head retry pause between placement-group bundle placement passes")
_d("head_demand_window_max", int, 512,
   "ring of recent unmet demands kept for the autoscaler demand report")

# --- core worker breadth ---
_d("put_create_retry_deadline_s", float, 60.0,
   "how long put() waits out a concurrent writer holding the same "
   "object slot before failing")
_d("object_poll_interval_s", float, 0.2,
   "sleep between remote-object readiness probes in get()/wait() "
   "fallback polling")
_d("recovering_ids_max", int, 4096,
   "FIFO window of object ids currently under lineage reconstruction "
   "(dedups concurrent recovery triggers)")
_d("push_ack_timeout_s", float, 5.0,
   "deadline for a worker's ack of a pushed task group before the "
   "group re-dispatches elsewhere")
_d("actor_connect_timeout_s", float, 120.0,
   "waiting for a created actor's address to publish before the first "
   "method call fails")
_d("push_ack_idle_poll_s", float, 0.01,
   "push-ack reaper pause when no ack is outstanding-but-ready")

# --- store breadth ---
_d("object_store_slots", int, 1 << 16,
   "shm store object-table slots (max resident objects per node)")
_d("object_store_shards", int, 8,
   "shm store arena shards: each has its own process-shared mutex, slot "
   "stripe and free list, so concurrent writers stop serializing on one "
   "lock. Ceiling — tiny stores shrink it so every sub-arena stays "
   "usefully large. NOTE: a single object cannot exceed one sub-arena "
   "(~capacity/shards); lower this for giant-object workloads")
_d("spill_restore_poll_s", float, 0.05,
   "pull-manager pause between spilled-object restore attempts")
_d("pull_fanout_max_holders", int, 4,
   "max holder nodes a chunked pull fans out across in parallel "
   "(reference: object_manager Pull spreads chunk requests over copies)")
_d("pull_fanout_min_bytes", int, 8 * 1024**2,
   "objects at least this large pull chunks from multiple holders in "
   "parallel; smaller ones single-stream from the nearest holder")
