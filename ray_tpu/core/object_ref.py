"""ObjectRef: a first-class future handle to a (possibly remote) value.

Parity target: the reference's ObjectRef semantics
(reference: python/ray/includes/object_ref.pxi) — hashable, picklable
(pickling registers a borrow with the owner), awaitable, and releasing the
last in-scope reference lets the store reclaim the value.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Any, Optional

from ray_tpu.core.ids import ObjectID

# The runtime currently driving this process; set by ray_tpu.init machinery.
_runtime_holder = threading.local()


def _current_runtime():
    from ray_tpu.core.runtime_context import get_runtime

    return get_runtime()


class ObjectRef:
    __slots__ = ("_id", "_owner_addr", "_skip_release", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_addr: Optional[str] = None,
                 _add_local_ref: bool = True):
        self._id = object_id
        self._owner_addr = owner_addr
        self._skip_release = not _add_local_ref
        if _add_local_ref:
            rt = _current_runtime()
            if rt is not None:
                rt.refcount.add_local_ref(object_id)

    def id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    @property
    def owner_address(self) -> Optional[str]:
        return self._owner_addr

    def task_id(self):
        return self._id.task_id()

    def future(self) -> Future:
        """A concurrent.futures.Future resolved with the value (or exception)."""
        rt = _current_runtime()
        fut: Future = Future()

        def _on_ready(rec):
            # The future may have been CANCELLED (asyncio.wait_for timeout
            # or a disconnected client cancelling its await): set_* would
            # raise InvalidStateError out of the store's delivery thread.
            try:
                value = rt.resolve_record(rec)
            except BaseException as e:  # noqa: BLE001 - propagate task errors
                if not fut.cancelled():
                    try:
                        fut.set_exception(e)
                    except Exception:
                        pass
                return
            if not fut.cancelled():
                try:
                    fut.set_result(value)
                except Exception:
                    pass

        rt.register_ready_callback(self._id, _on_ready)
        return fut

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        # Serializing a ref transfers a borrow: the deserializer re-registers
        # a local reference on its side (ownership stays with the creator).
        # The OWNER side must bridge the gap between "my last local ref
        # died" and "the receiver's add_borrower arrived" — without a pin,
        # returning a ref from an actor method frees the object before the
        # caller can fetch it.
        rt = _current_runtime()
        if rt is not None and hasattr(rt, "pin_for_transfer"):
            rt.pin_for_transfer(self._id, self._owner_addr)
        return (_deserialize_ref, (self._id.binary(), self._owner_addr))

    def __del__(self):
        if self._skip_release:
            return
        try:
            rt = _current_runtime()
            if rt is not None:
                rt.refcount.remove_local_ref(self._id)
        except Exception:
            pass  # interpreter shutdown


def _deserialize_ref(binary: bytes, owner_addr: Optional[str]) -> ObjectRef:
    oid = ObjectID(binary)
    rt = _current_runtime()
    if rt is not None:
        rt.on_ref_deserialized(oid, owner_addr)
    return ObjectRef(oid, owner_addr)
