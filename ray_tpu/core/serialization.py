"""Object serialization with zero-copy numpy/JAX array path.

Equivalent of the reference's SerializationContext
(reference: python/ray/_private/serialization.py) but laid out for the TPU
data path: encoding uses pickle protocol 5 with out-of-band buffers, so large
numpy arrays are written into shared memory (or a socket) without an
intermediate copy and decoded as views directly over the mapped store memory.
jax.Arrays are serialized via their host numpy form (``np.asarray``) — device
residency is a property of where a value is *used* (mesh shardings), never of
the wire format.

Flat wire layout (little-endian), used for shm store slots and sockets:
    u32 magic | u32 header_len | header bytes (cloudpickle, protocol 5)
    u64 nbufs | (u64 len, buf bytes)*          -- 8-byte aligned each
"""

from __future__ import annotations

import io
import pickle
import struct
import traceback
from typing import Any, List, Tuple

import cloudpickle
import numpy as np

from ray_tpu.exceptions import TaskError

_MAGIC = 0x52545055  # "RTPU"
_ALIGN = 8

# Buffers below this stay inline in the pickle stream; frame overhead wins.
_OOB_MIN_BYTES = 512


def _is_jax_array(value) -> bool:
    cls = type(value)
    return cls.__module__.startswith("jax") and cls.__name__ in ("ArrayImpl", "Array")


def _restore_jax(host: np.ndarray):
    import jax

    return jax.numpy.asarray(host)


def _jax_reduce(host: np.ndarray):
    """Reconstructs a jax.Array from a (possibly out-of-band) numpy array."""
    return (_restore_jax, (host,))


class _NeedsCloudpickle(Exception):
    """Raised inside the fast path to force the cloudpickle fallback."""


class _FastPickler(pickle.Pickler):
    """C-speed pickler for the common case (control frames, numpy, plain
    data). reducer_override keeps the jax-array host-numpy path; everything
    else runs the C fast paths (~10-20x cheaper per frame than cloudpickle,
    whose Python-level reducer_override is invoked per object).

    __main__-defined classes/functions (driver scripts, REPLs) MUST go
    by-value: stock pickle would happily encode them by reference
    ("__main__.Foo"), which decodes to the WRONG (or missing) attribute in
    a worker whose __main__ is worker_main — so seeing one aborts to the
    cloudpickle path."""

    def reducer_override(self, obj):
        if _is_jax_array(obj):
            return _jax_reduce(np.asarray(obj))
        if isinstance(obj, type) or callable(obj):
            if getattr(obj, "__module__", None) == "__main__":
                raise _NeedsCloudpickle
        elif type(obj).__module__ == "__main__":
            raise _NeedsCloudpickle
        return NotImplemented


class Serializer:
    """Stateless encode/decode; one instance per worker."""

    def serialize(self, value: Any) -> Tuple[bytes, List[memoryview]]:
        """Returns (header_bytes, out_of_band_buffers)."""
        buffers: List[memoryview] = []

        def buffer_callback(pb: pickle.PickleBuffer) -> bool:
            view = pb.raw()
            if view.nbytes < _OOB_MIN_BYTES:
                return True  # keep small buffers inline
            buffers.append(view)
            return False  # emitted out-of-band

        sio = io.BytesIO()
        try:
            _FastPickler(sio, protocol=5,
                         buffer_callback=buffer_callback).dump(value)
            return sio.getvalue(), buffers
        except Exception:
            # Functions / local classes / anything stock pickle rejects:
            # retry with cloudpickle's by-value machinery.
            buffers.clear()

        class _Pickler(cloudpickle.CloudPickler):
            def reducer_override(self, obj):
                if _is_jax_array(obj):
                    return _jax_reduce(np.asarray(obj))
                # Delegate: CloudPickler's own reducer_override implements
                # by-value pickling of __main__/unimportable functions.
                return super().reducer_override(obj)

        sio = io.BytesIO()
        _Pickler(sio, protocol=5, buffer_callback=buffer_callback).dump(value)
        return sio.getvalue(), buffers

    def deserialize(self, header: bytes, buffers: List[memoryview]) -> Any:
        return pickle.loads(header, buffers=buffers)

    # --- flat wire form (for shm / sockets) ---

    def encode_total_size(self, header: bytes, buffers: List[memoryview]) -> int:
        total = 8 + _pad(len(header)) + 8
        for b in buffers:
            total += 8 + _pad(b.nbytes)
        return total

    def encode_into(self, dest: memoryview, header: bytes, buffers: List[memoryview]) -> int:
        """Writes the flat wire form into dest; returns bytes written."""
        off = 0
        struct.pack_into("<II", dest, off, _MAGIC, len(header))
        off += 8
        dest[off : off + len(header)] = header
        off += _pad(len(header))
        struct.pack_into("<Q", dest, off, len(buffers))
        off += 8
        for b in buffers:
            flat = b.cast("B") if b.ndim != 1 or b.format != "B" else b
            struct.pack_into("<Q", dest, off, flat.nbytes)
            off += 8
            stream_copy(dest[off : off + flat.nbytes], flat)
            off += _pad(flat.nbytes)
        return off

    def encode(self, value: Any) -> bytearray:
        """One-copy flat encode: the bytearray the flat form is written
        into IS the return value (the old ``bytes(out)`` re-copied every
        payload — one full extra pass on the put/transfer path)."""
        header, buffers = self.serialize(value)
        out = bytearray(self.encode_total_size(header, buffers))
        n = self.encode_into(memoryview(out), header, buffers)
        if n != len(out):  # encode_total_size is exact; guard only
            del out[n:]
        return out

    def decode(self, data) -> Any:
        """Zero-copy decode: numpy results view into ``data``."""
        if isinstance(data, (bytes, bytearray)):
            data = memoryview(data)
        magic, hlen = struct.unpack_from("<II", data, 0)
        if magic != _MAGIC:
            raise ValueError("corrupt object header")
        off = 8
        header = bytes(data[off : off + hlen])
        off += _pad(hlen)
        (nbufs,) = struct.unpack_from("<Q", data, off)
        off += 8
        buffers: List[memoryview] = []
        for _ in range(nbufs):
            (blen,) = struct.unpack_from("<Q", data, off)
            off += 8
            buffers.append(data[off : off + blen])
            off += _pad(blen)
        return self.deserialize(header, buffers)


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


_STREAM_COPY_MIN = 1 << 20


def stream_copy(dest, src) -> None:
    """Copy ``src`` (bytes-like) into the equal-length writable buffer
    ``dest``. Blocks >= 1 MB go through np.copyto, which streams
    measurably faster than memoryview slice assignment (and this copy IS
    the put bandwidth for big objects); used by both the wire encoder and
    the shm store's put path so the threshold lives in one place."""
    n = len(src) if not isinstance(src, memoryview) else src.nbytes
    if n >= _STREAM_COPY_MIN:
        np.copyto(np.frombuffer(dest, np.uint8),
                  np.frombuffer(src, np.uint8))
    else:
        dest[:] = src


def capture_exception(exc: BaseException) -> TaskError:
    """Package a remote exception for transport to the get() site."""
    tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
    try:
        cloudpickle.dumps(exc)
        cause = exc
    except Exception:
        cause = None
    return TaskError(type(exc).__name__, tb, cause,
                     exc_type_mro=[c.__name__ for c in type(exc).__mro__])


SERIALIZER = Serializer()
