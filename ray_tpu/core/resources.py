"""Resource vectors with fixed-point fractional accounting.

Equivalent of the reference's scheduling resource model (reference:
src/ray/common/scheduling/resource_set.h, fixed_point.h,
resource_instance_set.h), rebuilt around TPU-pod semantics: resources are
string->fixed-point maps; ``TPU`` is countable per-chip like CPU/GPU, and TPU
*slices* are modeled with head resources (e.g. ``TPU-v5e-8-head``) plus node
labels carrying slice name/topology so placement can keep an SPMD group on one
ICI domain (mirrors python/ray/_private/accelerators/tpu.py semantics).
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping, Optional

PRECISION = 10_000  # fixed-point denominator: 1.0 == 10000 units

CPU = "CPU"
GPU = "GPU"
TPU = "TPU"
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"

# Label keys attached to nodes for topology-aware scheduling.
LABEL_SLICE_NAME = "ray_tpu.io/slice-name"
LABEL_SLICE_TOPOLOGY = "ray_tpu.io/slice-topology"
LABEL_ACCELERATOR_TYPE = "ray_tpu.io/accelerator-type"
LABEL_HOST_INDEX = "ray_tpu.io/slice-host-index"
LABEL_NODE_ID = "ray_tpu.io/node-id"


def to_fixed(v: float) -> int:
    return int(round(v * PRECISION))


def from_fixed(u: int) -> float:
    return u / PRECISION


class ResourceSet:
    """Immutable-ish demand vector (fixed-point internally)."""

    __slots__ = ("_units",)

    def __init__(self, units: Optional[Dict[str, int]] = None):
        self._units = {k: v for k, v in (units or {}).items() if v != 0}

    @classmethod
    def from_dict(cls, d: Mapping[str, float]) -> "ResourceSet":
        return cls({k: to_fixed(v) for k, v in d.items()})

    def to_dict(self) -> Dict[str, float]:
        return {k: from_fixed(v) for k, v in self._units.items()}

    def units(self) -> Dict[str, int]:
        return dict(self._units)

    def get(self, name: str) -> float:
        return from_fixed(self._units.get(name, 0))

    def is_empty(self) -> bool:
        return not self._units

    def keys(self) -> Iterable[str]:
        return self._units.keys()

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and other._units == self._units

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"

    def __add__(self, other: "ResourceSet") -> "ResourceSet":
        out = dict(self._units)
        for k, v in other._units.items():
            out[k] = out.get(k, 0) + v
        return ResourceSet(out)


class NodeResources:
    """Mutable total/available pair for one node, with allocation."""

    def __init__(self, total: ResourceSet, labels: Optional[Dict[str, str]] = None):
        self.total = total
        self._avail: Dict[str, int] = total.units()
        self.labels = dict(labels or {})

    @property
    def available(self) -> ResourceSet:
        return ResourceSet(self._avail)

    def can_fit(self, demand: ResourceSet) -> bool:
        for k, v in demand.units().items():
            if self._avail.get(k, 0) < v:
                return False
        return True

    def has_total(self, demand: ResourceSet) -> bool:
        tot = self.total.units()
        return all(tot.get(k, 0) >= v for k, v in demand.units().items())

    def allocate(self, demand: ResourceSet) -> bool:
        if not self.can_fit(demand):
            return False
        for k, v in demand.units().items():
            self._avail[k] = self._avail.get(k, 0) - v
        return True

    def release(self, demand: ResourceSet) -> None:
        tot = self.total.units()
        for k, v in demand.units().items():
            self._avail[k] = min(self._avail.get(k, 0) + v, tot.get(k, 0))

    def utilization(self) -> float:
        """Max utilization over dimensions the node actually has (for packing)."""
        util = 0.0
        for k, total in self.total.units().items():
            if total <= 0:
                continue
            used = total - self._avail.get(k, 0)
            util = max(util, used / total)
        return util

    def add_dynamic(self, extra: ResourceSet) -> None:
        """Registers placement-group bundle resources (2-phase commit target)."""
        tot = self.total.units()
        for k, v in extra.units().items():
            tot[k] = tot.get(k, 0) + v
            self._avail[k] = self._avail.get(k, 0) + v
        self.total = ResourceSet(tot)

    def remove_dynamic(self, extra: ResourceSet) -> None:
        tot = self.total.units()
        for k, v in extra.units().items():
            tot[k] = max(tot.get(k, 0) - v, 0)
            self._avail[k] = max(self._avail.get(k, 0) - v, 0)
        self.total = ResourceSet(tot)


def detect_node_resources(num_cpus: Optional[float] = None,
                          num_tpus: Optional[float] = None,
                          memory: Optional[int] = None,
                          resources: Optional[Dict[str, float]] = None,
                          labels: Optional[Dict[str, str]] = None) -> NodeResources:
    """Autodetect this host's resources (CPU count, TPU chips via jax)."""
    import os

    d: Dict[str, float] = dict(resources or {})
    d[CPU] = num_cpus if num_cpus is not None else float(os.cpu_count() or 1)
    lbl = dict(labels or {})
    if num_tpus is None:
        num_tpus, tpu_labels = _detect_tpu()
        lbl.update(tpu_labels)
    if num_tpus:
        d[TPU] = num_tpus
    if memory is None:
        try:
            import psutil  # pragma: no cover - optional

            memory = int(psutil.virtual_memory().total * 0.7)
        except Exception:
            memory = 8 * 1024**3
    d[MEMORY] = float(memory)
    return NodeResources(ResourceSet.from_dict(d), lbl)


def _detect_tpu():
    """Counts locally attached TPU chips without initializing a TPU runtime.

    Uses the env override first (tests / explicit isolation), then sysfs accel
    devices. Deliberately does NOT call jax.devices(): only one process per
    host may own the TPU runtime, and the node daemon must never claim it.
    """
    import glob
    import os

    env = os.environ.get("RTPU_TPU_CHIPS")
    if env is not None:
        try:
            n = float(env)
        except ValueError:
            n = 0.0
        return n, ({LABEL_ACCELERATOR_TYPE: "TPU"} if n else {})
    chips = glob.glob("/dev/accel*") or glob.glob("/dev/vfio/*")
    if chips:
        return float(len(chips)), {LABEL_ACCELERATOR_TYPE: "TPU"}
    return 0.0, {}
