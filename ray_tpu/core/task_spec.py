"""Task/actor specifications and scheduling strategies.

Equivalent of the reference's TaskSpecification + scheduling strategy types
(reference: src/ray/common/task/task_spec.h,
python/ray/util/scheduling_strategies.py), flattened into plain dataclasses
that serialize with cloudpickle for transport over the control plane.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID
from ray_tpu.core.resources import ResourceSet


# --- scheduling strategies (parity: python/ray/util/scheduling_strategies.py) ---

@dataclass(frozen=True)
class DefaultSchedulingStrategy:
    """Hybrid pack-then-spread with data locality.

    The real policy, end to end (reference: raylet hybrid_scheduling_
    policy.cc + the owner's locality-aware lease policy):

    1. The head filters ALIVE nodes whose availability fits the demand,
       packs onto the most-utilized feasible node until utilization
       crosses `scheduler_spread_threshold`, then prefers the
       least-utilized one. A transiently-saturated cluster falls back to
       ranking by TOTAL capacity so the lease request queues at a node.
    2. Locality: lease requests carry the requesting task's input-object
       ids; the head re-scores feasible nodes by locally-resident input
       bytes (object directory x sealed sizes) and the best holder wins
       — unless its utilization crossed
       `scheduler_locality_spill_threshold`, in which case step 1's
       choice stands (spillback: locality never starves a task).
    3. Owner-side dispatch pairs queued tasks with already-held leases on
       their inputs' holder node (`scheduler_locality_hits/misses`
       counters), falling back to the least-loaded lease so a free
       worker is never left idle while work exists.
    """


@dataclass(frozen=True)
class SpreadSchedulingStrategy:
    """Best-effort round-robin across feasible nodes."""


@dataclass(frozen=True)
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False


@dataclass(frozen=True)
class PlacementGroupSchedulingStrategy:
    placement_group_id: bytes
    bundle_index: int = -1
    capture_child_tasks: bool = False


@dataclass(frozen=True)
class NodeLabelSchedulingStrategy:
    """Hard/soft label match; used for slice-affine TPU placement."""

    hard: Tuple[Tuple[str, str], ...] = ()
    soft: Tuple[Tuple[str, str], ...] = ()


@dataclass(frozen=True)
class SliceAffinitySchedulingStrategy:
    """TPU-native: place onto hosts of one named ICI slice (same pod/slice).

    This is the first-class replacement for the reference's TPU pod resources
    pattern (python/ray/_private/accelerators/tpu.py: `TPU-<pod>-head`):
    instead of resource-name tricks, the scheduler filters on slice labels.
    """

    slice_name: str
    host_index: Optional[int] = None


SchedulingStrategy = Any  # union of the above


@dataclass
class FunctionDescriptor:
    """Identifies a remote function/method for caching across calls."""

    module: str
    qualname: str
    function_hash: bytes

    def key(self) -> Tuple[str, str, bytes]:
        return (self.module, self.qualname, self.function_hash)


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    name: str
    # Serialized callable (cloudpickle) OR descriptor resolved via function table.
    func_blob: Optional[bytes]
    descriptor: Optional[FunctionDescriptor]
    # Args: list of ("value", blob) | ("ref", ObjectID bytes + owner addr)
    args: List[Any]
    kwargs: Dict[str, Any]
    num_returns: int
    resources: ResourceSet
    scheduling_strategy: SchedulingStrategy = field(default_factory=DefaultSchedulingStrategy)
    max_retries: int = 0
    retry_exceptions: bool = False
    # Actor fields
    actor_id: Optional[ActorID] = None  # set for actor tasks
    actor_creation: bool = False
    actor_method_name: Optional[str] = None
    sequence_number: int = 0  # per-caller ordering for actor tasks
    # Actor creation fields
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    concurrency_groups: Dict[str, int] = field(default_factory=dict)
    # Ownership
    owner_addr: Optional[str] = None
    parent_task_id: Optional[TaskID] = None
    # Dependencies that must be local before dispatch (plasma objects).
    depends_on: List[ObjectID] = field(default_factory=list)
    # Runtime env (env vars for now; full plugin system lives in core/runtime_env.py)
    runtime_env: Optional[Dict[str, Any]] = None
    # Generator tasks
    is_streaming_generator: bool = False

    def return_ids(self) -> List[ObjectID]:
        return [ObjectID.for_task_return(self.task_id, i) for i in range(self.num_returns)]

    def scheduling_key(self) -> Tuple:
        """Tasks with equal keys can reuse one worker lease."""
        desc = self.descriptor.key() if self.descriptor else self.name
        return (desc, tuple(sorted(self.resources.units().items())),
                type(self.scheduling_strategy).__name__)


@dataclass
class Bundle:
    """One placement-group bundle (a resource reservation on a single node)."""

    index: int
    resources: ResourceSet


@dataclass
class PlacementGroupSpec:
    pg_id: PlacementGroupID
    bundles: List[Bundle]
    strategy: str  # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
    name: str = ""
    # TPU-native: require all bundles to land inside one named ICI slice.
    slice_affine: bool = False


def pg_key_from_strategy(strategy) -> "Optional[tuple]":
    """Lease-protocol PG key (pg_id, bundle_index) from a wire strategy
    dict; bundle_index -1 means "any bundle of the group" and is resolved
    by the serving node (node_manager._try_acquire). None for non-PG
    strategies."""
    if strategy and strategy.get("kind") == "placement_group":
        return (strategy["pg_id"], strategy.get("bundle_index", -1))
    return None
