"""Single-process runtime: full task/actor/object semantics on threads.

This is the equivalent of running the whole reference stack in one process
(reference behavior: ray.init(local_mode=True), python/ray/_private/worker.py)
but kept *concurrent*: tasks run on a thread pool, actors get dedicated
executors with ordered queues, so async patterns, actor concurrency and
wait/get semantics behave exactly as on a cluster.  The cluster runtime
(ray_tpu/core/cluster_runtime.py) reuses the execution-side pieces; the
difference is only where tasks are placed and where bytes live.

It is also the execution backend inside every cluster *worker* process for
nested task submission.
"""

from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ray_tpu.core import runtime_context
from ray_tpu.core.config import GLOBAL_CONFIG as cfg
from ray_tpu.core.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
    WorkerID,
)
from ray_tpu.core.memory_store import MemoryStore
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.refcount import ReferenceCounter
from ray_tpu.core.serialization import capture_exception
from ray_tpu.core.task_spec import PlacementGroupSpec, TaskSpec
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    TaskCancelledError,
    TaskError,
)

_task_local = threading.local()
_SENTINEL = object()


def _restore_task_local(attr: str, prev) -> None:
    """Restore a _task_local slot to its pre-task state. Deleting (rather than
    setting None) lets current_task_id() fall back to the driver task id on
    recycled pool threads."""
    if prev is _SENTINEL:
        try:
            delattr(_task_local, attr)
        except AttributeError:
            pass
    else:
        setattr(_task_local, attr, prev)


class _ActorState:
    """One live actor: instance + its execution queue/threads."""

    def __init__(self, actor_id: ActorID, name: Optional[str],
                 max_concurrency: int, max_restarts: int):
        self.actor_id = actor_id
        self.name = name
        self.instance: Any = None
        self.cls: Any = None
        self.init_args: Tuple = ()
        self.init_kwargs: Dict = {}
        self.max_concurrency = max_concurrency
        self.max_restarts = max_restarts
        self.restart_count = 0
        self.dead = False
        self.death_reason = ""
        self.lock = threading.Lock()  # serializes calls when max_concurrency == 1
        self.pool = ThreadPoolExecutor(
            max_workers=max_concurrency, thread_name_prefix=f"actor-{actor_id.hex()[:8]}"
        )
        self.is_async = False
        # Return ObjectIDs of submitted-but-unfinished calls; on kill these are
        # failed with ActorDiedError so callers' get() never hangs.
        self.pending_lock = threading.Lock()
        self.pending_returns: Dict[Any, List[Any]] = {}
        self.loop = None  # asyncio loop for async actors
        self.seq_counter = itertools.count()


class LocalRuntime:
    """Implements the runtime interface consumed by the public API layer."""

    is_cluster = False

    def __init__(self, num_cpus: Optional[float] = None, job_id: Optional[JobID] = None):
        self.job_id = job_id or JobID.from_int(1)
        self.node_id = NodeID.from_random()
        self.worker_id = WorkerID.from_random()
        self.memory_store = MemoryStore()
        self.refcount = ReferenceCounter(on_release=self._release_object)
        self._driver_task_id = TaskID.for_driver(self.job_id)
        self._put_counter = itertools.count(1)
        # Local mode simulates a cluster with threads: the pool must be deep
        # enough that nested submit+get chains never exhaust it (a cluster
        # scales workers for nested calls; we oversize instead).
        self._pool = ThreadPoolExecutor(max_workers=256, thread_name_prefix="task")
        self._actors: Dict[ActorID, _ActorState] = {}
        self._named_actors: Dict[Tuple[str, str], ActorID] = {}
        self._actors_lock = threading.Lock()
        self._pgs: Dict[PlacementGroupID, PlacementGroupSpec] = {}
        self._cancelled: set = set()
        self._shutdown = False

        def _flush_loop():
            while not self._shutdown:
                time.sleep(0.2)
                self.refcount.flush_deferred()

        # Finalizer-queued ref decrements apply even when idle (see
        # ReferenceCounter._deferred).
        threading.Thread(target=_flush_loop, daemon=True,
                         name="refcount-flush").start()

    # ------------------------------------------------------------------ refs

    def resolve_record(self, rec) -> Any:
        if rec.is_exception:
            raise rec.value
        return rec.value

    def register_ready_callback(self, oid: ObjectID, cb: Callable) -> None:
        self.memory_store.get_async(oid, cb)

    def on_ref_deserialized(self, oid: ObjectID, owner_addr: Optional[str]) -> None:
        pass  # single process: owner is always us

    def _release_object(self, oid: ObjectID) -> None:
        self.memory_store.delete([oid])

    # ------------------------------------------------------------------ tasks

    def current_task_id(self) -> TaskID:
        return getattr(_task_local, "task_id", self._driver_task_id)

    def current_actor_id(self) -> Optional[ActorID]:
        return getattr(_task_local, "actor_id", None)

    def current_resources(self) -> Dict[str, float]:
        return getattr(_task_local, "resources", {})

    def put(self, value: Any, _owner=None, inline_ok: bool = True
            ) -> ObjectRef:
        # inline_ok is interface parity with ClusterCore.put: one process
        # means the memory store IS the object's lifetime either way.
        oid = ObjectID.for_put(self.current_task_id(), next(self._put_counter))
        self.refcount.add_owned_object(oid)
        if isinstance(value, TaskError):
            self.memory_store.put(oid, value, is_exception=True)
        else:
            self.memory_store.put(oid, value)
        return ObjectRef(oid)

    def submit_task(self, func: Callable, args: Sequence, kwargs: Dict,
                    num_returns: int = 1, resources=None, max_retries: int = 0,
                    retry_exceptions: bool = False, scheduling_strategy=None,
                    name: str = "", runtime_env=None) -> List[ObjectRef]:
        task_id = TaskID.for_task(ActorID.nil_for_job(self.job_id))
        return_ids = [ObjectID.for_task_return(task_id, i) for i in range(num_returns)]
        for oid in return_ids:
            self.refcount.add_owned_object(oid)
        refs = [ObjectRef(oid) for oid in return_ids]
        arg_refs = [a for a in list(args) + list(kwargs.values())
                    if isinstance(a, ObjectRef)]
        for r in arg_refs:
            self.refcount.add_submitted_task_ref(r.id())

        def run():
            self._execute_task(task_id, func, args, kwargs, return_ids,
                               max_retries, retry_exceptions, name or func.__name__)
            for r in arg_refs:
                self.refcount.remove_submitted_task_ref(r.id())

        self._pool.submit(run)
        return refs

    def _execute_task(self, task_id: TaskID, func, args, kwargs, return_ids,
                      max_retries: int, retry_exceptions: bool, name: str) -> None:
        attempt = 0
        while True:
            if task_id in self._cancelled:
                err = TaskCancelledError(task_id)
                for oid in return_ids:
                    self._put_return(oid, err, is_exception=True)
                return
            try:
                r_args, r_kwargs = self._resolve_args(args, kwargs)
                prev = getattr(_task_local, "task_id", _SENTINEL)
                _task_local.task_id = task_id
                try:
                    result = func(*r_args, **r_kwargs)
                finally:
                    _restore_task_local("task_id", prev)
                self._store_results(result, return_ids)
                return
            except TaskError as te:
                # Dependency failed: propagate as-is, never retry here
                for oid in return_ids:
                    self._put_return(oid, te, is_exception=True)
                return
            except BaseException as e:  # noqa: BLE001
                attempt += 1
                if retry_exceptions and attempt <= max_retries:
                    time.sleep(cfg.task_retry_delay_ms / 1000.0)
                    continue
                err = capture_exception(e)
                for oid in return_ids:
                    self._put_return(oid, err, is_exception=True)
                return

    def _put_return(self, oid: ObjectID, value, is_exception: bool = False) -> None:
        """Store a task result; reclaim immediately if every ref was dropped
        before completion (fire-and-forget tasks must not leak results)."""
        self.memory_store.put(oid, value, is_exception=is_exception)
        if not self.refcount.is_in_scope(oid):
            self.memory_store.delete([oid])

    def _store_results(self, result, return_ids: List[ObjectID]) -> None:
        n = len(return_ids)
        if n == 0:
            return
        if n == 1:
            self._put_return(return_ids[0], result)
            return
        vals = list(result) if isinstance(result, (tuple, list)) else [result]
        if len(vals) != n:
            err = capture_exception(
                ValueError(f"task declared {n} returns but produced {len(vals)}")
            )
            for oid in return_ids:
                self._put_return(oid, err, is_exception=True)
            return
        for oid, v in zip(return_ids, vals):
            self._put_return(oid, v)

    def _resolve_args(self, args, kwargs):
        """Inline ObjectRef args with their values (raises if a dep failed)."""

        def res(a):
            if isinstance(a, ObjectRef):
                rec = self.memory_store.get([a.id()])[0]
                if rec.is_exception:
                    raise rec.value
                return rec.value
            return a

        return [res(a) for a in args], {k: res(v) for k, v in kwargs.items()}

    # ------------------------------------------------------------------ get/wait

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() expects ObjectRef, got {type(r).__name__}")
        recs = self.memory_store.get([r.id() for r in ref_list], timeout)
        out = []
        for rec in recs:
            if rec.is_exception:
                raise rec.value
            out.append(rec.value)
        return out[0] if single else out

    def wait(self, refs: List[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True):
        if len(set(r.id() for r in refs)) != len(refs):
            raise ValueError("wait() requires unique object refs")
        ready_ids = self.memory_store.wait([r.id() for r in refs], num_returns, timeout)
        ready, not_ready = [], []
        for r in refs:
            (ready if r.id() in ready_ids and len(ready) < num_returns
             else not_ready).append(r)
        return ready, not_ready

    def cancel(self, ref: ObjectRef, force: bool = False, recursive: bool = True):
        self._cancelled.add(ref.id().task_id())

    # ------------------------------------------------------------------ actors

    def create_actor(self, cls, args, kwargs, *, name: Optional[str] = None,
                     namespace: str = "default", max_concurrency: int = 1,
                     max_restarts: int = 0, max_task_retries: int = 0,
                     resources=None, lifetime=None,
                     scheduling_strategy=None, get_if_exists: bool = False,
                     runtime_env=None, release_resources: bool = False,
                     concurrency_groups=None,
                     allow_out_of_order_execution: bool = False
                     ) -> "ActorID":
        # Local mode runs every method on one pool; concurrency groups
        # only isolate executors in cluster workers.
        import inspect

        is_async = any(
            inspect.iscoroutinefunction(m)
            for _, m in inspect.getmembers(cls, inspect.isfunction)
        )
        if is_async and max_concurrency == 1:
            max_concurrency = 1000  # async actors default to high concurrency

        actor_id = ActorID.of(self.job_id)
        state = _ActorState(actor_id, name, max_concurrency, max_restarts)
        state.cls, state.init_args, state.init_kwargs = cls, tuple(args), dict(kwargs)
        state.is_async = is_async
        # Name reservation and actor registration are one atomic step so
        # concurrent creates with the same name cannot both win.
        with self._actors_lock:
            if name is not None:
                key = (namespace, name)
                if key in self._named_actors:
                    if get_if_exists:
                        return self._named_actors[key]
                    raise ValueError(f"actor name '{name}' already taken")
                self._named_actors[key] = actor_id
            self._actors[actor_id] = state

        if state.is_async:
            self._start_actor_loop(state)

        def init():
            try:
                r_args, r_kwargs = self._resolve_args(state.init_args, state.init_kwargs)
                prev = getattr(_task_local, "actor_id", _SENTINEL)
                _task_local.actor_id = actor_id
                try:
                    state.instance = cls(*r_args, **r_kwargs)
                finally:
                    _restore_task_local("actor_id", prev)
            except BaseException as e:  # noqa: BLE001
                state.dead = True
                state.death_reason = f"__init__ failed: {e!r}"

        state.pool.submit(init).result()  # creation is synchronous locally
        if state.dead:
            with self._actors_lock:
                if name is not None:
                    self._named_actors.pop((namespace, name), None)
                self._actors.pop(actor_id, None)
            raise ActorDiedError(actor_id, state.death_reason)
        return actor_id

    def _start_actor_loop(self, state: _ActorState) -> None:
        import asyncio

        ready = threading.Event()

        def run_loop():
            loop = asyncio.new_event_loop()
            state.loop = loop
            asyncio.set_event_loop(loop)
            ready.set()
            loop.run_forever()

        t = threading.Thread(target=run_loop, daemon=True,
                             name=f"actor-loop-{state.actor_id.hex()[:8]}")
        t.start()
        ready.wait()

    def submit_actor_task(self, actor_id: ActorID, method_name: str, args, kwargs,
                          num_returns: int = 1) -> List[ObjectRef]:
        state = self._actors.get(actor_id)
        task_id = TaskID.for_task(actor_id)
        return_ids = [ObjectID.for_task_return(task_id, i) for i in range(num_returns)]
        for oid in return_ids:
            self.refcount.add_owned_object(oid)
        refs = [ObjectRef(oid) for oid in return_ids]
        if state is None or state.dead:
            err = ActorDiedError(actor_id,
                                 state.death_reason if state else "unknown actor")
            for oid in return_ids:
                self._put_return(oid, err, is_exception=True)
            return refs

        with state.pending_lock:
            state.pending_returns[task_id] = return_ids

        def finish_pending():
            with state.pending_lock:
                state.pending_returns.pop(task_id, None)

        def run():
            still_pending = False
            if state.dead:
                finish_pending()
                err = ActorDiedError(actor_id, state.death_reason)
                for oid in return_ids:
                    self._put_return(oid, err, is_exception=True)
                return
            try:
                r_args, r_kwargs = self._resolve_args(args, kwargs)
                method = getattr(state.instance, method_name)
                import inspect

                if inspect.iscoroutinefunction(method):
                    # Run on the actor's event loop without holding a pool
                    # thread: concurrent awaits interleave like on a cluster.
                    import asyncio

                    fut = asyncio.run_coroutine_threadsafe(
                        method(*r_args, **r_kwargs), state.loop
                    )

                    def _done(f):
                        try:
                            self._store_results(f.result(), return_ids)
                        except BaseException as e:  # noqa: BLE001
                            err = capture_exception(e)
                            for oid in return_ids:
                                self._put_return(oid, err, is_exception=True)
                        finally:
                            finish_pending()

                    fut.add_done_callback(_done)
                    # The call stays pending until the coroutine resolves —
                    # _done owns finish_pending(); the sync path's finally
                    # below must not drain it while the coroutine is in
                    # flight (kill() could then never fail these refs and a
                    # concurrent get() would hang forever).
                    still_pending = True
                    return
                prev_task = getattr(_task_local, "task_id", _SENTINEL)
                prev_actor = getattr(_task_local, "actor_id", _SENTINEL)
                _task_local.task_id = task_id
                _task_local.actor_id = actor_id
                try:
                    if state.max_concurrency == 1:
                        with state.lock:
                            result = method(*r_args, **r_kwargs)
                    else:
                        result = method(*r_args, **r_kwargs)
                finally:
                    _restore_task_local("task_id", prev_task)
                    _restore_task_local("actor_id", prev_actor)
                self._store_results(result, return_ids)
            except BaseException as e:  # noqa: BLE001
                from ray_tpu.exceptions import RayTpuError

                err = e if isinstance(e, RayTpuError) else capture_exception(e)
                for oid in return_ids:
                    self._put_return(oid, err, is_exception=True)
            finally:
                if not still_pending:
                    finish_pending()

        if method_name == "__ray_terminate__":
            finish_pending()
            self._kill_actor(actor_id, "terminated by user")
            for oid in return_ids:
                self._put_return(oid, None)
            return refs
        try:
            state.pool.submit(run)
        except RuntimeError:
            # Pool shut down by a concurrent kill — fail the refs, don't raise.
            finish_pending()
            err = ActorDiedError(actor_id, state.death_reason or "actor killed")
            for oid in return_ids:
                self._put_return(oid, err, is_exception=True)
        return refs

    def get_actor(self, name: str, namespace: str = "default") -> ActorID:
        with self._actors_lock:
            key = (namespace, name)
            if key not in self._named_actors:
                raise ValueError(f"no actor named '{name}' in namespace '{namespace}'")
            return self._named_actors[key]

    def actor_class_of(self, actor_id: ActorID):
        state = self._actors.get(actor_id)
        return state.cls if state else None

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self._kill_actor(actor_id, "killed via ray_tpu.kill")

    def _kill_actor(self, actor_id: ActorID, reason: str) -> None:
        with self._actors_lock:
            state = self._actors.get(actor_id)
            if state is None:
                return
            state.dead = True
            state.death_reason = reason
            if state.name is not None:
                for k in [k for k, v in self._named_actors.items() if v == actor_id]:
                    self._named_actors.pop(k, None)
            if state.loop is not None:
                state.loop.call_soon_threadsafe(state.loop.stop)
        state.pool.shutdown(wait=False, cancel_futures=True)
        # Queued calls were cancelled before storing anything; fail their
        # return objects so pending get()s resolve with ActorDiedError.
        # (_put_return keeps the first value, so a call that actually finished
        # concurrently wins over this error.)
        with state.pending_lock:
            pending = [oid for oids in state.pending_returns.values() for oid in oids]
            state.pending_returns.clear()
        err = ActorDiedError(actor_id, reason)
        for oid in pending:
            self._put_return(oid, err, is_exception=True)

    def list_actors(self):
        with self._actors_lock:
            return [
                {"actor_id": a.hex(), "name": s.name, "dead": s.dead,
                 "class": s.cls.__name__ if s.cls else None}
                for a, s in self._actors.items()
            ]

    # ------------------------------------------------------------------ pgs

    def create_placement_group(self, spec: PlacementGroupSpec) -> None:
        self._pgs[spec.pg_id] = spec

    def placement_group_ready(self, pg_id: PlacementGroupID, timeout=None) -> bool:
        return pg_id in self._pgs

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        self._pgs.pop(pg_id, None)

    def placement_group_table(self):
        return {pg.hex(): {"state": "CREATED", "bundles": [b.resources.to_dict()
                                                           for b in spec.bundles],
                           "strategy": spec.strategy, "name": spec.name}
                for pg, spec in self._pgs.items()}

    # ------------------------------------------------------------------ misc

    def nodes(self):
        from ray_tpu.core.resources import detect_node_resources

        nr = detect_node_resources()
        return [{"node_id": self.node_id.hex(), "alive": True,
                 "resources": nr.total.to_dict(), "labels": nr.labels,
                 "address": "local"}]

    def cluster_resources(self) -> Dict[str, float]:
        return self.nodes()[0]["resources"]

    def available_resources(self) -> Dict[str, float]:
        return self.cluster_resources()

    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        for actor_id in list(self._actors):
            self._kill_actor(actor_id, "runtime shutdown")
        self._pool.shutdown(wait=False, cancel_futures=True)
        runtime_context.set_runtime(None)
