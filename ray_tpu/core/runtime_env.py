"""Runtime environments: per-task/actor worker process environments.

Parity target: the reference's runtime_env system
(reference: python/ray/_private/runtime_env/working_dir.py, pip.py,
py_executable plugin, runtime_env/agent/runtime_env_agent.py, and the
per-env worker pools keyed by runtime_env_hash in
src/ray/raylet/worker_pool.h), re-designed small:

- supported fields: ``env_vars`` (dict str->str), ``working_dir`` (local
  path the worker chdirs into), ``py_modules`` (local paths prepended to
  the worker's PYTHONPATH), ``pip`` (package list / options dict — the
  node materializes a CACHED venv per requirements fingerprint and spawns
  the worker from its interpreter), ``py_executable`` (explicit worker
  interpreter path)
- the env is validated AT OPTION TIME and anything unsupported raises —
  silently accepting a correctness-relevant option is worse than not
  having it
- a canonical fingerprint rides the scheduling key and the lease request,
  so leases and idle-pool workers are only ever reused within the SAME
  runtime env (two envs never share a worker process)
- pip venvs live under ``RTPU_RUNTIME_ENV_DIR`` (default
  /tmp/ray_tpu/runtime_envs), keyed by the requirements hash — the
  reference's URI cache role: N tasks with one env pay one install

working_dir/py_modules are local/shared-filesystem paths: in-cluster
workers resolve them directly (the reference uploads to GCS for remote
clusters; this runtime's nodes share a host or a filesystem).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Optional

_SUPPORTED = {"env_vars", "working_dir", "py_modules", "pip", "uv",
              "conda", "py_executable"}
_ENV_CACHE_DIR_VAR = "RTPU_RUNTIME_ENV_DIR"
_DEFAULT_ENV_CACHE = "/tmp/ray_tpu/runtime_envs"


def validate_runtime_env(env: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Normalize + validate; returns a canonical dict or None. Raises
    ValueError on unsupported fields or malformed values."""
    if env is None:
        return None
    if not isinstance(env, dict):
        raise ValueError(f"runtime_env must be a dict, got {type(env).__name__}")
    unknown = set(env) - _SUPPORTED
    if unknown:
        raise ValueError(
            f"unsupported runtime_env field(s) {sorted(unknown)}; "
            f"supported: {sorted(_SUPPORTED)}")
    out: Dict[str, Any] = {}
    ev = env.get("env_vars")
    if ev is not None:
        if not isinstance(ev, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in ev.items()):
            raise ValueError("runtime_env['env_vars'] must be Dict[str, str]")
        out["env_vars"] = dict(sorted(ev.items()))
    wd = env.get("working_dir")
    if wd is not None:
        if not isinstance(wd, str):
            raise ValueError("runtime_env['working_dir'] must be a path str")
        out["working_dir"] = os.path.abspath(wd)
    pm = env.get("py_modules")
    if pm is not None:
        if not isinstance(pm, (list, tuple)) or not all(
                isinstance(p, str) for p in pm):
            raise ValueError("runtime_env['py_modules'] must be a list of "
                             "path strings")
        out["py_modules"] = [os.path.abspath(p) for p in pm]
    pip = env.get("pip")
    if pip is not None:
        # List form: ["pkg==1.0", ...]. Dict form adds installer options
        # (find_links/no_index for offline/local-wheel installs).
        if isinstance(pip, (list, tuple)):
            pip = {"packages": list(pip)}
        if not isinstance(pip, dict) or not isinstance(
                pip.get("packages"), (list, tuple)) or not all(
                isinstance(p, str) for p in pip["packages"]):
            raise ValueError(
                "runtime_env['pip'] must be a list of requirement strings "
                "or {'packages': [...], 'find_links': path, "
                "'no_index': bool}")
        unknown_pip = set(pip) - {"packages", "find_links", "no_index"}
        if unknown_pip:
            # Same invariant as top-level fields: a silently-dropped
            # option would also alias distinct envs onto one cached venv.
            raise ValueError(
                f"unsupported pip option(s) {sorted(unknown_pip)}; "
                f"supported: packages, find_links, no_index")
        norm = {"packages": sorted(pip["packages"])}
        if pip.get("find_links") is not None:
            norm["find_links"] = os.path.abspath(str(pip["find_links"]))
        if pip.get("no_index"):
            norm["no_index"] = True
        out["pip"] = norm
    uv = env.get("uv")
    if uv is not None:
        # Same shape as pip (reference: runtime_env/uv.py — uv is a
        # drop-in faster installer over the same venv model).
        if isinstance(uv, (list, tuple)):
            uv = {"packages": list(uv)}
        if not isinstance(uv, dict) or not isinstance(
                uv.get("packages"), (list, tuple)) or not all(
                isinstance(p, str) for p in uv["packages"]):
            raise ValueError(
                "runtime_env['uv'] must be a list of requirement strings "
                "or {'packages': [...], 'find_links': path, "
                "'no_index': bool}")
        unknown_uv = set(uv) - {"packages", "find_links", "no_index"}
        if unknown_uv:
            raise ValueError(
                f"unsupported uv option(s) {sorted(unknown_uv)}; "
                f"supported: packages, find_links, no_index")
        norm = {"packages": sorted(uv["packages"])}
        if uv.get("find_links") is not None:
            norm["find_links"] = os.path.abspath(str(uv["find_links"]))
        if uv.get("no_index"):
            norm["no_index"] = True
        out["uv"] = norm
    conda = env.get("conda")
    if conda is not None:
        # A named pre-existing env, or an environment.yml-style dict
        # (reference: runtime_env/conda.py — name vs dict spec).
        if isinstance(conda, str):
            out["conda"] = conda
        elif isinstance(conda, dict):
            try:
                out["conda"] = json.loads(json.dumps(conda, sort_keys=True))
            except (TypeError, ValueError):
                raise ValueError(
                    "runtime_env['conda'] dict must be JSON-serializable")
        else:
            raise ValueError(
                "runtime_env['conda'] must be an env name or an "
                "environment dict")
    pyx = env.get("py_executable")
    if pyx is not None:
        if not isinstance(pyx, str):
            raise ValueError("runtime_env['py_executable'] must be a path")
        out["py_executable"] = os.path.abspath(pyx)
    interp_sources = [k for k in ("pip", "uv", "conda", "py_executable")
                      if out.get(k) is not None]
    if len(interp_sources) > 1:
        raise ValueError(
            f"{interp_sources} are mutually exclusive: each selects the "
            f"worker interpreter")
    return out or None


def runtime_env_hash(env: Optional[Dict[str, Any]]) -> str:
    """Stable fingerprint for worker-pool keying ('' = default env)."""
    if not env:
        return ""
    return hashlib.sha1(
        json.dumps(env, sort_keys=True).encode()).hexdigest()[:16]


def apply_to_spawn_env(env: Optional[Dict[str, Any]],
                       spawn_env: Dict[str, str]) -> Optional[str]:
    """Mutates a worker spawn environment in place; returns the cwd to
    spawn with (None = inherit)."""
    if not env:
        return None
    for k, v in (env.get("env_vars") or {}).items():
        spawn_env[k] = v
    for p in reversed(env.get("py_modules") or ()):
        spawn_env["PYTHONPATH"] = p + os.pathsep + spawn_env.get(
            "PYTHONPATH", "")
    if any(env.get(k) for k in ("pip", "uv", "conda", "py_executable")):
        # A non-default interpreter must still import ray_tpu: the repo
        # root rides PYTHONPATH (venvs use --system-site-packages for the
        # baked-in deps, but ray_tpu itself may be path-imported).
        import ray_tpu as _pkg

        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(_pkg.__file__)))
        spawn_env["PYTHONPATH"] = (
            repo_root + os.pathsep + spawn_env.get("PYTHONPATH", ""))
    return env.get("working_dir")


def needs_materialization(env: Optional[Dict[str, Any]]) -> bool:
    """True when worker spawn requires building state first (pip/uv venv,
    conda env)."""
    return bool(env and (env.get("pip") or env.get("uv")
                         or env.get("conda")))


def resolve_python_executable(env: Optional[Dict[str, Any]]) -> Optional[str]:
    """The interpreter the worker should spawn with, materializing the
    pip venv on first use (reference: pip.py's virtualenv-per-URI with the
    agent's cache; None = the node's own interpreter). Creation is
    CACHED per requirements fingerprint and concurrency-safe via an
    atomic rename: parallel spawns of one env pay one install."""
    if not env:
        return None
    if env.get("py_executable"):
        return env["py_executable"]
    if env.get("uv"):
        return _materialize_uv(env["uv"])
    if env.get("conda"):
        return _materialize_conda(env["conda"])
    pip = env.get("pip")
    if not pip:
        return None

    def build_pip(target: str) -> None:
        import subprocess
        import sys

        subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages",
             target], check=True, capture_output=True, timeout=300)
        _link_parent_site_packages(target)
        if not pip["packages"]:  # empty = bare isolated venv, no install
            return
        cmd = [os.path.join(target, "bin", "python"), "-m", "pip",
               "install", "--quiet", "--disable-pip-version-check"]
        if pip.get("no_index"):
            cmd.append("--no-index")
        if pip.get("find_links"):
            cmd += ["--find-links", pip["find_links"]]
        cmd += list(pip["packages"])
        proc = subprocess.run(cmd, capture_output=True, timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(
                f"pip install for runtime_env failed: "
                f"{proc.stderr.decode(errors='replace')[-800:]}")

    return _materialize_cached("pip", pip, build_pip)


def _materialize_cached(prefix: str, key_obj, build_fn) -> str:
    """The one copy of the cache-probe / build / atomic-publish / loser-
    cleanup protocol every interpreter source shares. ``build_fn(target)``
    materializes an environment into ``target`` (a fresh path that does
    NOT yet exist — venv and `conda env create -p` both require that).
    Concurrency-safe: each builder works in its own temp parent; the
    rename into the cache slot is atomic and losers discard their build."""
    import shutil
    import tempfile

    key = hashlib.sha1(json.dumps(key_obj, sort_keys=True).encode()) \
        .hexdigest()[:16]
    cache_root = os.environ.get(_ENV_CACHE_DIR_VAR, _DEFAULT_ENV_CACHE)
    final = os.path.join(cache_root, f"{prefix}-{key}")
    python = os.path.join(final, "bin", "python")
    if os.path.exists(python):
        return python
    os.makedirs(cache_root, exist_ok=True)
    parent = tempfile.mkdtemp(prefix=f"{prefix}-{key}-", dir=cache_root)
    target = os.path.join(parent, "env")
    try:
        build_fn(target)
        try:
            os.rename(target, final)  # atomic publish
        except OSError:
            if not os.path.exists(python):
                # Rename failed for a reason OTHER than losing the race:
                # serve from the private build rather than failing.
                return os.path.join(target, "bin", "python")
        shutil.rmtree(parent, ignore_errors=True)
        return python
    except Exception:
        shutil.rmtree(parent, ignore_errors=True)
        raise


def _link_parent_site_packages(venv_dir: str) -> None:
    """The node's interpreter may ITSELF be a venv: --system-site-packages
    then exposes the BASE python's site dir, not the node's (where
    jax/cloudpickle/... actually live). Link the node's site-packages via
    a .pth — appended AFTER the new venv's own site dir on sys.path, so
    per-env installed versions still override."""
    import sys

    site_dir = os.path.join(
        venv_dir, "lib",
        f"python{sys.version_info.major}.{sys.version_info.minor}",
        "site-packages")
    parent_sites = [p for p in __import__("site").getsitepackages()
                    if os.path.isdir(p)]
    with open(os.path.join(site_dir, "_rtpu_parent_site.pth"), "w") as f:
        f.write("\n".join(parent_sites) + "\n")


def _find_tool(kind: str, names) -> str:
    """Locate an installer binary; ``RTPU_<KIND>_BIN`` overrides (also the
    test seam — this image ships neither uv nor conda, mirroring how the
    reference's conda tests stub the binary)."""
    import shutil as _shutil

    override = os.environ.get(f"RTPU_{kind.upper()}_BIN")
    if override:
        return override
    for name in names:
        path = _shutil.which(name)
        if path:
            return path
    raise RuntimeError(
        f"runtime_env['{kind}'] requires a {kind} executable on PATH "
        f"(or RTPU_{kind.upper()}_BIN); none of {list(names)} found")


def _materialize_uv(uv: Dict[str, Any]) -> str:
    """uv-built venv, cached per requirements fingerprint (reference:
    runtime_env/uv.py). Shares the pip path's publish protocol."""
    uv_bin = _find_tool("uv", ("uv",))

    def build_uv(target: str) -> None:
        import subprocess
        import sys

        subprocess.run(
            [uv_bin, "venv", "--system-site-packages",
             "--python", sys.executable, target],
            check=True, capture_output=True, timeout=300)
        _link_parent_site_packages(target)
        if not uv["packages"]:  # empty = bare isolated venv, no install
            return
        cmd = [uv_bin, "pip", "install", "--python",
               os.path.join(target, "bin", "python")]
        if uv.get("no_index"):
            cmd.append("--no-index")
        if uv.get("find_links"):
            cmd += ["--find-links", uv["find_links"]]
        cmd += list(uv["packages"])
        proc = subprocess.run(cmd, capture_output=True, timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(
                f"uv install for runtime_env failed: "
                f"{proc.stderr.decode(errors='replace')[-800:]}")

    return _materialize_cached("uv", uv, build_uv)


#: name -> interpreter path; `conda run` costs seconds per invocation and
#: resolve_python_executable runs per worker spawn.
_named_conda_cache: Dict[str, str] = {}


def _materialize_conda(conda) -> str:
    """Conda env interpreter (reference: runtime_env/conda.py). A string
    names a PRE-EXISTING env (resolved once via `conda run`, memoized); a
    dict is an environment spec created as a cached prefix env."""
    import subprocess
    import tempfile

    conda_bin = _find_tool("conda", ("conda", "mamba", "micromamba"))
    if isinstance(conda, str):
        cached = _named_conda_cache.get(conda)
        if cached is not None:
            return cached
        proc = subprocess.run(
            [conda_bin, "run", "-n", conda, "python", "-c",
             "import sys; print(sys.executable)"],
            capture_output=True, timeout=120)
        if proc.returncode != 0:
            raise RuntimeError(
                f"conda env {conda!r} resolution failed: "
                f"{proc.stderr.decode(errors='replace')[-400:]}")
        lines = proc.stdout.decode().strip().splitlines()
        path = lines[-1] if lines else ""
        if not path:
            raise RuntimeError(f"conda env {conda!r}: empty interpreter")
        _named_conda_cache[conda] = path
        return path

    def build_conda(target: str) -> None:
        with tempfile.NamedTemporaryFile(
                "w", suffix=".yml", delete=False) as f:
            json.dump(conda, f)
            spec_path = f.name
        try:
            proc = subprocess.run(
                [conda_bin, "env", "create", "-p", target, "-f",
                 spec_path], capture_output=True, timeout=900)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"conda env create failed: "
                    f"{proc.stderr.decode(errors='replace')[-800:]}")
        finally:
            try:
                os.unlink(spec_path)
            except OSError:
                pass

    return _materialize_cached("conda", conda, build_conda)
