"""Runtime environments: per-task/actor worker process environments.

Parity target: the reference's runtime_env system
(reference: python/ray/_private/runtime_env/working_dir.py, pip.py,
py_executable plugin, runtime_env/agent/runtime_env_agent.py, and the
per-env worker pools keyed by runtime_env_hash in
src/ray/raylet/worker_pool.h), re-designed small:

- supported fields: ``env_vars`` (dict str->str), ``working_dir`` (local
  path the worker chdirs into), ``py_modules`` (local paths prepended to
  the worker's PYTHONPATH), ``pip`` (package list / options dict — the
  node materializes a CACHED venv per requirements fingerprint and spawns
  the worker from its interpreter), ``py_executable`` (explicit worker
  interpreter path)
- the env is validated AT OPTION TIME and anything unsupported raises —
  silently accepting a correctness-relevant option is worse than not
  having it
- a canonical fingerprint rides the scheduling key and the lease request,
  so leases and idle-pool workers are only ever reused within the SAME
  runtime env (two envs never share a worker process)
- pip venvs live under ``RTPU_RUNTIME_ENV_DIR`` (default
  /tmp/ray_tpu/runtime_envs), keyed by the requirements hash — the
  reference's URI cache role: N tasks with one env pay one install

working_dir/py_modules are local/shared-filesystem paths: in-cluster
workers resolve them directly (the reference uploads to GCS for remote
clusters; this runtime's nodes share a host or a filesystem).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Optional

_SUPPORTED = {"env_vars", "working_dir", "py_modules", "pip",
              "py_executable"}
_ENV_CACHE_DIR_VAR = "RTPU_RUNTIME_ENV_DIR"
_DEFAULT_ENV_CACHE = "/tmp/ray_tpu/runtime_envs"


def validate_runtime_env(env: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Normalize + validate; returns a canonical dict or None. Raises
    ValueError on unsupported fields or malformed values."""
    if env is None:
        return None
    if not isinstance(env, dict):
        raise ValueError(f"runtime_env must be a dict, got {type(env).__name__}")
    unknown = set(env) - _SUPPORTED
    if unknown:
        raise ValueError(
            f"unsupported runtime_env field(s) {sorted(unknown)}; "
            f"supported: {sorted(_SUPPORTED)}")
    out: Dict[str, Any] = {}
    ev = env.get("env_vars")
    if ev is not None:
        if not isinstance(ev, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in ev.items()):
            raise ValueError("runtime_env['env_vars'] must be Dict[str, str]")
        out["env_vars"] = dict(sorted(ev.items()))
    wd = env.get("working_dir")
    if wd is not None:
        if not isinstance(wd, str):
            raise ValueError("runtime_env['working_dir'] must be a path str")
        out["working_dir"] = os.path.abspath(wd)
    pm = env.get("py_modules")
    if pm is not None:
        if not isinstance(pm, (list, tuple)) or not all(
                isinstance(p, str) for p in pm):
            raise ValueError("runtime_env['py_modules'] must be a list of "
                             "path strings")
        out["py_modules"] = [os.path.abspath(p) for p in pm]
    pip = env.get("pip")
    if pip is not None:
        # List form: ["pkg==1.0", ...]. Dict form adds installer options
        # (find_links/no_index for offline/local-wheel installs).
        if isinstance(pip, (list, tuple)):
            pip = {"packages": list(pip)}
        if not isinstance(pip, dict) or not isinstance(
                pip.get("packages"), (list, tuple)) or not all(
                isinstance(p, str) for p in pip["packages"]):
            raise ValueError(
                "runtime_env['pip'] must be a list of requirement strings "
                "or {'packages': [...], 'find_links': path, "
                "'no_index': bool}")
        unknown_pip = set(pip) - {"packages", "find_links", "no_index"}
        if unknown_pip:
            # Same invariant as top-level fields: a silently-dropped
            # option would also alias distinct envs onto one cached venv.
            raise ValueError(
                f"unsupported pip option(s) {sorted(unknown_pip)}; "
                f"supported: packages, find_links, no_index")
        norm = {"packages": sorted(pip["packages"])}
        if pip.get("find_links") is not None:
            norm["find_links"] = os.path.abspath(str(pip["find_links"]))
        if pip.get("no_index"):
            norm["no_index"] = True
        out["pip"] = norm
    pyx = env.get("py_executable")
    if pyx is not None:
        if not isinstance(pyx, str):
            raise ValueError("runtime_env['py_executable'] must be a path")
        if env.get("pip") is not None:
            raise ValueError("py_executable and pip are mutually "
                             "exclusive (pip builds its own interpreter)")
        out["py_executable"] = os.path.abspath(pyx)
    return out or None


def runtime_env_hash(env: Optional[Dict[str, Any]]) -> str:
    """Stable fingerprint for worker-pool keying ('' = default env)."""
    if not env:
        return ""
    return hashlib.sha1(
        json.dumps(env, sort_keys=True).encode()).hexdigest()[:16]


def apply_to_spawn_env(env: Optional[Dict[str, Any]],
                       spawn_env: Dict[str, str]) -> Optional[str]:
    """Mutates a worker spawn environment in place; returns the cwd to
    spawn with (None = inherit)."""
    if not env:
        return None
    for k, v in (env.get("env_vars") or {}).items():
        spawn_env[k] = v
    for p in reversed(env.get("py_modules") or ()):
        spawn_env["PYTHONPATH"] = p + os.pathsep + spawn_env.get(
            "PYTHONPATH", "")
    if env.get("pip") or env.get("py_executable"):
        # A non-default interpreter must still import ray_tpu: the repo
        # root rides PYTHONPATH (venvs use --system-site-packages for the
        # baked-in deps, but ray_tpu itself may be path-imported).
        import ray_tpu as _pkg

        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(_pkg.__file__)))
        spawn_env["PYTHONPATH"] = (
            repo_root + os.pathsep + spawn_env.get("PYTHONPATH", ""))
    return env.get("working_dir")


def needs_materialization(env: Optional[Dict[str, Any]]) -> bool:
    """True when worker spawn requires building state first (pip venv)."""
    return bool(env and env.get("pip"))


def resolve_python_executable(env: Optional[Dict[str, Any]]) -> Optional[str]:
    """The interpreter the worker should spawn with, materializing the
    pip venv on first use (reference: pip.py's virtualenv-per-URI with the
    agent's cache; None = the node's own interpreter). Creation is
    CACHED per requirements fingerprint and concurrency-safe via an
    atomic rename: parallel spawns of one env pay one install."""
    if not env:
        return None
    if env.get("py_executable"):
        return env["py_executable"]
    pip = env.get("pip")
    if not pip:
        return None
    import subprocess
    import sys
    import tempfile

    key = hashlib.sha1(json.dumps(pip, sort_keys=True).encode()) \
        .hexdigest()[:16]
    cache_root = os.environ.get(_ENV_CACHE_DIR_VAR, _DEFAULT_ENV_CACHE)
    final = os.path.join(cache_root, f"pip-{key}")
    python = os.path.join(final, "bin", "python")
    if os.path.exists(python):
        return python
    os.makedirs(cache_root, exist_ok=True)
    build = tempfile.mkdtemp(prefix=f"pip-{key}-", dir=cache_root)
    try:
        subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages",
             build], check=True, capture_output=True, timeout=300)
        # The node's interpreter may ITSELF be a venv: --system-site-
        # packages then exposes the BASE python's site dir, not the
        # node's (where jax/cloudpickle/... actually live). Link the
        # node's site-packages via a .pth — appended AFTER the new
        # venv's own site dir on sys.path, so per-env installed versions
        # still override.
        site_dir = os.path.join(
            build, "lib",
            f"python{sys.version_info.major}.{sys.version_info.minor}",
            "site-packages")
        parent_sites = [p for p in __import__("site").getsitepackages()
                        if os.path.isdir(p)]
        with open(os.path.join(site_dir, "_rtpu_parent_site.pth"),
                  "w") as f:
            f.write("\n".join(parent_sites) + "\n")
        cmd = [os.path.join(build, "bin", "python"), "-m", "pip",
               "install", "--quiet", "--disable-pip-version-check"]
        if pip.get("no_index"):
            cmd.append("--no-index")
        if pip.get("find_links"):
            cmd += ["--find-links", pip["find_links"]]
        cmd += list(pip["packages"])
        proc = subprocess.run(cmd, capture_output=True, timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(
                f"pip install for runtime_env failed: "
                f"{proc.stderr.decode(errors='replace')[-800:]}")
        try:
            os.rename(build, final)  # atomic publish
        except OSError:
            # A concurrent builder won the rename: use theirs, drop ours.
            if os.path.exists(python):
                import shutil

                shutil.rmtree(build, ignore_errors=True)
            else:
                return os.path.join(build, "bin", "python")
        return python
    except Exception:
        import shutil

        shutil.rmtree(build, ignore_errors=True)
        raise
