"""Runtime environments: per-task/actor worker process environments.

Parity target: the reference's runtime_env system
(reference: python/ray/_private/runtime_env/working_dir.py,
runtime_env/agent/runtime_env_agent.py, and the per-env worker pools keyed
by runtime_env_hash in src/ray/raylet/worker_pool.h), re-designed small:

- supported fields: ``env_vars`` (dict str->str), ``working_dir`` (local
  path the worker chdirs into), ``py_modules`` (local paths prepended to
  the worker's PYTHONPATH)
- the env is validated AT OPTION TIME and anything unsupported raises —
  silently accepting a correctness-relevant option is worse than not
  having it
- a canonical fingerprint rides the scheduling key and the lease request,
  so leases and idle-pool workers are only ever reused within the SAME
  runtime env (two envs never share a worker process)

working_dir/py_modules are local/shared-filesystem paths: in-cluster
workers resolve them directly (the reference uploads to GCS for remote
clusters; this runtime's nodes share a host or a filesystem).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Optional

_SUPPORTED = {"env_vars", "working_dir", "py_modules"}


def validate_runtime_env(env: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Normalize + validate; returns a canonical dict or None. Raises
    ValueError on unsupported fields or malformed values."""
    if env is None:
        return None
    if not isinstance(env, dict):
        raise ValueError(f"runtime_env must be a dict, got {type(env).__name__}")
    unknown = set(env) - _SUPPORTED
    if unknown:
        raise ValueError(
            f"unsupported runtime_env field(s) {sorted(unknown)}; "
            f"supported: {sorted(_SUPPORTED)}")
    out: Dict[str, Any] = {}
    ev = env.get("env_vars")
    if ev is not None:
        if not isinstance(ev, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in ev.items()):
            raise ValueError("runtime_env['env_vars'] must be Dict[str, str]")
        out["env_vars"] = dict(sorted(ev.items()))
    wd = env.get("working_dir")
    if wd is not None:
        if not isinstance(wd, str):
            raise ValueError("runtime_env['working_dir'] must be a path str")
        out["working_dir"] = os.path.abspath(wd)
    pm = env.get("py_modules")
    if pm is not None:
        if not isinstance(pm, (list, tuple)) or not all(
                isinstance(p, str) for p in pm):
            raise ValueError("runtime_env['py_modules'] must be a list of "
                             "path strings")
        out["py_modules"] = [os.path.abspath(p) for p in pm]
    return out or None


def runtime_env_hash(env: Optional[Dict[str, Any]]) -> str:
    """Stable fingerprint for worker-pool keying ('' = default env)."""
    if not env:
        return ""
    return hashlib.sha1(
        json.dumps(env, sort_keys=True).encode()).hexdigest()[:16]


def apply_to_spawn_env(env: Optional[Dict[str, Any]],
                       spawn_env: Dict[str, str]) -> Optional[str]:
    """Mutates a worker spawn environment in place; returns the cwd to
    spawn with (None = inherit)."""
    if not env:
        return None
    for k, v in (env.get("env_vars") or {}).items():
        spawn_env[k] = v
    for p in reversed(env.get("py_modules") or ()):
        spawn_env["PYTHONPATH"] = p + os.pathsep + spawn_env.get(
            "PYTHONPATH", "")
    return env.get("working_dir")
