"""Binary identifiers with embedded lineage.

Design parity with the reference's ID scheme (reference: src/ray/common/id.h),
re-designed rather than ported: IDs are flat ``bytes`` wrappers with lineage
*embedded by prefix* so that containment tests and owner extraction are O(1)
slices instead of table lookups:

    JobID   (4B)                         -- per driver/job
    ActorID (12B) = unique(8)  + job(4)  -- actor identity
    TaskID  (20B) = unique(8)  + actor(12)
    ObjectID(28B) = index(4)   + task(20) + flags(4)

(8 random bytes of task uniqueness: collision probability stays negligible at
billions of tasks; 4 bytes would hit birthday-bound collisions at ~10^4.)

So ``ObjectID.task_id()`` and ``TaskID.actor_id()`` are pure slicing, which the
lineage/ownership layers (ray_tpu/core/lineage.py, refcount.py) rely on in
their hot paths.  NodeID / WorkerID / PlacementGroupID are 16B random.
"""

from __future__ import annotations

import os
import threading
import struct

_rng_lock = threading.Lock()
_counter = 0

# Batched entropy: os.urandom is a syscall (~10us) and sits on the
# per-task hot path (one TaskID per submit). Refill 8KB at a time and
# slice; fork safety comes from re-keying on pid change (a forked child
# must not replay the parent's buffered entropy).
_rand_buf = b""
_rand_pos = 0
_rand_pid = -1


def _rand_bytes(n: int) -> bytes:
    global _rand_buf, _rand_pos, _rand_pid
    if n > 8192:
        return os.urandom(n)
    with _rng_lock:
        if _rand_pos + n > len(_rand_buf) or _rand_pid != os.getpid():
            _rand_buf = os.urandom(8192)
            _rand_pos = 0
            _rand_pid = os.getpid()
        out = _rand_buf[_rand_pos:_rand_pos + n]
        _rand_pos += n
        return out


def _next_counter() -> int:
    global _counter
    with _rng_lock:
        _counter += 1
        return _counter


class BaseID:
    """Immutable binary ID. Subclasses fix SIZE."""

    SIZE = 16
    __slots__ = ("_bytes", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._bytes = bytes(binary)
        self._hash = hash(self._bytes)

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(_rand_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __ne__(self, other):
        return not self.__eq__(other)

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, i: int) -> "JobID":
        return cls(struct.pack("<I", i))

    def int_value(self) -> int:
        return struct.unpack("<I", self._bytes)[0]


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class PlacementGroupID(BaseID):
    SIZE = 16


class ClusterID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    SIZE = 12
    UNIQUE = 8

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(_rand_bytes(cls.UNIQUE) + job_id.binary())

    @classmethod
    def nil_for_job(cls, job_id: JobID) -> "ActorID":
        return cls(b"\xff" * cls.UNIQUE + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[self.UNIQUE :])


class TaskID(BaseID):
    SIZE = 20
    UNIQUE = 8

    @classmethod
    def for_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(_rand_bytes(cls.UNIQUE) + actor_id.binary())

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        return cls.for_task(ActorID.nil_for_job(job_id))

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[self.UNIQUE :])

    def job_id(self) -> JobID:
        return self.actor_id().job_id()


# ObjectID flag bits (last 4 bytes, little-endian u32).
_FLAG_PUT = 0x1  # created by put() rather than a task return
_FLAG_STREAM = 0x2  # streaming-generator return


class ObjectID(BaseID):
    SIZE = 28
    _IDX = 4

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(struct.pack("<I", index) + task_id.binary() + struct.pack("<I", 0))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        return cls(
            struct.pack("<I", put_index) + task_id.binary() + struct.pack("<I", _FLAG_PUT)
        )

    @classmethod
    def for_stream_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        """The index-th yield of a streaming-generator task (reference:
        streaming-generator return refs, task_manager.h:212)."""
        return cls(struct.pack("<I", index) + task_id.binary()
                   + struct.pack("<I", _FLAG_STREAM))

    def is_stream(self) -> bool:
        return bool(self.flags() & _FLAG_STREAM)

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[self._IDX : self._IDX + TaskID.SIZE])

    def index(self) -> int:
        return struct.unpack("<I", self._bytes[: self._IDX])[0]

    def flags(self) -> int:
        return struct.unpack("<I", self._bytes[self._IDX + TaskID.SIZE :])[0]

    def is_put(self) -> bool:
        return bool(self.flags() & _FLAG_PUT)

    def created_by_task(self) -> bool:
        return not self.is_put()
