"""Process-global runtime holder + public runtime context.

Parity: python/ray/runtime_context.py (get_runtime_context) in the reference.
"""

from __future__ import annotations

import contextvars
import threading
from typing import Optional

_lock = threading.Lock()
_runtime = None

# Per-execution-context task info for cluster workers (task_id, actor_id,
# resources) — set by the worker's execution loop around user code. A
# ContextVar (not threading.local) so asyncio-actor coroutines interleaving
# on one event-loop thread each see their OWN task context.
_worker_ctx: "contextvars.ContextVar[Optional[dict]]" = (
    contextvars.ContextVar("rtpu_worker_ctx", default=None))


def current_worker_context() -> dict:
    return _worker_ctx.get() or {}


def set_worker_context(ctx: Optional[dict]):
    """Returns the previous context; pass it back to restore."""
    prev = _worker_ctx.get()
    _worker_ctx.set(ctx)
    return prev


def get_runtime():
    return _runtime


def set_runtime(rt) -> None:
    global _runtime
    with _lock:
        _runtime = rt


def require_runtime():
    rt = get_runtime()
    if rt is None:
        raise RuntimeError(
            "ray_tpu has not been initialized; call ray_tpu.init() first."
        )
    return rt


class RuntimeContext:
    """User-facing view of the current worker's runtime state."""

    def __init__(self, rt):
        self._rt = rt

    @property
    def job_id(self):
        return self._rt.job_id

    @property
    def node_id(self):
        return self._rt.node_id

    @property
    def worker_id(self):
        return self._rt.worker_id

    def get_task_id(self):
        return self._rt.current_task_id()

    def get_actor_id(self):
        return self._rt.current_actor_id()

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return getattr(self._rt, "actor_restart_count", 0) > 0

    def get_assigned_resources(self):
        return self._rt.current_resources()


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(require_runtime())
