"""Python client for the native shared-memory object store (ray_tpu/_cpp).

This is the per-node object plane. Parity target: the reference's plasma
client (reference: src/ray/object_manager/plasma/client.h — Create/Seal/Get/
Release/Delete over a unix-socket protocol), re-designed: here every process
maps the same POSIX shm segment and calls straight into the store library —
no store server, no socket round trip, zero-copy reads via memoryview into
the mapping. The segment is SHARDED (layout v2): per-shard process-shared
robust mutexes, slot stripes, and sub-arena free lists, with process-affine
allocation so concurrent writers neither serialize on one lock nor ping-pong
pages between each other's page tables (see shm_store.cc).

The creator process calls `ShmStore.create(...)`; workers `ShmStore.open(...)`
with the same name. Both sides then use identical put/get APIs.
"""

from __future__ import annotations

import ctypes
import os
import threading
import time
import weakref
from typing import Optional, Tuple

from ray_tpu.core.ids import ObjectID

from ray_tpu.devtools.lock_debug import make_lock as _make_lock

_LIB = None
_LIB_LOCK = _make_lock("shm_store._LIB_LOCK")

#: Expected shm segment layout version. MUST match kLayoutVersion in
#: shm_store.cc: the v2 layout shards the arena (per-shard mutexes, slot
#: stripes, sub-arena free lists), so a library built from older source
#: would corrupt a v2 segment — attach fails fast instead.
_LAYOUT_VERSION = 2


def _check_layout_version(lib, so: str) -> None:
    """Refuse a store library whose compiled-in layout disagrees with this
    client. A stale prebuilt .so (or an RTPU_SHM_STORE_SO override pointing
    at an old build) must fail LOUDLY at load, not corrupt the arena."""
    try:
        lib.rtpu_lib_layout_version.restype = ctypes.c_uint64
        got = int(lib.rtpu_lib_layout_version())
    except AttributeError:
        got = 1  # pre-versioning builds exported no version symbol
    if got != _LAYOUT_VERSION:
        override = os.environ.get("RTPU_SHM_STORE_SO")
        hint = (f" (RTPU_SHM_STORE_SO points at {override!r} — rebuild "
                "that file or unset the override)" if override else "")
        raise OSError(
            f"stale shm store library {so!r}: layout version {got}, "
            f"this client needs {_LAYOUT_VERSION}. Rebuild with "
            f"`python ray_tpu/_cpp/build.py`{hint}.")


def _load_lib():
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        # RTPU_SHM_STORE_SO points at an out-of-tree build of the store
        # library (e.g. one rebuilt for this machine's glibc) without
        # touching the checked-in binary; inherited by every spawned
        # head/node/worker process.
        so = os.environ.get("RTPU_SHM_STORE_SO") or ""
        if not so:
            here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            so = os.path.join(here, "_cpp", "libshm_store.so")
        if not os.path.exists(so):
            from ray_tpu._cpp.build import build

            build(verbose=False)
        try:
            lib = ctypes.CDLL(so)
            _check_layout_version(lib, so)
        except OSError as e:
            # The shipped .so was built against a different libc (e.g.
            # `GLIBC_2.33 not found`) or from pre-layout-bump source.
            # Rebuilding from the checked-in source fixes it, but only on
            # explicit request: an implicit rebuild here would race (every
            # node process dlopens this path — concurrent g++ runs into
            # one .so corrupt it).
            if os.environ.get("RTPU_REBUILD_NATIVE") != "1":
                raise OSError(
                    f"{e}\nThe prebuilt libshm_store.so does not match "
                    "this machine/source; rerun with RTPU_REBUILD_NATIVE=1 "
                    "(or run `python ray_tpu/_cpp/build.py`) to rebuild it "
                    "from source.") from e
            from ray_tpu._cpp.build import build

            build(verbose=False, force=True)
            lib = ctypes.CDLL(so)
            _check_layout_version(lib, so)
        lib.rtpu_store_create.restype = ctypes.c_void_p
        lib.rtpu_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                          ctypes.c_uint64, ctypes.c_uint64,
                                          ctypes.c_int, ctypes.c_int]
        lib.rtpu_store_open.restype = ctypes.c_void_p
        lib.rtpu_store_open.argtypes = [ctypes.c_char_p]
        lib.rtpu_store_close.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_unlink.argtypes = [ctypes.c_char_p]
        lib.rtpu_store_base.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.rtpu_store_base.argtypes = [ctypes.c_void_p]
        lib.rtpu_obj_create.restype = ctypes.c_uint64
        lib.rtpu_obj_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_uint64, ctypes.c_int64,
                                        ctypes.POINTER(ctypes.c_int)]
        lib.rtpu_obj_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_obj_get.restype = ctypes.c_int
        lib.rtpu_obj_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int64,
                                     ctypes.POINTER(ctypes.c_uint64),
                                     ctypes.POINTER(ctypes.c_uint64)]
        lib.rtpu_obj_release.restype = ctypes.c_int
        lib.rtpu_obj_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_obj_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_obj_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_obj_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_obj_reclaim_pending.restype = ctypes.c_int
        lib.rtpu_obj_reclaim_pending.argtypes = [ctypes.c_void_p,
                                                 ctypes.c_char_p]
        lib.rtpu_store_stats.argtypes = [ctypes.c_void_p] + [
            ctypes.POINTER(ctypes.c_uint64)] * 4
        lib.rtpu_store_prefault.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_size.restype = ctypes.c_uint64
        lib.rtpu_store_size.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_set_auto_evict.argtypes = [ctypes.c_void_p,
                                                  ctypes.c_int]
        lib.rtpu_store_spill_victims.restype = ctypes.c_int
        lib.rtpu_store_spill_victims.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
        lib.rtpu_store_layout_version.restype = ctypes.c_uint64
        lib.rtpu_store_layout_version.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_n_shards.restype = ctypes.c_uint64
        lib.rtpu_store_n_shards.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_spill_note.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int64]
        lib.rtpu_store_spill_count.restype = ctypes.c_int64
        lib.rtpu_store_spill_count.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_max_object_bytes.restype = ctypes.c_uint64
        lib.rtpu_store_max_object_bytes.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return lib


_KEY_SIZE = 28  # must match kKeySize in shm_store.cc (== ObjectID bytes)


class ShmObjectExistsError(Exception):
    pass


class ShmStoreFullError(Exception):
    pass


class PinnedBuffer:
    """Zero-copy view of a sealed object; releases its pin when closed /
    garbage-collected. Holding one keeps the object unevictable.

    Implements the buffer protocol: ``memoryview(pinned_buffer)`` (and every
    slice derived from it, and every numpy array deserialized over those
    slices) keeps THIS object alive, so the pin is only dropped once no view
    into the shm segment remains. This is how zero-copy ``get()`` stays safe
    against LRU eviction reusing the arena block (the reference ties plasma
    buffer lifetime to the python object the same way)."""

    def __init__(self, store: "ShmStore", key: bytes, mv: memoryview,
                 spill_pin: bool = False):
        self._store = store
        self._key = key
        self.buffer = mv
        self._released = False
        self._finalizer = weakref.finalize(
            self, store._release_raw, key, spill_pin)

    def release(self) -> None:
        if not self._released:
            self._released = True
            self.buffer = None
            self._finalizer()

    def __buffer__(self, flags: int) -> memoryview:
        return memoryview(self.buffer)

    def __len__(self):
        return len(self.buffer)


class ShmStore:
    """One mapped store segment."""

    def __init__(self, handle: int, name: str, owner: bool):
        self._lib = _load_lib()
        self._h = handle
        self.name = name
        self._owner = owner
        # Belt-and-braces attach guard: the C open/create already rejects
        # mismatched segments via the versioned magic, but a corrupted or
        # hand-rolled mapping must still fail fast here.
        seg_ver = int(self._lib.rtpu_store_layout_version(self._h))
        if seg_ver != _LAYOUT_VERSION:
            raise OSError(
                f"shm store {name!r} has layout version {seg_ver}, this "
                f"client needs {_LAYOUT_VERSION}; the creating process ran "
                "a different build — rebuild everything with "
                "`python ray_tpu/_cpp/build.py` and restart the cluster.")
        self.n_shards = int(self._lib.rtpu_store_n_shards(self._h))
        # Allocation affinity: this process prefers one sub-arena, so the
        # blocks it cycles through stay mapped in ITS page tables (soft
        # page faults are per-process and brutally slow on sandboxed
        # kernels — concurrent writers swapping blocks was the
        # multi-writer put collapse). Lookup correctness is unaffected:
        # an object's slot location is always key-hashed.
        self._pref_shard = os.getpid() % self.n_shards
        self.max_object_bytes = int(
            self._lib.rtpu_store_max_object_bytes(self._h))
        # Object views are built per-get from this base pointer; offsets from
        # the store are segment-relative.
        self._base_ptr = self._lib.rtpu_store_base(self._h)
        # Disk spilling (reference: local_object_manager.h:110 +
        # external_storage.py): when enabled (config), memory pressure
        # spills LRU sealed objects to per-store files instead of
        # destructively evicting; reads transparently restore. The spill
        # dir derives from the store name so every process mapping the
        # segment (workers, node manager, driver) resolves the same files.
        from ray_tpu.core.config import GLOBAL_CONFIG as _cfg

        self._spill_enabled = bool(_cfg.object_spilling_enabled)
        self._spill_dir = os.path.join(_cfg.object_spilling_dir,
                                       name.lstrip("/"))
        if self._spill_enabled:
            os.makedirs(self._spill_dir, exist_ok=True)
            if owner:
                self._lib.rtpu_store_set_auto_evict(self._h, 0)
        self.n_spilled = 0
        self.n_restored = 0

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def create(cls, name: str, capacity: int, n_slots: int = 0,
               n_shards: int = 0, unlink_existing: bool = True,
               prefault: bool = True) -> "ShmStore":
        lib = _load_lib()
        from ray_tpu.core.config import GLOBAL_CONFIG as _cfg

        if not n_slots:
            n_slots = _cfg.object_store_slots
        if not n_shards:
            n_shards = _cfg.object_store_shards
        # The C side shrinks the shard count for tiny segments so every
        # sub-arena can still hold a real object; n_shards is a ceiling.
        h = lib.rtpu_store_create(name.encode(), capacity, n_slots,
                                  n_shards, 1 if unlink_existing else 0, 0)
        if not h:
            raise OSError(f"failed to create shm store {name!r}")
        store = cls(h, name, owner=True)
        if prefault:
            # madvise(MADV_POPULATE_WRITE) from a daemon thread: pages are
            # faulted in (not modified — safe alongside writers) while
            # create() returns instantly.
            threading.Thread(
                target=lambda: store._lib.rtpu_store_prefault(store._h),
                daemon=True, name=f"shm-prefault-{name}").start()
        return store

    @classmethod
    def open(cls, name: str) -> "ShmStore":
        lib = _load_lib()
        h = lib.rtpu_store_open(name.encode())
        if not h:
            raise OSError(
                f"failed to open shm store {name!r} (missing, or created "
                f"by a build with a different layout version — expected "
                f"v{_LAYOUT_VERSION}; rebuild with "
                "`python ray_tpu/_cpp/build.py`)")
        return cls(h, name, owner=False)

    def close(self) -> None:
        # Deliberately does NOT rtpu_store_close (munmap): background
        # threads (push-ack sweeps, GC-driven deferred releases) can still
        # be inside a store call with the handle in hand — unmapping under
        # them is a use-after-unmap SIGSEGV at shutdown. The mapping is
        # reclaimed at process exit. Unlink (owner only) removes the NAME;
        # live mappings in other processes stay valid per POSIX shm.
        if getattr(self, "_closed", False):
            return
        self._closed = True
        if self._h and self._owner:
            self._lib.rtpu_store_unlink(self.name.encode())
            if self._spill_enabled:
                import shutil

                shutil.rmtree(self._spill_dir, ignore_errors=True)

    # -- raw segment access ------------------------------------------------

    def _view(self, offset: int, size: int) -> memoryview:
        ArrayT = ctypes.c_uint8 * size
        arr = ArrayT.from_address(
            ctypes.addressof(self._base_ptr.contents) + offset)
        return memoryview(arr).cast("B")

    @staticmethod
    def _key(oid: ObjectID) -> bytes:
        return oid.binary()

    # -- spilling ----------------------------------------------------------

    def _spill_path(self, key: bytes) -> str:
        return os.path.join(self._spill_dir, key.hex() + ".bin")

    def spill_for(self, need: int) -> bool:
        """Write LRU sealed unpinned objects out to disk (then delete them
        from the arena) until ~`need` bytes could be freed. Returns True if
        anything was spilled."""
        Buf = ctypes.c_uint8 * (256 * _KEY_SIZE)
        keys_buf = Buf()
        n = self._lib.rtpu_store_spill_victims(
            self._h, max(need, 1), keys_buf, 256)
        spilled = False
        for i in range(n):
            key = bytes(keys_buf[i * _KEY_SIZE:(i + 1) * _KEY_SIZE])
            oid = ObjectID(key)
            buf = self.get(oid, timeout_ms=0, _no_restore=True)
            if buf is None:
                continue  # raced: deleted/spilled by someone else
            path = self._spill_path(key)
            # Unique per (process, thread): two exec threads spilling the
            # same victim concurrently must not share a tmp name (the
            # second os.replace would find it already moved).
            tmp = path + f".tmp{os.getpid()}.{threading.get_ident()}"
            try:
                with open(tmp, "wb") as f:
                    f.write(buf.buffer)
                # Shared live-file counter: delete() on every process
                # mapping this store skips its unlink syscall while this
                # reads 0 (the overwhelmingly common case). Incremented
                # BEFORE the rename so a concurrent delete() can never
                # observe the file without the counter — skipping an
                # unlink there would let a stale file resurrect a deleted
                # object. Over-counting (rename lost a race) only costs
                # extra unlink attempts, never correctness.
                self._lib.rtpu_store_spill_note(self._h, 1)
                try:
                    os.replace(tmp, path)  # atomic: whole files only
                except FileNotFoundError:
                    # A concurrent spill (or a shutdown rmtree) won the
                    # race; the object is either safely on disk already or
                    # the store is going away.
                    self._lib.rtpu_store_spill_note(self._h, -1)
            finally:
                buf.release()
            self.spill_delete_only(oid)  # keep the file we just wrote
            self.n_spilled += 1
            spilled = True
        return spilled

    def _spill_files_live(self) -> bool:
        """True when any process mapping this store may have spill files on
        disk. One mapped-memory read — gates the per-op unlink/stat/open
        syscalls (~400us each on overlayfs) off the spill-less hot path."""
        return (self._spill_enabled
                and self._lib.rtpu_store_spill_count(self._h) > 0)

    def _maybe_restore(self, oid: ObjectID) -> bool:
        """Bring a spilled object back into the arena. True if present
        afterwards (restored here or concurrently by another process)."""
        if not self._spill_files_live():
            return False
        path = self._spill_path(self._key(oid))
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return False
        try:
            mv = self.create_buffer(oid, len(data))
        except ShmObjectExistsError:
            return True  # another process is restoring it; get() will wait
        except ShmStoreFullError:
            return False
        try:
            mv[:] = data
        except BaseException:
            self.abort(oid)
            raise
        self.seal(oid)
        self.n_restored += 1
        # Keep the file: it is the cheap insurance copy until delete().
        return True

    def _create_raw(self, key: bytes, total: int, what: str) -> int:
        """rtpu_obj_create with a spill-on-pressure rescue OFF the hot
        path: the common case is exactly one C call under one shard mutex
        (concurrent creates from separate processes proceed in parallel).
        Only a full store enters the spill/retry loop below — and the
        gc.collect rescue (zero-copy views stuck in GC cycles keeping
        arena pins alive) runs at most once per call, never per lap."""
        if total > self.max_object_bytes:
            raise ShmStoreFullError(
                f"object of {total} bytes exceeds the largest sub-arena "
                f"({self.max_object_bytes} bytes across {self.n_shards} "
                "shards); raise object_store_memory_bytes or lower "
                "object_store_shards")
        err = ctypes.c_int(0)
        off = self._lib.rtpu_obj_create(self._h, key, total,
                                        self._pref_shard, ctypes.byref(err))
        if off:
            return off
        if err.value == 1:
            raise ShmObjectExistsError(key.hex())

        def full():
            return ShmStoreFullError(
                f"store full ({what}: {total} bytes requested; "
                f"err={err.value}, spilling="
                f"{'on' if self._spill_enabled else 'off'})")

        if not self._spill_enabled:
            raise full()
        gc_done = False
        for attempt in range(24):
            spilled = self.spill_for(total)
            off = self._lib.rtpu_obj_create(self._h, key, total,
                                            self._pref_shard,
                                            ctypes.byref(err))
            if off:
                return off
            if err.value == 1:
                raise ShmObjectExistsError(key.hex())
            if not spilled:
                if not gc_done:
                    import gc

                    gc.collect()
                    gc_done = True
                    continue
                if attempt >= 4:
                    raise full()
                # Nothing spillable and GC already ran: concurrent pins
                # are the only thing that can still free room — wait them
                # out briefly, then give up.
                time.sleep(0.02 * (attempt + 1))
        raise full()

    # -- object API --------------------------------------------------------

    def put_bytes(self, oid: ObjectID, payload) -> None:
        """Create+write+seal in one call. payload: bytes-like or list of
        bytes-like (scattered write, no intermediate concat copy)."""
        parts = payload if isinstance(payload, (list, tuple)) else [payload]
        total = sum(len(p) for p in parts)
        key = self._key(oid)
        off = self._create_raw(key, total, "put_bytes")
        try:
            from ray_tpu.core.serialization import stream_copy

            mv = self._view(off, total)
            pos = 0
            for p in parts:
                n = len(p)
                if not isinstance(p, (bytes, bytearray, memoryview)):
                    p = bytes(p)
                stream_copy(mv[pos:pos + n], p)
                pos += n
        except BaseException:
            self._lib.rtpu_obj_abort(self._h, key)
            raise
        self._lib.rtpu_obj_seal(self._h, key)

    def create_buffer(self, oid: ObjectID, size: int) -> memoryview:
        """Two-phase create: returns a writable view; call seal() after."""
        off = self._create_raw(self._key(oid), size, "create_buffer")
        return self._view(off, size)

    def seal(self, oid: ObjectID) -> None:
        self._lib.rtpu_obj_seal(self._h, self._key(oid))

    def abort(self, oid: ObjectID) -> None:
        self._lib.rtpu_obj_abort(self._h, self._key(oid))

    def get(self, oid: ObjectID, timeout_ms: int = 0,
            _no_restore: bool = False) -> Optional[PinnedBuffer]:
        """Pinned zero-copy read; transparently restores spilled objects.
        None on timeout/missing. ``_no_restore`` pins are SPILL pins: their
        release must never unlink the spill file (see _release_raw)."""
        key = self._key(oid)
        off = ctypes.c_uint64(0)
        size = ctypes.c_uint64(0)
        rc = self._lib.rtpu_obj_get(self._h, key, 0,
                                    ctypes.byref(off), ctypes.byref(size))
        if rc != 0 and not _no_restore and self._maybe_restore(oid):
            rc = self._lib.rtpu_obj_get(self._h, key, timeout_ms or 5000,
                                        ctypes.byref(off), ctypes.byref(size))
        elif rc != 0 and timeout_ms != 0:
            rc = self._lib.rtpu_obj_get(self._h, key, timeout_ms,
                                        ctypes.byref(off), ctypes.byref(size))
        if rc != 0:
            return None
        return PinnedBuffer(self, key, self._view(off.value, size.value),
                            spill_pin=_no_restore)

    def get_bytes(self, oid: ObjectID,
                  timeout_ms: int = 0) -> Optional[bytes]:
        """Copying read (no pin held afterwards)."""
        buf = self.get(oid, timeout_ms)
        if buf is None:
            return None
        try:
            return bytes(buf.buffer)
        finally:
            buf.release()

    def _release_raw(self, key: bytes, spill_pin: bool = False) -> None:
        if self._h:
            rc = self._lib.rtpu_obj_release(self._h, key)
            if rc == 2 and not spill_pin and self._spill_files_live():
                # Last pin of a DOOMED object (deleted while we held it):
                # any spill file we or others wrote must not resurrect it.
                # SPILL pins are exempt: two concurrent spills of the same
                # victim interleave as (T1 pin, T2 pin, T2 file, T2
                # arena-drop, T1 file, T1 release<-rc2) — T1 unlinking here
                # destroyed the just-written backing file, leaving a GHOST
                # object (owner says in_store; nothing anywhere). A stale
                # file after a real delete() is already unlinked by
                # delete() itself; the residual race leaks only a dead
                # file, never data.
                try:
                    os.unlink(self._spill_path(key))
                    self._lib.rtpu_store_spill_note(self._h, -1)
                except OSError:
                    pass

    def delete(self, oid: ObjectID) -> bool:
        """Remove the in-memory copy AND any spill file (a freed object must
        not resurrect on a later read). The unlink syscall is skipped while
        the shared spill-file counter reads 0 — the common (spill-less)
        case pays exactly one C call."""
        ok = self._lib.rtpu_obj_delete(self._h, self._key(oid)) == 0
        if self._spill_files_live():
            try:
                os.unlink(self._spill_path(self._key(oid)))
                self._lib.rtpu_store_spill_note(self._h, -1)
                ok = True
            except OSError:
                pass
        return ok

    def reclaim_pending(self, oid: ObjectID) -> bool:
        """Reclaim a create whose owner died between inserting its
        placeholder slot and filling it (the slot would otherwise wedge
        the key forever). Only touches PENDING placeholders — a live
        writer's allocated-but-unsealed object is never affected."""
        return self._lib.rtpu_obj_reclaim_pending(
            self._h, self._key(oid)) == 0

    def spill_delete_only(self, oid: ObjectID) -> bool:
        """delete() semantics as used by spill_for: drop ONLY the arena
        copy, keeping the spill file as the object's backing."""
        return self._lib.rtpu_obj_delete(self._h, self._key(oid)) == 0

    def contains(self, oid: ObjectID) -> bool:
        if bool(self._lib.rtpu_obj_contains(self._h, self._key(oid))):
            return True
        return (self._spill_files_live()
                and os.path.exists(self._spill_path(self._key(oid))))

    def stats(self) -> Tuple[int, int, int, int]:
        """(used_bytes, capacity, n_objects, n_evictions)."""
        vals = [ctypes.c_uint64(0) for _ in range(4)]
        self._lib.rtpu_store_stats(self._h, *[ctypes.byref(v) for v in vals])
        return tuple(v.value for v in vals)
