"""Distributed reference counting for object lifetimes.

Equivalent of the reference's ReferenceCounter (reference:
src/ray/core_worker/reference_count.h): every object has exactly one owner
(the worker whose task created it or that called put); the owner tracks
  - local refs      (ObjectRef instances alive in the owner process),
  - submitted refs  (pending tasks that take the object as an argument),
  - borrower refs   (other workers holding deserialized copies of the ref),
and releases the value from the store when all three reach zero.  Borrowers
report their local count reaching zero back to the owner asynchronously
(mirrors the reference's WaitForRefRemoved long-poll protocol, simplified to a
single release message over the control plane).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Set

from ray_tpu.core.ids import ObjectID


class _Ref:
    __slots__ = ("local", "submitted", "borrowers", "owned", "lineage_pinned")

    def __init__(self, owned: bool):
        self.local = 0
        self.submitted = 0
        self.borrowers: Set[str] = set()
        self.owned = owned
        self.lineage_pinned = False

    def out_of_scope(self) -> bool:
        return self.local <= 0 and self.submitted <= 0 and not self.borrowers


class ReferenceCounter:
    def __init__(self, on_release: Optional[Callable[[ObjectID], None]] = None,
                 on_borrow_release: Optional[Callable[[ObjectID],
                                                      None]] = None):
        import collections

        self._lock = threading.Lock()
        self._refs: Dict[ObjectID, _Ref] = {}
        self._on_release = on_release
        # Fires when a BORROWED (non-owned) ref goes out of scope in this
        # process: the borrower's half of the WaitForRefRemoved protocol —
        # without it the owner pins every borrowed object forever.
        self._on_borrow_release = on_borrow_release
        self.enabled = True
        # ObjectRef.__del__ may run INSIDE a locked section of this very
        # counter (any allocation under the lock can trigger GC, which
        # collects refs whose __del__ re-enters here — a guaranteed
        # self-deadlock on a plain Lock). Finalizers therefore never take
        # the lock: they append to this queue (deque.append is atomic) and
        # decrements are applied by the next normal-context operation.
        self._deferred: "collections.deque" = collections.deque()

    def _apply_deferred_locked(self) -> list:
        """Caller holds the lock. Returns release callbacks to run after
        the lock is dropped."""
        releases = []
        while self._deferred:
            try:
                oid = self._deferred.popleft()
            except IndexError:
                break
            ref = self._refs.get(oid)
            if ref is None:
                continue
            ref.local -= 1
            cb = self._maybe_release_locked(oid, ref)
            if cb:
                releases.append(cb)
        return releases

    def flush_deferred(self) -> None:
        """Apply queued finalizer decrements (called from normal contexts:
        periodic sweeps and every counter operation)."""
        if not self._deferred:
            return
        with self._lock:
            releases = self._apply_deferred_locked()
        for cb in releases:
            cb()

    # --- owner-side ---

    def add_owned_object(self, oid: ObjectID) -> None:
        self.flush_deferred()
        with self._lock:
            ref = self._refs.setdefault(oid, _Ref(owned=True))
            ref.owned = True

    def drop_owned_object(self, oid: ObjectID) -> None:
        """Owner-side FORCED release (e.g. abandoned-stream items that no
        ObjectRef was ever minted for): removes the record and fires the
        release hook so stored bytes free immediately."""
        self.flush_deferred()
        with self._lock:
            ref = self._refs.pop(oid, None)
        if ref is not None and self._on_release is not None:
            try:
                self._on_release(oid)
            except Exception:
                pass

    def add_local_ref(self, oid: ObjectID) -> None:
        if not self.enabled:
            return
        self.flush_deferred()
        with self._lock:
            self._refs.setdefault(oid, _Ref(owned=False)).local += 1

    def remove_local_ref(self, oid: ObjectID) -> None:
        """Finalizer-safe: runs from ObjectRef.__del__ (possibly mid-GC
        inside our own locked section, or inside ANY other subsystem's
        lock), so it must not take locks or do IO — enqueue only; the next
        normal-context counter operation or periodic sweep applies it."""
        if not self.enabled:
            return
        self._deferred.append(oid)

    def add_submitted_task_ref(self, oid: ObjectID) -> None:
        if not self.enabled:
            return
        self.flush_deferred()
        with self._lock:
            self._refs.setdefault(oid, _Ref(owned=False)).submitted += 1

    def remove_submitted_task_ref(self, oid: ObjectID) -> None:
        if not self.enabled:
            return
        self.flush_deferred()
        self._dec(oid, "submitted")

    def add_borrower(self, oid: ObjectID, borrower_addr: str) -> None:
        with self._lock:
            self._refs.setdefault(oid, _Ref(owned=True)).borrowers.add(borrower_addr)

    def remove_borrower(self, oid: ObjectID, borrower_addr: str) -> None:
        release = None
        with self._lock:
            ref = self._refs.get(oid)
            if ref is None:
                return
            ref.borrowers.discard(borrower_addr)
            release = self._maybe_release_locked(oid, ref)
        if release:
            release()

    def pin_lineage(self, oid: ObjectID) -> None:
        with self._lock:
            ref = self._refs.get(oid)
            if ref:
                ref.lineage_pinned = True

    def local_count(self, oid: ObjectID) -> int:
        with self._lock:
            ref = self._refs.get(oid)
            return ref.local if ref else 0

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._refs)

    def tracked_ids(self) -> Set[ObjectID]:
        with self._lock:
            return set(self._refs)

    def is_in_scope(self, oid: ObjectID) -> bool:
        with self._lock:
            ref = self._refs.get(oid)
            return ref is not None and not ref.out_of_scope()

    # --- internals ---

    def _dec(self, oid: ObjectID, kind: str) -> None:
        release = None
        with self._lock:
            ref = self._refs.get(oid)
            if ref is None:
                return
            if kind == "local":
                ref.local -= 1
            else:
                ref.submitted -= 1
            release = self._maybe_release_locked(oid, ref)
        if release:
            release()

    def _maybe_release_locked(self, oid: ObjectID, ref: _Ref):
        if not ref.out_of_scope():
            return None
        del self._refs[oid]
        if ref.owned and self._on_release:
            cb = self._on_release
            return lambda: cb(oid)
        if not ref.owned and self._on_borrow_release is not None:
            cb = self._on_borrow_release
            return lambda: cb(oid)
        return None
