"""Driver-side cluster runtime: boots head + node processes and connects.

Parity target: the reference's Node/process-launcher path (reference:
python/ray/_private/node.py:37 start_head_processes :1407,
services.py start_gcs_server :1445 / start_raylet :1523) — collapsed to two
subprocess kinds (head, node manager) plus the in-driver ClusterCore.

Also provides `Cluster` (the fake multi-node test harness, parity with
python/ray/cluster_utils.py:135 add_node :202): extra node managers are
plain local processes with caller-chosen fake resources, so multi-node
scheduling/transfer paths run on one machine.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core import runtime_context
from ray_tpu.core.cluster_core import ClusterCore
from ray_tpu.core.config import GLOBAL_CONFIG as cfg
from ray_tpu.core.ids import JobID


def _spawn(args: List[str], log_name: str) -> subprocess.Popen:
    from ray_tpu.core.process_util import spawn_env

    os.makedirs(cfg.log_dir, exist_ok=True)
    logf = open(os.path.join(cfg.log_dir, log_name), "ab", buffering=0)
    try:
        env = spawn_env()  # child arms PDEATHSIG itself (see process_util:
        # preexec_fn would force fork()-with-threads, the JAX deadlock
        # class). Children must import ray_tpu from wherever the driver
        # imported it (repo checkouts aren't pip-installed).
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(args, stdout=subprocess.PIPE, stderr=logf,
                                env=env, cwd=os.getcwd())
    except BaseException:
        logf.close()  # Popen failed: nobody else will ever close the fd
        raise
    logf.close()  # the child holds its own dup; the parent's copy leaks
    return proc


def _read_tagged_line(proc: subprocess.Popen, tag: str, timeout: float) -> Dict[str, str]:
    """Reads stdout lines until one starting with `tag` appears; returns the
    space-separated key/value pairs of that line."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"process exited rc={proc.returncode} before "
                               f"printing {tag}")
        line = proc.stdout.readline().decode()
        if not line:
            time.sleep(0.01)
            continue
        parts = line.strip().split()
        if parts and parts[0] == tag:
            out = {}
            for i in range(0, len(parts) - 1, 2):
                out[parts[i]] = parts[i + 1]
            return out
    raise TimeoutError(f"timed out waiting for {tag} line")


class NodeProc:
    def __init__(self, proc: subprocess.Popen, address: str, node_id: str,
                 store_name: str):
        self.proc = proc
        self.address = address
        self.node_id = node_id
        self.store_name = store_name


def start_node_process(head_addr: str, resources: Optional[Dict[str, float]],
                       labels: Optional[Dict[str, str]] = None,
                       object_store_bytes: Optional[int] = None,
                       timeout: Optional[float] = None) -> NodeProc:
    if timeout is None:
        timeout = cfg.node_boot_timeout_s
    args = [sys.executable, "-m", "ray_tpu.cluster.node_main",
            "--head-addr", head_addr,
            "--resources", json.dumps(resources or {}),
            "--labels", json.dumps(labels or {})]
    if object_store_bytes:
        args += ["--object-store-bytes", str(object_store_bytes)]
    proc = _spawn(args, f"node-{int(time.time()*1000)%100000}.log")
    info = _read_tagged_line(proc, "ADDRESS", timeout)
    return NodeProc(proc, info["ADDRESS"], info["NODE"], info["STORE"])


class SimulatedCluster:
    """Scale-mode harness (bench.py --scale): ONE in-process HeadServer
    plus N in-process ``NodeManager(simulated=True)`` instances with
    stubbed stores. Everything control-plane is real — registration,
    versioned heartbeat delta sync, holder-set mirrors, the lease
    census — so head RPC dispatch, heartbeat fan-in, and directory
    lookups can be profiled at 100+ node counts on one machine."""

    def __init__(self, n_nodes: int, resources: Optional[Dict[str, float]]
                 = None, zones: int = 4):
        import uuid as _uuid

        from ray_tpu.cluster.head import HeadServer
        from ray_tpu.cluster.node_manager import NodeManager
        from ray_tpu.cluster.protocol import RpcClient

        self.head = HeadServer()
        self.nodes: List[Any] = []
        res = dict(resources or {"CPU": 8.0})
        for i in range(n_nodes):
            node_id = _uuid.uuid4().hex
            self.nodes.append(NodeManager(
                self.head.address, node_id, dict(res),
                {"zone": f"z{i % max(1, zones)}"}, 0, simulated=True))
        self.client = RpcClient(self.head.address)

    def wait_registered(self, timeout_s: float = 30.0) -> None:
        deadline = time.monotonic() + timeout_s
        want = len(self.nodes)
        while time.monotonic() < deadline:
            views = self.client.call("list_nodes", timeout=10)
            if sum(1 for v in views if v["alive"]) >= want:
                return
            time.sleep(0.2)
        raise TimeoutError(f"only {len(self.client.call('list_nodes'))} "
                           f"of {want} simulated nodes registered")

    def shutdown(self) -> None:
        try:
            self.client.close()
        except Exception as e:
            logging.getLogger(__name__).debug(
                "sim client close failed: %r", e)
        for n in self.nodes:
            try:
                n.shutdown()
            except Exception as e:
                logging.getLogger(__name__).debug(
                    "sim node shutdown failed: %r", e)
        self.head.shutdown()


class ClusterRuntime(ClusterCore):
    """The driver's runtime: owns the head/node subprocesses it started."""

    def __init__(self, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 object_store_memory: Optional[int] = None,
                 labels: Optional[Dict[str, str]] = None,
                 address: Optional[str] = None):
        self._procs: List[subprocess.Popen] = []
        self._nodes: List[NodeProc] = []
        if address is None and "RTPU_LOG_DIR" not in os.environ:
            # Session-scoped log dir: a long-lived shared dir accumulates
            # thousands of stale worker logs, and the driver's log monitor
            # (plus every spawn) would glob+stat all of them every poll.
            import uuid as _uuid

            # Remember the base across init/shutdown cycles so re-inits
            # don't nest session dirs inside the previous session's.
            base = getattr(ClusterRuntime, "_base_log_dir", None)
            if base is None:
                base = ClusterRuntime._base_log_dir = cfg.log_dir
            session_dir = os.path.join(
                base, f"session-{_uuid.uuid4().hex[:12]}")
            cfg.set("log_dir", session_dir)
            os.environ["RTPU_LOG_DIR"] = session_dir  # inherited by spawns
            self._owns_log_dir_env = True
        if address is None:
            self._head_persist = os.path.join(cfg.log_dir, "head_state.db")
            head_proc = _spawn(
                [sys.executable, "-m", "ray_tpu.cluster.head_main",
                 "--persist", self._head_persist],
                "head.log")
            self._procs.append(head_proc)
            head_addr = _read_tagged_line(
                head_proc, "ADDRESS",
                cfg.node_boot_timeout_s)["ADDRESS"]
            self._head_proc = head_proc
            self._head_addr_str = head_addr
            # Head fault tolerance: supervise + respawn on the SAME port
            # with the SAME durable tables; clients' retrying calls ride
            # out the gap (reference: GCS restart + redis-backed tables).
            threading.Thread(target=self._head_supervisor_loop, daemon=True,
                             name="head-supervisor").start()

            res = dict(resources or {})
            if num_cpus is not None:
                res["CPU"] = float(num_cpus)
            if num_tpus is not None:
                res["TPU"] = float(num_tpus)
            node = start_node_process(
                head_addr, res or None, labels,
                object_store_memory or cfg.object_store_memory_bytes)
            self._procs.append(node.proc)
            self._nodes.append(node)
            self._owns_cluster = True
        else:
            # Connect to an existing cluster: join as driver on a new node?
            # Round 1: drivers must run on a machine with a node manager;
            # we start a zero-resource "driver node" for the object plane.
            head_addr = address
            node = start_node_process(head_addr, {"CPU": 0.0}, labels,
                                      object_store_memory
                                      or cfg.object_store_memory_bytes)
            self._procs.append(node.proc)
            self._nodes.append(node)
            self._owns_cluster = False

        super().__init__(head_addr, node.address, node.node_id,
                         node.store_name, JobID.from_int(1), is_driver=True)
        job_int = self.head.retrying_call("new_job_id", timeout=10)
        self.job_id = JobID.from_int(job_int)
        atexit.register(self.shutdown)
        if cfg.log_to_driver:
            from ray_tpu.util.log_monitor import LogMonitor

            self._log_monitor = LogMonitor(cfg.log_dir)
            self._log_monitor.start()
        if cfg.metrics_report_period_ms > 0:
            threading.Thread(target=self._metrics_report_loop, daemon=True,
                             name="metrics-report").start()

    def _head_supervisor_loop(self) -> None:
        """Respawns a crashed head on its original port with its durable
        tables. The port is stable so every cached client address stays
        valid; reconnects happen inside retrying_call."""
        port = self._head_addr_str.rsplit(":", 1)[1]
        while not getattr(self, "_shutdown_flag", False):
            if getattr(self, "_upgrading", False):
                # Rolling upgrade owns the head process handover: the
                # supervisor racing it would double-bind the port.
                time.sleep(cfg.head_supervisor_poll_s)
                continue
            proc = self._head_proc
            if proc.poll() is None:
                time.sleep(cfg.head_supervisor_poll_s)
                continue
            if getattr(self, "_shutdown_flag", False) or getattr(
                    self, "_upgrading", False):
                continue
            try:
                new_proc = _spawn(
                    [sys.executable, "-m", "ray_tpu.cluster.head_main",
                     "--port", port, "--persist", self._head_persist],
                    "head.log")
                _read_tagged_line(new_proc, "ADDRESS",
                                  cfg.node_boot_timeout_s)
                self._head_proc = new_proc
                self._procs.append(new_proc)
            except Exception:
                time.sleep(1.0)  # port may linger in TIME_WAIT; retry

    def rolling_head_upgrade(self) -> Dict[str, Any]:
        """Zero-request-failure head swap (ROADMAP item 3's rolling
        upgrade): drain + WAL-checkpoint the serving head, SIGTERM it
        (graceful stop severs parked peer conns and releases the port),
        bind a NEW head process — a new incarnation — on the SAME port
        with the SAME durable tables, and let the cluster re-converge:
        clients ride retrying_call across the gap, nodes re-register on
        their first heartbeat NACK and republish holder sets (the PR 8
        path), and recovered-ALIVE actors are confirmed as their nodes
        come back. Returns the step timings; the chaos scenario driver
        (devtools.chaos.run_rolling_upgrade) asserts zero failed client
        requests around it."""
        if not getattr(self, "_owns_cluster", False):
            raise RuntimeError("rolling_head_upgrade needs the driver "
                               "that owns the head process")
        port = self._head_addr_str.rsplit(":", 1)[1]
        report: Dict[str, Any] = {}
        t0 = time.monotonic()
        self._upgrading = True
        try:
            summary = self.head.retrying_call(
                "prepare_upgrade",
                timeout=cfg.head_upgrade_drain_timeout_s + 10)
            report["old_incarnation"] = summary.get("incarnation")
            report["drain_s"] = round(time.monotonic() - t0, 3)
            old = self._head_proc
            old.terminate()
            try:
                old.wait(timeout=10)
            except subprocess.TimeoutExpired:
                old.kill()
                old.wait(timeout=5)
            t_swap = time.monotonic()
            report["handover_at_s"] = round(t_swap - t0, 3)
            # Port may linger a beat after process exit: retry the bind.
            deadline = time.monotonic() + cfg.node_boot_timeout_s
            new_proc = None
            while new_proc is None:
                try:
                    new_proc = _spawn(
                        [sys.executable, "-m", "ray_tpu.cluster.head_main",
                         "--port", port, "--persist", self._head_persist],
                        "head.log")
                    _read_tagged_line(new_proc, "ADDRESS",
                                      cfg.node_boot_timeout_s)
                except Exception:
                    if new_proc is not None and new_proc.poll() is None:
                        new_proc.kill()
                    new_proc = None
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.5)
            self._head_proc = new_proc
            self._procs.append(new_proc)
        finally:
            self._upgrading = False
        # The swap is done when the successor answers on the old port.
        stats = self.head.retrying_call("scheduler_stats", timeout=30)
        report["new_incarnation"] = stats.get("head_incarnation")
        report["upgrade_s"] = round(time.monotonic() - t0, 3)
        return report

    # --------------------------------------------------------------- kv

    def kv_put(self, key: str, value: bytes, *, namespace: str = "default",
               overwrite: bool = True) -> bool:
        data = value if isinstance(value, bytes) else str(value).encode()
        return self.head.retrying_call("kv_put", namespace, key.encode(),
                                       data, overwrite, timeout=10)

    def kv_get(self, key: str, *, namespace: str = "default"):
        return self.head.retrying_call("kv_get", namespace, key.encode(),
                                       timeout=10)

    def kv_del(self, key: str, *, namespace: str = "default") -> bool:
        return self.head.retrying_call("kv_del", namespace, key.encode(),
                                       timeout=10)

    def kv_keys(self, prefix: str = "", *,
                namespace: str = "default") -> List[str]:
        keys = self.head.retrying_call("kv_keys", namespace,
                                       prefix.encode(), timeout=10)
        return [k.decode() for k in keys]

    def _metrics_report_loop(self) -> None:
        """Publish this process's metric registry to the head KV
        (reference: per-node metrics agents pushing to Prometheus)."""
        from ray_tpu.util.metrics import prometheus_text

        period = cfg.metrics_report_period_ms / 1000.0
        while not self._shutdown_flag:
            time.sleep(period)
            try:
                self.kv_put(f"metrics/{self.node_id[:12]}",
                            prometheus_text().encode())
            except Exception:
                pass

    def add_node(self, num_cpus: float = 1.0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 object_store_bytes: Optional[int] = None) -> NodeProc:
        """Test/scale-out hook: boot another (possibly fake-resource) node."""
        res = dict(resources or {})
        res.setdefault("CPU", num_cpus)
        node = start_node_process(self.head_addr, res, labels,
                                  object_store_bytes or (256 << 20))
        self._procs.append(node.proc)
        self._nodes.append(node)
        return node

    def remove_node(self, node: NodeProc) -> None:
        try:
            self.head.call("drain_node", node.node_id, timeout=5)
        except Exception:
            pass
        node.proc.terminate()
        try:
            node.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            node.proc.kill()
        if node in self._nodes:
            self._nodes.remove(node)

    def kill_node(self, node: NodeProc) -> None:
        """Chaos hook: SIGKILL a node manager (health check must notice)."""
        node.proc.kill()
        if node in self._nodes:
            self._nodes.remove(node)

    def shutdown(self) -> None:
        if getattr(self, "_shutdown_flag", False):
            return
        try:
            atexit.unregister(self.shutdown)
        except Exception:
            pass
        if getattr(self, "_log_monitor", None) is not None:
            self._log_monitor.stop()  # else init/shutdown cycles double-ship
        if getattr(self, "_owns_log_dir_env", False):
            os.environ.pop("RTPU_LOG_DIR", None)  # fresh dir per session
        super().shutdown()
        for p in self._procs:
            try:
                p.terminate()
            except Exception:
                pass
        deadline = time.monotonic() + 5
        for p in self._procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
