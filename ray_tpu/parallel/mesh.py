"""Device-mesh construction and named sharding axes.

TPU-first replacement for the reference's process-group world (Ray Train wires
torch ``init_process_group`` per worker, reference `train/torch/config.py:94-163`;
collectives go through NCCL in `util/collective/collective.py:120`). Here the
unit of parallelism is a single SPMD program over a `jax.sharding.Mesh`; XLA
inserts the collectives over ICI.

Logical mesh axes (scaling-book convention):

- ``dp``   — pure data parallelism (gradient all-reduce over ICI/DCN)
- ``fsdp`` — data parallelism with parameter/optimizer sharding (ZeRO-3-style;
             XLA turns this into all-gather + reduce-scatter)
- ``tp``   — tensor (Megatron-style) parallelism inside each layer
- ``sp``   — sequence/context parallelism (ring attention over this axis)
- ``pp``   — pipeline stages (layer groups; `parallel/pipeline.py`)
- ``ep``   — expert parallelism for MoE layers (`models/mixtral.py`)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "sp", "pp", "ep", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Sizes for each logical axis. 1 = axis unused (still present in the Mesh,
    so the same jitted program works for any configuration)."""

    dp: int = 1
    fsdp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1
    tp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.fsdp * self.sp * self.pp * self.ep * self.tp

    def axis_sizes(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXES)

    @staticmethod
    def auto(n_devices: int, *, tp: Optional[int] = None, sp: int = 1,
             pp: int = 1, ep: int = 1, dp: int = 1) -> "MeshSpec":
        """Fill ``fsdp`` with whatever is left after the explicit axes.

        Default policy (one host / one slice): put tensor parallelism over the
        fastest ICI dimension (up to 8-way on v5p trays), FSDP over the rest.
        """
        if tp is None:
            tp = 8 if n_devices >= 8 else 1
        used = tp * sp * pp * ep * dp
        if n_devices % used:
            raise ValueError(f"{n_devices} devices not divisible by tp*sp*pp*ep*dp={used}")
        return MeshSpec(dp=dp, fsdp=n_devices // used, sp=sp, pp=pp, ep=ep, tp=tp)


def make_mesh(spec: MeshSpec, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh with all six logical axes.

    Device order matters for ICI locality: ``tp`` is the innermost
    (fastest-varying) axis so tensor-parallel collectives ride nearest-neighbor
    ICI links; ``dp``/``fsdp`` are outermost so their (bigger, less frequent)
    reductions can cross DCN on multi-slice deployments.
    """
    if devices is None:
        devices = jax.devices()
    if spec.size != len(devices):
        raise ValueError(f"mesh spec {spec} needs {spec.size} devices, got {len(devices)}")
    arr = np.asarray(devices).reshape(spec.axis_sizes())
    return Mesh(arr, AXES)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    devs = [device] if device is not None else jax.devices()[:1]
    return make_mesh(MeshSpec(), devs)


def mesh_2d(n_devices: Optional[int] = None, *, tp: Optional[int] = None,
            devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """The canonical 2D **FSDP x tensor** training mesh.

    This is the production shape for dense-model pretraining (the
    scaling-book default): parameters ZeRO-3-shard over ``fsdp`` (outer
    axis — bigger, less frequent all-gather/reduce-scatter, DCN-safe)
    while each layer's matmuls split over ``tp`` (inner axis — chatty
    collectives ride nearest-neighbor ICI, see `make_mesh`). ``tp``
    defaults to the largest power of two <= min(8, n_devices) that
    divides ``n_devices``; everything left fills ``fsdp``. All other
    axes stay 1, so the mesh is logically 2D while remaining
    program-compatible with the full six-axis Mesh.

    The Llama train step needs no further wiring: `param_logical_axes`
    names every weight dim, `DEFAULT_RULES` maps embed->fsdp and
    heads/mlp/vocab->tp, and `spmd.sharded_init` materializes the
    NamedShardings (verified by `spmd.assert_params_sharded`).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = list(devices)[:n_devices]
        if len(devices) != n_devices:
            raise ValueError(
                f"mesh_2d: need {n_devices} devices, have {len(devices)}")
    n = len(devices)
    if tp is None:
        tp = largest_pow2_leq(min(8, n))
        while n % tp:
            tp //= 2
    if n % tp:
        raise ValueError(f"mesh_2d: {n} devices not divisible by tp={tp}")
    return make_mesh(MeshSpec(fsdp=n // tp, tp=tp), devices)


# ---------------------------------------------------------------------------
# Logical-axis → mesh-axis mapping (t5x-style logical annotations, minimal).
# ---------------------------------------------------------------------------

# Every tensor dimension in the model is named; this table maps the name to
# mesh axes. None = replicated along that dim.
DEFAULT_RULES: Dict[str, Optional[object]] = {
    "batch": ("dp", "fsdp"),   # batch dim sharded over all data axes
    "seq": "sp",               # sequence dim sharded for context parallelism
    "embed": "fsdp",           # parameters: d_model dim sharded for ZeRO-3
    "heads": "tp",             # attention heads over tensor parallel
    "kv_heads": "tp",
    "head_dim": None,
    "mlp": "tp",               # ffn hidden dim over tensor parallel
    "vocab": "tp",             # output vocab over tensor parallel
    "layers": None,            # stacked-layer leading dim (scanned over)
    "stages": "pp",            # pipeline stage dim
    "experts": "ep",           # MoE expert dim
    "kv_len": None,
    "patch_in": None,          # ViT flattened-patch input dim
    "classes": "tp",           # classifier head over tensor parallel
    "kh": None,                # conv kernel spatial dims (diffusion UNet)
    "kw": None,
    "c_in": None,              # conv input channels
    "channels": "tp",          # conv output channels over tensor parallel
}


def logical_spec(names: Sequence[Optional[str]],
                 rules: Optional[Dict[str, Optional[object]]] = None) -> P:
    """Translate per-dimension logical names into a PartitionSpec."""
    rules = rules or DEFAULT_RULES
    return P(*[rules.get(n) if n is not None else None for n in names])


def named_sharding(mesh: Mesh, names: Sequence[Optional[str]],
                   rules: Optional[Dict[str, Optional[object]]] = None) -> NamedSharding:
    return NamedSharding(mesh, logical_spec(names, rules))


def constrain(x, names: Sequence[Optional[str]],
              rules: Optional[Dict[str, Optional[object]]] = None):
    """`with_sharding_constraint` by logical dimension names (no-op outside jit
    over a mesh). Real spec errors (rank mismatch, unknown axis) surface —
    the no-mesh case is detected explicitly, not by matching error text."""
    get_abstract_mesh = getattr(jax.sharding, "get_abstract_mesh", None)
    if get_abstract_mesh is None:
        # Older jax (< 0.5): no ambient-mesh query; constraints only apply
        # under an explicit set_mesh there, so pass through unsharded.
        return x
    mesh = get_abstract_mesh()
    if mesh is None or getattr(mesh, "empty", False) or not mesh.shape_tuple:
        return x
    return jax.lax.with_sharding_constraint(x, logical_spec(names, rules))


def mesh_context(mesh: Mesh):
    """``jax.sharding.set_mesh(mesh)`` where available (jax >= 0.5); on
    older jax the physical mesh itself is the ambient-mesh context
    manager. Use for version-portable `with mesh_context(m):` blocks."""
    set_mesh = getattr(jax.sharding, "set_mesh", None)
    return set_mesh(mesh) if set_mesh is not None else mesh


def param_shardings(mesh: Mesh, logical_tree,
                    rules: Optional[Dict[str, Optional[object]]] = None):
    """Map a pytree of logical-name tuples to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda names: named_sharding(mesh, names, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def mfu_denominator(n_devices: int, dtype_flops: float = 197e12) -> float:
    """Peak bf16 FLOP/s for the mesh (default: v5e = 197 TFLOP/s/chip;
    v5p = 459e12). Used by bench/MFU reporting."""
    return n_devices * dtype_flops


def largest_pow2_leq(n: int) -> int:
    return 1 << (n.bit_length() - 1) if n > 0 else 1
