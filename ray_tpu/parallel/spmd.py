"""SPMD training-step construction: sharded init + jitted train step.

This is the TPU-native execution model replacing the reference's per-worker
torch DDP wiring (reference `train/_internal/backend_executor.py:69` +
`train/torch/config.py:94-163`): ONE compiled XLA program over a Mesh instead
of N processes exchanging NCCL messages. Gradient reductions, fsdp
all-gathers/reduce-scatters, tp collectives, and ring-attention ppermutes are
all emitted by XLA from sharding annotations.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.devtools import jax_debug
from ray_tpu.models import llama
from ray_tpu.parallel.mesh import logical_spec, param_shardings


class TrainState(NamedTuple):
    step: jnp.ndarray
    params: Any
    opt_state: Any


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Token batches: batch over dp+fsdp, sequence over sp."""
    return NamedSharding(mesh, P(("dp", "fsdp"), "sp"))


def _with_mesh_context(mesh: Mesh, fn):
    """Wrap a jitted callable so tracing always sees ``mesh`` as the ambient
    abstract mesh — `constrain()`'s PartitionSpec annotations then apply
    regardless of whether the caller entered `jax.sharding.set_mesh`."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        use_am = getattr(jax.sharding, "use_abstract_mesh", None)
        if use_am is None:
            # Older jax (< 0.5): no abstract-mesh context; enter the
            # physical mesh instead (constrain() passes through there,
            # but explicit in/out_shardings still place the arrays).
            with mesh:
                return fn(*args, **kwargs)
        with use_am(mesh.abstract_mesh):
            return fn(*args, **kwargs)

    return wrapped


def sharded_init(cfg: llama.LlamaConfig, mesh: Mesh, key: jax.Array,
                 tx: optax.GradientTransformation) -> TrainState:
    """Initialize params directly INTO their shards (no host-side full copy —
    required for models larger than one host's HBM)."""
    shardings = param_shardings(mesh, llama.param_logical_axes(cfg))
    p_init = _with_mesh_context(mesh, jax.jit(
        functools.partial(llama.init_params, cfg), out_shardings=shardings))
    params = p_init(key)
    # Optimizer state mirrors param shapes; XLA propagates the input shardings.
    opt_state = jax.jit(tx.init)(params)
    step = jnp.zeros((), jnp.int32)
    return TrainState(step, params, opt_state)


def make_train_step(
    cfg: llama.LlamaConfig, mesh: Mesh, tx: optax.GradientTransformation,
) -> Callable[[TrainState, jnp.ndarray], Tuple[TrainState, Dict[str, jnp.ndarray]]]:
    """Returns jitted (state, tokens [B,S]) -> (state, metrics). Buffers are
    donated, so the step is in-place in HBM."""

    def step_fn(state: TrainState, tokens: jnp.ndarray):
        (loss, metrics), grads = jax.value_and_grad(
            llama.loss_fn, has_aux=True)(state.params, tokens, cfg, mesh=mesh)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        metrics = dict(metrics, grad_norm=gnorm)
        return TrainState(state.step + 1, params, opt_state), metrics

    # Budget 1: a steady-state trainer compiles its step ONCE — a
    # recompile per step (shape churn, structure churn from a stray
    # python scalar in the state) is the most expensive silent bug a
    # training loop can have. The RTPU_DEBUG_JAX witness reports it;
    # off, wrap_jit returns the jitted step untouched.
    return _with_mesh_context(mesh, jax_debug.wrap_jit(
        jax.jit(step_fn, donate_argnums=(0,)), "spmd.train_step",
        budget=1))


def make_eval_step(cfg: llama.LlamaConfig, mesh: Mesh):
    def eval_fn(params, tokens):
        loss, metrics = llama.loss_fn(params, tokens, cfg, mesh=mesh)
        return metrics
    return _with_mesh_context(mesh, jax_debug.wrap_jit(
        jax.jit(eval_fn), "spmd.eval_step", budget=1))


def sharding_summary(params: Any, logical_tree: Any) -> Dict[str, str]:
    """Flat ``{param path: "logical names -> PartitionSpec @ shard
    shape"}`` map for dryrun/debug output — the human-readable view of
    where every weight actually lives on the mesh."""
    flat_p = jax.tree_util.tree_flatten_with_path(
        params, is_leaf=lambda x: hasattr(x, "sharding"))[0]
    flat_l = jax.tree_util.tree_flatten_with_path(
        logical_tree, is_leaf=lambda x: isinstance(x, tuple))[0]
    if len(flat_p) != len(flat_l):
        raise ValueError(
            f"params tree has {len(flat_p)} leaves but logical tree has "
            f"{len(flat_l)} — structures diverge (quantized trees and "
            "extra keys are not summarizable)")
    out: Dict[str, str] = {}
    for (path, leaf), (_, names) in zip(flat_p, flat_l):
        key = "/".join(str(getattr(k, "key", k)) for k in path)
        shard_shape = getattr(
            leaf.sharding, "shard_shape", lambda s: s)(leaf.shape)
        out[key] = (f"{names} -> {logical_spec(names)} "
                    f"@ {tuple(shard_shape)}")
    return out


def assert_params_sharded(params: Any, mesh: Mesh, logical_tree: Any,
                          ) -> None:
    """Verify every param leaf carries EXACTLY the NamedSharding its
    logical axis names prescribe — the "is the 2D story real" check the
    MULTICHIP dryrun and the CPU multi-device test both run. Raises
    AssertionError naming the first offending leaf."""
    expected = param_shardings(mesh, logical_tree)
    flat_p = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_e = jax.tree_util.tree_flatten_with_path(
        expected, is_leaf=lambda x: isinstance(x, NamedSharding))[0]
    # A silent zip truncation would let leaves after a structure
    # divergence go unchecked — in the function whose job is checking.
    assert len(flat_p) == len(flat_e), (
        f"params tree has {len(flat_p)} leaves but the logical tree "
        f"prescribes {len(flat_e)} — structures diverge")
    for (path, leaf), (_, want) in zip(flat_p, flat_e):
        key = "/".join(str(getattr(k, "key", k)) for k in path)
        got = getattr(leaf, "sharding", None)
        assert got is not None, f"{key}: leaf has no sharding"
        ok = got.is_equivalent_to(want, leaf.ndim) \
            if hasattr(got, "is_equivalent_to") else got == want
        assert ok, f"{key}: sharding {got} != expected {want}"
        # And the shards really are smaller than the array on >1-way axes.
        shard = got.shard_shape(leaf.shape)
        want_shard = want.shard_shape(leaf.shape)
        assert tuple(shard) == tuple(want_shard), (
            f"{key}: shard shape {shard} != expected {want_shard}")


def default_optimizer(lr: float = 3e-4, weight_decay: float = 0.1,
                      warmup: int = 100, decay_steps: int = 10000,
                      grad_clip: float = 1.0) -> optax.GradientTransformation:
    sched = optax.warmup_cosine_decay_schedule(0.0, lr, warmup, decay_steps,
                                               end_value=lr * 0.1)
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )
