"""Pipeline parallelism over the ``pp`` mesh axis (GPipe schedule).

TPU-first design: the pipeline is ONE jitted SPMD program, not N actors
exchanging activations (the reference-era pattern this replaces routes
stage hand-offs through host RPC; see also reference
dag/dag_node_operation.py:506 for its schedule machinery). Weights carry a
leading ``stages`` dim sharded over ``pp``; the activation rotor is a
[stages, ...] buffer likewise sharded, advanced by `jnp.roll` (XLA lowers
the stage shift to a collective-permute over ICI). Each tick every device
applies its OWN stage's layer block to its rotor slot — the classic GPipe
bubble of (stages-1) ticks at fill and drain, with microbatches streamed
through `lax.scan`.

Backward pass: plain autodiff through the scan — XLA emits the reverse
collective-permutes; per-tick remat keeps activation memory at
O(stages + microbatches) boundaries.

Numerical contract (tested): with the same weights, pipeline_forward ==
dense forward exactly — GPipe is a schedule, not an approximation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
from jax import lax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.models import llama
from ray_tpu.parallel.mesh import constrain

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    stages: int                   # == mesh.shape["pp"]
    microbatches: int             # batch must divide evenly

    def validate(self, cfg: llama.LlamaConfig, batch: int) -> None:
        if cfg.n_layers % self.stages:
            raise ValueError(
                f"n_layers={cfg.n_layers} not divisible by "
                f"stages={self.stages}")
        if batch % self.microbatches:
            raise ValueError(
                f"batch={batch} not divisible by "
                f"microbatches={self.microbatches}")
        if self.microbatches < self.stages:
            raise ValueError("need microbatches >= stages to fill the pipe")


def stage_params(params: Params, stages: int) -> Params:
    """Reshape stacked blocks [L, ...] -> [stages, L/stages, ...].

    The embed/ln_out/lm_head stay replicated-by-'pp' (they run outside the
    rotor). Use `pipeline_param_logical_axes` for the matching shardings.
    """
    blocks = params["blocks"]
    out = dict(params)
    out["blocks"] = {
        k: v.reshape((stages, v.shape[0] // stages) + v.shape[1:])
        for k, v in blocks.items()
    }
    return out


def pipeline_param_logical_axes(cfg: llama.LlamaConfig) -> Params:
    """Logical axes with the extra leading ``stages`` dim on blocks."""
    tree = llama.param_logical_axes(cfg)
    tree["blocks"] = {k: ("stages",) + v
                      for k, v in tree["blocks"].items()}
    return tree


def _apply_stage(stage_blocks: Params, x: jnp.ndarray,
                 positions: jnp.ndarray, cfg: llama.LlamaConfig):
    """Run one stage's layer group (scan over its layers) on x [mb,S,D]."""

    def body(h, layer):
        y, _ = llama._block(h, layer, positions, cfg, None,
                            standard_positions=True)
        return y, None

    if cfg.remat:
        body = jax.checkpoint(body, policy=llama._remat_policy(cfg))
    x, _ = lax.scan(body, x, stage_blocks)
    return x


def pipeline_forward_hidden(params: Params, tokens: jnp.ndarray,
                            cfg: llama.LlamaConfig, pcfg: PipelineConfig,
                            *, mesh: Optional[Mesh] = None) -> jnp.ndarray:
    """Tokens [B,S] -> final hidden [B,S,D] via the GPipe rotor.

    `params` must be stage-shaped (see `stage_params`).
    """
    b, s = tokens.shape
    pcfg.validate(cfg, b)
    S, M = pcfg.stages, pcfg.microbatches
    mb = b // M
    d = cfg.d_model
    positions = jnp.broadcast_to(jnp.arange(s), (mb, s))

    x = jnp.take(constrain(params["embed"], ("vocab", None)), tokens,
                 axis=0).astype(cfg.dtype)
    # Microbatch stream: [M, mb, S_len, D].
    stream = x.reshape(M, mb, s, d)

    # Rotor: slot i holds the activation currently owned by stage i.
    rotor = jnp.zeros((S, mb, s, d), cfg.dtype)
    rotor = constrain(rotor, ("stages", None, "seq", None))
    n_ticks = M + S - 1
    # vmap over the stage dim: each pp shard computes ITS stage only.
    stage_apply = jax.vmap(
        lambda blocks, act: _apply_stage(blocks, act, positions, cfg),
        in_axes=(0, 0))

    def tick(carry, t):
        rotor, outputs = carry
        # Feed: stage 0 receives microbatch t (zeros once drained — their
        # outputs are never collected).
        feed = lax.dynamic_index_in_dim(
            stream, jnp.minimum(t, M - 1), axis=0, keepdims=False)
        feed = jnp.where(t < M, feed, jnp.zeros_like(feed))
        rotor = rotor.at[0].set(feed)
        rotor = constrain(rotor, ("stages", None, "seq", None))
        rotor = stage_apply(params["blocks"], rotor)
        rotor = constrain(rotor, ("stages", None, "seq", None))
        # Collect: stage S-1 just finished microbatch t-(S-1).
        out_idx = t - (S - 1)
        outputs = lax.cond(
            out_idx >= 0,
            lambda o: lax.dynamic_update_index_in_dim(
                o, rotor[S - 1], jnp.maximum(out_idx, 0), axis=0),
            lambda o: o,
            outputs)
        # Advance: stage i's output becomes stage i+1's input (the roll is
        # XLA's collective-permute over pp).
        rotor = jnp.roll(rotor, 1, axis=0)
        return (rotor, outputs), None

    outputs = jnp.zeros((M, mb, s, d), cfg.dtype)
    (rotor, outputs), _ = lax.scan(tick, (rotor, outputs),
                                   jnp.arange(n_ticks))
    hidden = outputs.reshape(b, s, d)
    from ray_tpu.ops import rms_norm

    return rms_norm(hidden, params["ln_out"], cfg.norm_eps)


def pipeline_loss_fn(params: Params, tokens: jnp.ndarray,
                     cfg: llama.LlamaConfig, pcfg: PipelineConfig,
                     *, mesh: Optional[Mesh] = None) -> Tuple[jnp.ndarray, Dict]:
    """Next-token CE over the pipelined forward (same chunked-CE math as
    llama.loss_fn — reuses its head/target handling on our hidden)."""
    hidden = pipeline_forward_hidden(params, tokens, cfg, pcfg, mesh=mesh)
    return llama.loss_from_hidden(params, hidden, tokens, cfg)


def make_pipeline_train_step(cfg: llama.LlamaConfig, pcfg: PipelineConfig,
                             mesh: Mesh, tx):
    """Jitted (state, tokens) -> (state, metrics) over stage-shaped params
    (mirror of spmd.make_train_step for the pp axis)."""
    import optax

    from ray_tpu.parallel import spmd

    def step_fn(state, tokens):
        (loss, metrics), grads = jax.value_and_grad(
            pipeline_loss_fn, has_aux=True)(
                state.params, tokens, cfg, pcfg, mesh=mesh)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return spmd.TrainState(state.step + 1, new_params, opt_state), metrics

    return spmd._with_mesh_context(mesh, jax.jit(step_fn,
                                                 donate_argnums=(0,)))
