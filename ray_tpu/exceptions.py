"""Public exception hierarchy.

Parity with the reference's error surface (reference: python/ray/exceptions.py
and ErrorType in src/ray/protobuf/common.proto), flattened to the set the
libraries actually need.  Errors that occurred remotely are captured with a
formatted traceback and re-raised at the ``get`` site.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at the ray_tpu.get site.

    ``cause_repr`` carries the remote traceback text, so the original failure
    is readable even when the exception type could not be unpickled.
    """

    def __init__(self, exc_type_name: str, cause_repr: str, cause=None,
                 exc_type_mro=None):
        self.exc_type_name = exc_type_name
        self.cause_repr = cause_repr
        self.cause = cause
        # Class names along the original exception's MRO: when the cause
        # fails to unpickle at the retry site, isinstance checks against a
        # retry_exceptions policy still work by NAME over the ancestry
        # (ConnectionResetError retries under (ConnectionError,)).
        self.exc_type_mro = list(exc_type_mro or [exc_type_name])
        super().__init__(f"task failed with {exc_type_name}:\n{cause_repr}")

    def __reduce__(self):
        # Exception's default reduce would replay __init__ with the formatted
        # message as the only argument; rebuild from the real fields (the
        # cause may itself be unpicklable — drop it then).
        try:
            import cloudpickle

            cloudpickle.dumps(self.cause)
            cause = self.cause
        except Exception:
            cause = None
        return (TaskError, (self.exc_type_name, self.cause_repr, cause,
                            self.exc_type_mro))


class ActorError(RayTpuError):
    """Base for actor-related failures."""


class ActorDiedError(ActorError):
    def __init__(self, actor_id=None, reason: str = "actor died"):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"{reason} (actor={actor_id})")


class ActorUnavailableError(ActorError):
    """Actor is restarting or temporarily unreachable; call may be retried."""


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died (OOM kill, segfault, node loss)."""


class ObjectLostError(RayTpuError):
    """Object's primary copy was lost and could not be reconstructed."""

    def __init__(self, object_id=None, msg: str = "object lost"):
        self.object_id = object_id
        super().__init__(f"{msg} (object={object_id})")


class ObjectReconstructionFailedError(ObjectLostError):
    """Lineage reconstruction exhausted retries or lineage was evicted."""


class OwnerDiedError(ObjectLostError):
    """The owner process of this object died; value can never be resolved."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """ray_tpu.get(timeout=...) expired."""


class NodeDiedError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupUnschedulableError(RayTpuError):
    """No node (or set of nodes) can ever satisfy the bundle request."""


class TaskUnschedulableError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    """Raised to tasks killed by the memory monitor."""


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"task cancelled (task={task_id})")


class CrossLanguageError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    """Actor's max_pending_calls backpressure limit hit."""
