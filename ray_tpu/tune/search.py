"""Search spaces + the basic variant generator.

Parity target: reference python/ray/tune/search/sample.py (Categorical/
Float/Integer domains) + basic_variant.py (grid cross-product x
num_samples). Advanced searchers (hyperopt/optuna/...) are pluggable via
the same `suggest` seam but not bundled.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        if self.log:
            import math

            return math.exp(rng.uniform(math.log(self.lower),
                                        math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def generate_variants(param_space: Dict[str, Any], num_samples: int = 1,
                      seed: Optional[int] = None) -> List[Dict[str, Any]]:
    """Cross-product of every grid_search axis x num_samples random draws
    of the stochastic domains (reference BasicVariantGenerator)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items()
                 if isinstance(v, GridSearch)]
    grids = [param_space[k].values for k in grid_keys]
    variants: List[Dict[str, Any]] = []
    for combo in itertools.product(*grids) if grids else [()]:
        for _ in range(num_samples):
            cfg = {}
            for k, v in param_space.items():
                if isinstance(v, GridSearch):
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            variants.append(cfg)
    return variants
