"""Search spaces + the basic variant generator.

Parity target: reference python/ray/tune/search/sample.py (Categorical/
Float/Integer domains) + basic_variant.py (grid cross-product x
num_samples). Advanced searchers (hyperopt/optuna/...) are pluggable via
the same `suggest` seam but not bundled.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Optional


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        if self.log:
            import math

            return math.exp(rng.uniform(math.log(self.lower),
                                        math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def generate_variants(param_space: Dict[str, Any], num_samples: int = 1,
                      seed: Optional[int] = None) -> List[Dict[str, Any]]:
    """Cross-product of every grid_search axis x num_samples random draws
    of the stochastic domains (reference BasicVariantGenerator)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items()
                 if isinstance(v, GridSearch)]
    grids = [param_space[k].values for k in grid_keys]
    variants: List[Dict[str, Any]] = []
    for combo in itertools.product(*grids) if grids else [()]:
        for _ in range(num_samples):
            cfg = {}
            for k, v in param_space.items():
                if isinstance(v, GridSearch):
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            variants.append(cfg)
    return variants


# --------------------------------------------------------------------------
# Searchers (sequential model-based suggestion)
# --------------------------------------------------------------------------


class Searcher:
    """ABC for sequential config suggestion (reference:
    python/ray/tune/search/searcher.py Searcher — suggest /
    on_trial_complete; hyperopt/optuna plug in behind the same seam).
    The Tuner draws configs lazily from a searcher so every suggestion
    can condition on finished trials."""

    def set_search_properties(self, metric: str, mode: str,
                              param_space: Dict[str, Any]) -> None:
        for k, v in param_space.items():
            if isinstance(v, GridSearch):
                raise ValueError(
                    f"param {k!r}: grid_search axes are exhaustive, not "
                    f"suggestible — use tune.choice() with a searcher, or "
                    f"drop the searcher for grid execution")
        self.metric = metric
        self.mode = mode
        self.space = param_space

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        raise NotImplementedError

    # Snapshot/restore of the observation history (rides the experiment
    # state file; reference: searcher save/restore).
    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (the algorithm behind hyperopt's
    default searcher; reference integration surface:
    python/ray/tune/search/hyperopt/hyperopt_search.py).

    After ``n_initial`` random trials, observations split into the top
    ``gamma`` fraction (good) and the rest (bad). Candidates are drawn
    from the good-set density l(x) and ranked by l(x)/g(x): maximizing
    that ratio proposes configs that look like winners and unlike losers
    (Bergstra et al. 2011). Floats use Gaussian KDEs (log-space when the
    domain is log); integers round; categoricals use smoothed counts."""

    def __init__(self, *, n_initial: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._live: Dict[str, Dict[str, Any]] = {}
        self._obs: List[Dict[str, Any]] = []   # {"config", "score"}

    # ------------------------------------------------------------ state

    def get_state(self) -> Dict[str, Any]:
        return {"obs": self._obs}

    def set_state(self, state: Dict[str, Any]) -> None:
        self._obs = list(state.get("obs", []))

    # ---------------------------------------------------------- suggest

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        import math

        if len(self._obs) < self.n_initial:
            cfg = {k: (v.sample(self._rng) if isinstance(v, Domain) else v)
                   for k, v in self.space.items()}
            self._live[trial_id] = cfg
            return cfg
        # Split observations: maximize -> high scores are "good". The
        # good-set size grows ~ gamma*sqrt(n) (hyperopt's rule): a flat
        # top-25% dilutes the winners' density with mediocre points.
        ordered = sorted(self._obs, key=lambda o: o["score"],
                         reverse=(self.mode == "max"))
        n = len(ordered)
        n_good = min(max(2, round(4 * self.gamma * math.sqrt(n))), 25)
        good, bad = ordered[:n_good], ordered[n_good:] or ordered[:1]

        # Per-dimension TPE (matching hyperopt's independent-factor
        # model): draw candidates from the good density MIXED WITH THE
        # PRIOR (the mixture keeps exploration alive — a pure good-KDE
        # collapses onto early mediocre winners), rank by l(x)/g(x),
        # keep each dimension's argmax.
        cfg: Dict[str, Any] = {}
        for key, dom in self.space.items():
            if not isinstance(dom, Domain):
                cfg[key] = dom
                continue
            gvals = [o["config"][key] for o in good]
            bvals = [o["config"][key] for o in bad]
            if isinstance(dom, Categorical):
                cats = dom.categories
                gc = {c: 1.0 for c in cats}
                for v in gvals:
                    gc[v] = gc.get(v, 1.0) + 1.0
                bc = {c: 1.0 for c in cats}
                for w in bvals:
                    bc[w] = bc.get(w, 1.0) + 1.0
                gtot, btot = sum(gc.values()), sum(bc.values())
                # Sample candidates from the good distribution, keep the
                # best ratio (sampling, not argmax over all categories:
                # preserves stochasticity across parallel suggests).
                best_c, best_r = None, -math.inf
                for _ in range(self.n_candidates):
                    c = self._rng.choices(
                        cats, weights=[gc[x] for x in cats])[0]
                    r = math.log(gc[c] / gtot) - math.log(bc[c] / btot)
                    if r > best_r:
                        best_c, best_r = c, r
                cfg[key] = best_c
                continue
            log_space = isinstance(dom, Float) and dom.log
            xform = math.log if log_space else (lambda z: z)
            lo_d = xform(float(dom.lower))
            hi_d = xform(float(dom.upper if isinstance(dom, Float)
                               else dom.upper - 1))
            width = max(hi_d - lo_d, 1e-12)
            gx = [xform(float(v)) for v in gvals]
            bx = [xform(float(v)) for v in bvals]

            def bandwidths(samples: List[float]) -> List[float]:
                """Per-sample bandwidth = spacing to adjacent samples
                (hyperopt's adaptive-parzen rule): kernels SHRINK as
                points concentrate, so refinement is unbounded, while
                isolated points keep wide kernels for exploration."""
                order = sorted(range(len(samples)),
                               key=lambda i: samples[i])
                srt = [samples[i] for i in order]
                bws = [0.0] * len(samples)
                for pos, i in enumerate(order):
                    left = srt[pos] - srt[pos - 1] if pos > 0 else width
                    right = (srt[pos + 1] - srt[pos]
                             if pos + 1 < len(srt) else width)
                    bws[i] = min(max(max(left, right), width / 100.0),
                                 width)
                return bws

            gbws = bandwidths(gx)
            bbws = bandwidths(bx)

            def logpdf(x: float, samples: List[float],
                       bws: List[float]) -> float:
                # MEAN kernel density blended with a uniform prior, no
                # count asymmetry: normalizing l by n_good and g by n_bad
                # hands every EMPTY region a constant ratio advantage of
                # log((n_bad+1)/(n_good+1)) and the argmax degenerates to
                # uniform exploration.
                n = max(1, len(samples))
                acc = 0.0
                for s, bw in zip(samples, bws):
                    acc += math.exp(-0.5 * ((x - s) / bw) ** 2) / (
                        bw * 2.5066282746310002)
                dens = 0.9 * (acc / n) + 0.1 / width
                return math.log(max(dens, 1e-300))

            best_x, best_r = None, -math.inf
            for _ in range(self.n_candidates):
                if self._rng.random() < 1.0 / (len(gx) + 1):
                    x = self._rng.uniform(lo_d, hi_d)  # prior draw
                else:
                    i = self._rng.randrange(len(gx))
                    x = self._rng.gauss(gx[i], gbws[i])
                    x = min(max(x, lo_d), hi_d)
                if isinstance(dom, Integer):
                    x = float(int(round(x)))
                r = logpdf(x, gx, gbws) - logpdf(x, bx, bbws)
                if r > best_r:
                    best_x, best_r = x, r
            if log_space:
                # exp(log(bound)) can land an ulp outside the domain.
                cfg[key] = min(max(math.exp(best_x), dom.lower),
                               dom.upper)
            elif isinstance(dom, Integer):
                cfg[key] = min(max(int(best_x), dom.lower), dom.upper - 1)
            else:
                cfg[key] = min(max(best_x, dom.lower), dom.upper)
        self._live[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        cfg = self._live.pop(trial_id, None)
        if cfg is None or result is None:
            return
        score = result.get(self.metric)
        if score is None:
            return
        self._obs.append({"config": cfg, "score": float(score)})


class BOHBSearcher(TPESearcher):
    """BOHB's model component (Falkner et al. 2018), for pairing with
    HyperBandScheduler (reference: python/ray/tune/search/bohb/
    bohb_search.py + schedulers/hb_bohb.py).

    Multi-fidelity twist on TPE: each observation records the budget
    (training_iteration) the trial reached — HyperBand stops losers at
    low rungs, so completions arrive at mixed fidelities. Suggestions are
    modeled on the HIGHEST budget tier that has accumulated ``n_initial``
    observations (higher-fidelity scores are more trustworthy); until any
    tier has enough, sampling stays random."""

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        cfg = self._live.pop(trial_id, None)
        if cfg is None or result is None:
            return
        score = result.get(self.metric)
        if score is None:
            return
        self._obs.append({
            "config": cfg, "score": float(score),
            "budget": int(result.get("training_iteration", 0) or 0),
        })

    def _model_obs(self) -> List[Dict[str, Any]]:
        budgets = sorted({o.get("budget", 0) for o in self._obs},
                         reverse=True)
        for b in budgets:
            sub = [o for o in self._obs if o.get("budget", 0) >= b]
            if len(sub) >= self.n_initial:
                return sub
        return []

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        full = self._obs
        self._obs = self._model_obs()
        try:
            return super().suggest(trial_id)
        finally:
            self._obs = full


class OptunaSearch(Searcher):
    """Optuna adapter over the Searcher seam (reference:
    python/ray/tune/search/optuna/optuna_search.py OptunaSearch —
    ask/tell against an optuna Study). Lazily creates the study at the
    first suggest (direction needs the mode, which arrives via
    set_search_properties). ``optuna`` (or any object with its
    create_study/ask/tell surface, e.g. a test double) can be injected
    via ``optuna_module`` — the import is gated so the tune package
    never hard-depends on it."""

    def __init__(self, sampler: Any = None, seed: Optional[int] = None,
                 optuna_module: Any = None):
        self._optuna = optuna_module
        self._sampler = sampler
        self._seed = seed
        self._study = None
        self._live: Dict[str, Any] = {}

    def _ensure_study(self):
        if self._study is not None:
            return
        ot = self._optuna
        if ot is None:
            try:
                import optuna as ot  # type: ignore
            except ImportError as e:
                raise ImportError(
                    "OptunaSearch requires the `optuna` package (pass "
                    "optuna_module=... to inject a compatible object)"
                ) from e
            self._optuna = ot
        sampler = self._sampler
        if sampler is None and self._seed is not None:
            try:
                sampler = ot.samplers.TPESampler(seed=self._seed)
            except Exception:
                sampler = None
        direction = "minimize" if self.mode == "min" else "maximize"
        self._study = ot.create_study(direction=direction,
                                      sampler=sampler)

    def _suggest_param(self, trial, name: str, dom: Any):
        if isinstance(dom, Categorical):
            return trial.suggest_categorical(name, dom.categories)
        if isinstance(dom, Float):
            return trial.suggest_float(name, dom.lower, dom.upper,
                                       log=dom.log)
        if isinstance(dom, Integer):
            return trial.suggest_int(name, dom.lower, dom.upper - 1)
        return dom  # literal values pass through

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        self._ensure_study()
        t = self._study.ask()
        self._live[trial_id] = t
        return {k: self._suggest_param(t, k, v)
                for k, v in self.space.items()}

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        t = self._live.pop(trial_id, None)
        if t is None or self._study is None:
            return
        value = None if result is None else result.get(self.metric)
        if value is None:
            try:
                state = self._optuna.trial.TrialState.FAIL
                self._study.tell(t, state=state)
            except Exception:
                pass
            return
        self._study.tell(t, float(value))
