"""Tuner: concurrent trial orchestration over actors.

Parity target: reference python/ray/tune/tuner.py (Tuner.fit :344) +
execution/tune_controller.py (:666 step loop): trials are actors running
the user trainable with a report session; the controller caps concurrency,
feeds every report to the scheduler, stops losers early, and collects a
ResultGrid. Function trainables call `ray_tpu.tune.report(metrics)` per
iteration (same session machinery as ray_tpu.train).
"""

from __future__ import annotations

import dataclasses
import os
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.config import TrainContextConfig
from ray_tpu.train.session import TrainSession
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.search import generate_variants


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"                   # "max" | "min"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Optional[Any] = None     # FIFOScheduler | ASHAScheduler
    seed: Optional[int] = None


@dataclasses.dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Optional[Dict[str, Any]]       # last reported
    history: List[Dict[str, Any]]
    error: Optional[str] = None
    stopped_early: bool = False


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self) -> int:
        return len(self._results)

    def __iter__(self):
        return iter(self._results)

    def __getitem__(self, i: int) -> TrialResult:
        return self._results[i]

    @property
    def errors(self) -> List[TrialResult]:
        return [r for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("specify metric= (none set in TuneConfig)")
        scored = [r for r in self._results
                  if r.metrics and metric in r.metrics]
        if not scored:
            raise RuntimeError("no trial reported the metric "
                               f"{metric!r}")
        key = lambda r: float(r.metrics[metric])  # noqa: E731
        return (max if mode == "max" else min)(scored, key=key)

    def get_dataframe(self) -> List[Dict[str, Any]]:
        return [dict(r.metrics or {}, trial_id=r.trial_id, **{
            f"config/{k}": v for k, v in r.config.items()})
            for r in self._results]


class TrialActor:
    """Hosts one trial: the trainable runs under a report session."""

    def __init__(self):
        self._session: Optional[TrainSession] = None

    def start(self, trainable: Callable, config: Dict[str, Any],
              trial_id: str) -> None:
        ctx = TrainContextConfig(world_size=1, world_rank=0,
                                 experiment_path=trial_id,
                                 trial_info={"trial_id": trial_id,
                                             "config": config})

        def runner(cfg):
            out = trainable(cfg)
            # Return-style trainables: a returned dict is the final report.
            if isinstance(out, dict):
                from ray_tpu.train.session import _require_session

                _require_session().report(out)

        self._session = TrainSession(runner, config, ctx)
        self._session.start()

    def poll(self, timeout: float = 1.0):
        r = self._session.poll(timeout)
        if r is None:
            return None
        if r.done:
            out = {"done": True}
            if r.error is not None:
                exc, tb = r.error
                out["error"] = f"{type(exc).__name__}: {exc}"
            return out
        return {"done": False, "metrics": r.metrics}


@dataclasses.dataclass
class _Trial:
    trial_id: str
    config: Dict[str, Any]
    actor: Any = None
    history: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    iteration: int = 0
    done: bool = False
    error: Optional[str] = None
    stopped_early: bool = False


class Tuner:
    def __init__(self, trainable: Callable, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[Any] = None):
        self._trainable = trainable
        self._space = param_space or {}
        self._cfg = tune_config or TuneConfig()
        self._run_config = run_config

    def fit(self) -> ResultGrid:
        cfg = self._cfg
        scheduler = cfg.scheduler or sched_mod.FIFOScheduler()
        variants = generate_variants(self._space, cfg.num_samples, cfg.seed)
        trials = [_Trial(f"trial_{i:04d}_{uuid.uuid4().hex[:6]}", v)
                  for i, v in enumerate(variants)]
        pending = list(trials)
        running: List[_Trial] = []
        actor_cls = ray_tpu.remote(TrialActor)

        while pending or running:
            while pending and len(running) < cfg.max_concurrent_trials:
                t = pending.pop(0)
                try:
                    t.actor = actor_cls.options(num_cpus=1).remote()
                    ray_tpu.get(t.actor.start.remote(
                        self._trainable, t.config, t.trial_id), timeout=120)
                except Exception as e:
                    # Cluster can't host another concurrent trial right
                    # now: requeue and run at the concurrency that fits —
                    # unless nothing at all is running (then it never
                    # will; fail the trial instead of spinning).
                    if t.actor is not None:
                        try:
                            ray_tpu.kill(t.actor)
                        except Exception:
                            pass
                        t.actor = None
                    if running:
                        pending.insert(0, t)
                        break
                    t.done = True
                    t.error = f"could not schedule trial: {e}"
                    continue
                running.append(t)
            polls = [(t, t.actor.poll.remote(1.0)) for t in running]
            round_results = []
            for t, ref in polls:
                try:
                    r = ray_tpu.get(ref, timeout=60)
                except Exception as e:
                    t.done, t.error = True, f"trial actor died: {e}"
                    continue
                if r is None:
                    continue
                if r.get("done"):
                    t.done = True
                    t.error = r.get("error")
                    continue
                t.iteration += 1
                t.history.append(r["metrics"])
                round_results.append((t, r["metrics"]))
            # Whole round to the scheduler at once (batch-synchronous):
            # the lockstep polling order must not decide rung survival.
            if round_results:
                decisions = scheduler.on_batch(
                    [(t.trial_id, t.iteration, m)
                     for t, m in round_results])
                for t, _m in round_results:
                    if decisions.get(t.trial_id) == sched_mod.STOP:
                        t.done = True
                        t.stopped_early = True
            for t in [t for t in running if t.done]:
                running.remove(t)
                try:
                    ray_tpu.kill(t.actor)
                except Exception:
                    pass

        results = [TrialResult(
            trial_id=t.trial_id, config=t.config,
            metrics=t.history[-1] if t.history else None,
            history=t.history, error=t.error,
            stopped_early=t.stopped_early) for t in trials]
        return ResultGrid(results, cfg.metric, cfg.mode)
