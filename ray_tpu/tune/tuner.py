"""Tuner: concurrent trial orchestration over actors.

Parity target: reference python/ray/tune/tuner.py (Tuner.fit :344) +
execution/tune_controller.py (:666 step loop): trials are actors running
the user trainable with a report session; the controller caps concurrency,
feeds every report to the scheduler, stops losers early, and collects a
ResultGrid. Function trainables call `ray_tpu.tune.report(metrics)` per
iteration (same session machinery as ray_tpu.train).
"""

from __future__ import annotations

import dataclasses
import os
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.config import TrainContextConfig
from ray_tpu.train.session import TrainSession
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.search import generate_variants


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"                   # "max" | "min"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Optional[Any] = None     # FIFOScheduler | ASHAScheduler | ...
    #: sequential searcher (TPESearcher, ...); None = random/grid variants
    search_alg: Optional[Any] = None
    seed: Optional[int] = None


@dataclasses.dataclass
class TrialResult:
    trial_id: str
    config: Dict[str, Any]
    metrics: Optional[Dict[str, Any]]       # last reported
    history: List[Dict[str, Any]]
    error: Optional[str] = None
    stopped_early: bool = False


class ResultGrid:
    def __init__(self, results: List[TrialResult], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self) -> int:
        return len(self._results)

    def __iter__(self):
        return iter(self._results)

    def __getitem__(self, i: int) -> TrialResult:
        return self._results[i]

    @property
    def errors(self) -> List[TrialResult]:
        return [r for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("specify metric= (none set in TuneConfig)")
        scored = [r for r in self._results
                  if r.metrics and metric in r.metrics]
        if not scored:
            raise RuntimeError("no trial reported the metric "
                               f"{metric!r}")
        key = lambda r: float(r.metrics[metric])  # noqa: E731
        return (max if mode == "max" else min)(scored, key=key)

    def get_dataframe(self) -> List[Dict[str, Any]]:
        return [dict(r.metrics or {}, trial_id=r.trial_id, **{
            f"config/{k}": v for k, v in r.config.items()})
            for r in self._results]


class TrialActor:
    """Hosts one trial: the trainable runs under a report session."""

    def __init__(self):
        self._session: Optional[TrainSession] = None

    def start(self, trainable: Callable, config: Dict[str, Any],
              trial_id: str,
              checkpoint_path: Optional[str] = None) -> None:
        from ray_tpu.train.checkpoint import Checkpoint

        ctx = TrainContextConfig(world_size=1, world_rank=0,
                                 experiment_path=trial_id,
                                 trial_info={"trial_id": trial_id,
                                             "config": config})

        def runner(cfg):
            out = trainable(cfg)
            # Return-style trainables: a returned dict is the final report.
            if isinstance(out, dict):
                from ray_tpu.train.session import _require_session

                _require_session().report(out)

        self._session = TrainSession(
            runner, config, ctx,
            checkpoint=Checkpoint(checkpoint_path) if checkpoint_path
            else None)
        self._session.start()

    def poll(self, timeout: float = 1.0):
        r = self._session.poll(timeout)
        if r is None:
            return None
        if r.done:
            out = {"done": True}
            if r.error is not None:
                exc, tb = r.error
                out["error"] = f"{type(exc).__name__}: {exc}"
            return out
        return {"done": False, "metrics": r.metrics,
                "checkpoint_path": r.checkpoint_path}


@dataclasses.dataclass
class _Trial:
    trial_id: str
    config: Dict[str, Any]
    actor: Any = None
    history: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    iteration: int = 0
    done: bool = False
    error: Optional[str] = None
    stopped_early: bool = False
    latest_checkpoint: Optional[str] = None
    perturbs: int = 0


class Tuner:
    def __init__(self, trainable: Callable, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[Any] = None):
        self._trainable = trainable
        self._space = param_space or {}
        self._cfg = tune_config or TuneConfig()
        self._run_config = run_config
        self._restored_trials: Optional[List[_Trial]] = None
        self._searcher_state: Optional[Dict[str, Any]] = None

    # ------------------------------------------------- experiment state

    def _experiment_dir(self) -> Optional[str]:
        rc = self._run_config
        if rc is None or getattr(rc, "storage_path", None) is None:
            return None
        name = getattr(rc, "name", None) or "tune_experiment"
        return os.path.join(rc.storage_path, name)

    def _snapshot(self, trials: List["_Trial"], searcher=None) -> None:
        """Atomic experiment-state snapshot after every round (reference:
        python/ray/tune/execution/experiment_state.py checkpointing) —
        a killed driver restores with Tuner.restore(). Searcher
        observation state rides along (reference: searcher save/restore)
        so a resumed BO experiment keeps its model."""
        path = self._experiment_dir()
        if path is None:
            return
        import json

        os.makedirs(path, exist_ok=True)
        state = {"trials": [{
            "trial_id": t.trial_id, "config": t.config,
            "history": t.history, "iteration": t.iteration,
            "done": t.done, "error": t.error,
            "stopped_early": t.stopped_early,
            "latest_checkpoint": t.latest_checkpoint,
            "perturbs": t.perturbs,
        } for t in trials]}
        if searcher is not None:
            try:
                state["searcher"] = searcher.get_state()
            except Exception:
                pass
        tmp = os.path.join(path, ".experiment_state.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, os.path.join(path, "experiment_state.json"))

    @classmethod
    def restore(cls, path: str, trainable: Callable, *,
                tune_config: Optional[TuneConfig] = None,
                run_config: Optional[Any] = None) -> "Tuner":
        """Resume an interrupted experiment: finished trials keep their
        results; unfinished ones restart from their latest checkpoint
        (the trainable resumes via tune.get_checkpoint())."""
        import json

        with open(os.path.join(path, "experiment_state.json")) as f:
            state = json.load(f)
        tuner = cls(trainable, tune_config=tune_config,
                    run_config=run_config)
        trials = []
        for ts in state["trials"]:
            t = _Trial(ts["trial_id"], ts["config"],
                       history=list(ts["history"]),
                       iteration=ts["iteration"], done=ts["done"],
                       error=ts.get("error"),
                       stopped_early=ts.get("stopped_early", False),
                       latest_checkpoint=ts.get("latest_checkpoint"),
                       perturbs=ts.get("perturbs", 0))
            trials.append(t)
        tuner._restored_trials = trials
        tuner._searcher_state = state.get("searcher")
        return tuner

    def fit(self) -> ResultGrid:
        cfg = self._cfg
        scheduler = cfg.scheduler or sched_mod.FIFOScheduler()
        searcher = cfg.search_alg
        if searcher is not None:
            searcher.set_search_properties(cfg.metric, cfg.mode,
                                           self._space)
            if self._searcher_state:
                searcher.set_state(self._searcher_state)
        if self._restored_trials is not None:
            trials = self._restored_trials
        elif searcher is not None:
            trials = []  # created lazily: each suggest sees prior results
        else:
            variants = generate_variants(self._space, cfg.num_samples,
                                         cfg.seed)
            trials = [_Trial(f"trial_{i:04d}_{uuid.uuid4().hex[:6]}", v)
                      for i, v in enumerate(variants)]
        register = getattr(scheduler, "register", None)
        if register is not None:
            for t in trials:
                register(t.trial_id, t.config)
        pending = [t for t in trials if not t.done]
        running: List[_Trial] = []
        created = len(trials)
        reported_done: set = set()
        actor_cls = ray_tpu.remote(TrialActor)

        def can_create() -> bool:
            return searcher is not None and created < cfg.num_samples

        while pending or running or can_create():
            while ((pending or can_create())
                   and len(running) < cfg.max_concurrent_trials):
                if pending:
                    t = pending.pop(0)
                else:
                    trial_id = (f"trial_{created:04d}_"
                                f"{uuid.uuid4().hex[:6]}")
                    t = _Trial(trial_id, searcher.suggest(trial_id))
                    trials.append(t)
                    created += 1
                    if register is not None:
                        register(t.trial_id, t.config)
                try:
                    t.actor = actor_cls.options(num_cpus=1).remote()
                    ray_tpu.get(t.actor.start.remote(
                        self._trainable, t.config, t.trial_id,
                        t.latest_checkpoint), timeout=120)
                except Exception as e:
                    # Cluster can't host another concurrent trial right
                    # now: requeue and run at the concurrency that fits —
                    # unless nothing at all is running (then it never
                    # will; fail the trial instead of spinning).
                    if t.actor is not None:
                        try:
                            ray_tpu.kill(t.actor)
                        except Exception:
                            pass
                        t.actor = None
                    if running:
                        pending.insert(0, t)
                        break
                    t.done = True
                    t.error = f"could not schedule trial: {e}"
                    continue
                running.append(t)
            polls = [(t, t.actor.poll.remote(1.0)) for t in running]
            round_results = []
            for t, ref in polls:
                try:
                    r = ray_tpu.get(ref, timeout=60)
                except Exception as e:
                    t.done, t.error = True, f"trial actor died: {e}"
                    continue
                if r is None:
                    continue
                if r.get("done"):
                    t.done = True
                    t.error = r.get("error")
                    continue
                t.iteration += 1
                t.history.append(r["metrics"])
                if r.get("checkpoint_path"):
                    t.latest_checkpoint = r["checkpoint_path"]
                round_results.append((t, r["metrics"]))
            # Whole round to the scheduler at once (batch-synchronous):
            # the lockstep polling order must not decide rung survival.
            if round_results:
                decisions = scheduler.on_batch(
                    [(t.trial_id, t.iteration, m)
                     for t, m in round_results])
                by_id = {t.trial_id: t for t in trials}
                # Apply EVERY decision, not just this round's reporters:
                # cohort schedulers (HyperBand) judge stragglers when the
                # cohort completes a rung, stopping trials that reported
                # in EARLIER rounds.
                for tid, d in decisions.items():
                    t = by_id.get(tid)
                    if (t is not None and not t.done
                            and d == sched_mod.STOP
                            and all(t is not rt_ for rt_, _m
                                    in round_results)):
                        t.done = True
                        t.stopped_early = True
                for t, _m in round_results:
                    d = decisions.get(t.trial_id)
                    if d == sched_mod.STOP:
                        t.done = True
                        t.stopped_early = True
                    elif isinstance(d, dict) and d.get("action") == "clone":
                        # PBT exploit+explore: restart this trial from the
                        # SOURCE trial's checkpoint with the explored
                        # config (reference: pbt.py _exploit).
                        source = by_id.get(d["source"])
                        src_ckpt = (source.latest_checkpoint
                                    if source else None)
                        try:
                            ray_tpu.kill(t.actor)
                        except Exception:
                            pass
                        t.config = d["config"]
                        t.perturbs += 1
                        if src_ckpt:
                            t.latest_checkpoint = src_ckpt
                        try:
                            t.actor = actor_cls.options(
                                num_cpus=1).remote()
                            ray_tpu.get(t.actor.start.remote(
                                self._trainable, t.config, t.trial_id,
                                t.latest_checkpoint), timeout=120)
                        except Exception as e:
                            t.done = True
                            t.error = f"PBT clone restart failed: {e}"
            for t in [t for t in running if t.done]:
                running.remove(t)
                try:
                    ray_tpu.kill(t.actor)
                except Exception:
                    pass
            sched_complete = getattr(scheduler, "on_trial_complete", None)
            for t in trials:
                if t.done and t.trial_id not in reported_done:
                    reported_done.add(t.trial_id)
                    if searcher is not None:
                        # Merge the fidelity reached into the final result
                        # (reports carry only user metrics): multi-fidelity
                        # searchers (BOHB) tier observations by it.
                        last = None
                        if t.history:
                            last = dict(t.history[-1])
                            last.setdefault("training_iteration",
                                            t.iteration)
                        searcher.on_trial_complete(t.trial_id, last)
                    if sched_complete is not None:
                        # Cohort schedulers must drop terminal trials
                        # from readiness checks (a dead peer would block
                        # its bracket's halving forever).
                        sched_complete(t.trial_id)
            self._snapshot(trials, searcher)

        results = [TrialResult(
            trial_id=t.trial_id, config=t.config,
            metrics=t.history[-1] if t.history else None,
            history=t.history, error=t.error,
            stopped_early=t.stopped_early) for t in trials]
        return ResultGrid(results, cfg.metric, cfg.mode)
