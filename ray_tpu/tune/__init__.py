"""ray_tpu.tune: hyperparameter search over concurrent trial actors.

Parity target: the reference Ray Tune surface (python/ray/tune/__init__ —
Tuner/TuneConfig/report/search spaces/schedulers), orchestration-only over
this runtime's actors: trials run the user trainable under a report
session; ASHA prunes losers at successive-halving rungs.
"""

from ray_tpu.train.session import get_checkpoint, report  # session API
from ray_tpu.tune.schedulers import (ASHAScheduler, FIFOScheduler,
                                     HyperBandScheduler,
                                     MedianStoppingRule, PB2,
                                     PopulationBasedTraining)
from ray_tpu.tune.search import (BOHBSearcher, OptunaSearch,
                                 Searcher, TPESearcher,
                                 choice, grid_search, loguniform,
                                 randint, uniform)
from ray_tpu.tune.tuner import (ResultGrid, TrialResult, TuneConfig, Tuner)

__all__ = [
    "ASHAScheduler", "BOHBSearcher", "FIFOScheduler", "HyperBandScheduler",
    "MedianStoppingRule", "PB2",
    "PopulationBasedTraining", "OptunaSearch", "Searcher", "TPESearcher",
    "ResultGrid", "TrialResult", "TuneConfig", "Tuner", "choice",
    "get_checkpoint", "grid_search", "loguniform", "randint", "report",
    "uniform",
]
