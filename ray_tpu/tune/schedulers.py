"""Trial schedulers: FIFO and ASHA early stopping.

Parity target: reference python/ray/tune/schedulers/async_hyperband.py
(ASHAScheduler) — asynchronous successive halving: at each rung
(iteration r, r*eta, r*eta^2, ...) a trial survives only if its metric is
in the top 1/eta of results recorded AT that rung so far.
"""

from __future__ import annotations

from typing import Any, Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, iteration: int,
                  metrics: Dict[str, Any]) -> str:
        return CONTINUE

    def on_batch(self, results) -> Dict[str, str]:
        return {trial_id: CONTINUE for trial_id, _i, _m in results}


class ASHAScheduler:
    def __init__(self, metric: str, mode: str = "max",
                 grace_period: int = 1, reduction_factor: int = 3,
                 max_t: int = 100):
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.eta = reduction_factor
        self.max_t = max_t
        # rung iteration -> list of recorded metric values
        self._rungs: Dict[int, List[float]] = {}
        r = grace_period
        self._rung_levels = []
        while r < max_t:
            self._rung_levels.append(r)
            r *= reduction_factor

    def _score(self, metrics: Dict[str, Any]) -> float:
        v = float(metrics[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, iteration: int,
                  metrics: Dict[str, Any]) -> str:
        return self.on_batch([(trial_id, iteration, metrics)])[trial_id]

    def on_batch(self, results) -> Dict[str, str]:
        """Batch-synchronous halving: record EVERY result of the round at
        its rung first, then judge each against the updated cutoff — a
        lockstep tuner feeding results one-by-one would otherwise prune by
        arrival order, not by score."""
        decisions: Dict[str, str] = {}
        judge = []
        for trial_id, iteration, metrics in results:
            if iteration >= self.max_t:
                decisions[trial_id] = STOP
                continue
            if iteration not in self._rung_levels:
                decisions[trial_id] = CONTINUE
                continue
            score = self._score(metrics)
            rung = self._rungs.setdefault(iteration, [])
            rung.append(score)
            judge.append((trial_id, iteration, score))
        for trial_id, iteration, score in judge:
            rung = sorted(self._rungs[iteration], reverse=True)
            # Top 1/eta of everything recorded at this rung survives
            # (ceil: a 2-entry rung at eta=2 keeps 1, a 4-entry keeps 2).
            k = max(1, -(-len(rung) // self.eta))
            decisions[trial_id] = (CONTINUE if score >= rung[k - 1]
                                   else STOP)
        return decisions


class PopulationBasedTraining:
    """PBT (reference: python/ray/tune/schedulers/pbt.py:221
    PopulationBasedTraining): at each perturbation interval, bottom-
    quantile trials EXPLOIT a top-quantile trial (clone its checkpoint +
    config) and EXPLORE (mutate hyperparameters). Decisions come back as
    {"action": "clone", "source": trial_id, "config": {...}} entries the
    Tuner applies by restarting the trial from the source's checkpoint.
    """

    def __init__(self, metric: str, mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Dict[str, Any] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: int = 0):
        import random as _random

        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        if not hyperparam_mutations:
            raise ValueError("hyperparam_mutations must be non-empty")
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self._rng = _random.Random(seed)
        self._configs: Dict[str, Dict[str, Any]] = {}
        self._latest: Dict[str, float] = {}

    # The Tuner registers configs so explore() can mutate them.
    def register(self, trial_id: str, config: Dict[str, Any]) -> None:
        self._configs[trial_id] = dict(config)

    def _score(self, metrics: Dict[str, Any]) -> float:
        v = float(metrics[self.metric])
        return v if self.mode == "max" else -v

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """Mutate each listed hyperparameter: resample with probability
        resample_probability, else perturb x1.2 / x0.8 (numeric) or step
        to a neighboring option (categorical) — reference explore()."""
        out = dict(config)
        for key, spec in self.mutations.items():
            cur = out.get(key)
            if self._rng.random() < self.resample_p or cur is None:
                if callable(spec):
                    out[key] = spec()
                elif isinstance(spec, (list, tuple)):
                    out[key] = self._rng.choice(list(spec))
                elif hasattr(spec, "sample"):
                    out[key] = spec.sample(self._rng)
                continue
            if isinstance(spec, (list, tuple)) and cur in spec:
                idx = list(spec).index(cur)
                step = self._rng.choice([-1, 1])
                out[key] = list(spec)[max(0, min(len(spec) - 1, idx + step))]
            elif isinstance(cur, (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                out[key] = type(cur)(cur * factor)
        return out

    def on_batch(self, results) -> Dict[str, Any]:
        decisions: Dict[str, Any] = {}
        for trial_id, _it, metrics in results:
            if self.metric in metrics:
                self._latest[trial_id] = self._score(metrics)
            decisions[trial_id] = CONTINUE
        ranked = sorted(self._latest.items(), key=lambda kv: kv[1])
        n = len(ranked)
        if n < 2:
            return decisions
        k = max(1, int(n * self.quantile))
        bottom = {tid for tid, _s in ranked[:k]}
        top = [tid for tid, _s in ranked[-k:]]
        for trial_id, iteration, _metrics in results:
            if (trial_id in bottom and iteration > 0
                    and iteration % self.interval == 0):
                source = self._rng.choice(top)
                if source == trial_id:
                    continue
                new_config = self._explore(self._configs.get(source, {}))
                self._configs[trial_id] = new_config
                decisions[trial_id] = {"action": "clone", "source": source,
                                       "config": new_config}
        return decisions


class MedianStoppingRule:
    """Median stopping (reference: python/ray/tune/schedulers/
    median_stopping_rule.py): a trial stops when its best metric so far
    falls below the MEDIAN of other trials' running-average metric at the
    same iteration — a gentle prune that needs no rung schedule.

    Guards: no stops before `min_samples_required` trials have reported
    at an iteration, nor before `grace_period` iterations of the trial
    itself (fresh trials get time to warm up)."""

    def __init__(self, metric: str, mode: str = "max",
                 grace_period: int = 2, min_samples_required: int = 3):
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        # trial_id -> list of scores per reported iteration
        self._scores: Dict[str, List[float]] = {}

    def _score(self, metrics: Dict[str, Any]) -> float:
        v = float(metrics[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, iteration: int,
                  metrics: Dict[str, Any]) -> str:
        return self.on_batch([(trial_id, iteration, metrics)])[trial_id]

    def on_batch(self, results) -> Dict[str, str]:
        decisions: Dict[str, str] = {}
        for trial_id, _iteration, metrics in results:
            self._scores.setdefault(trial_id, []).append(
                self._score(metrics))
        for trial_id, iteration, _metrics in results:
            mine = self._scores[trial_id]
            if iteration < self.grace:
                decisions[trial_id] = CONTINUE
                continue
            t = len(mine)
            # Other trials' RUNNING AVERAGE over their first t reports.
            others = [sum(s[:t]) / min(t, len(s))
                      for tid, s in self._scores.items()
                      if tid != trial_id and s]
            if len(others) < self.min_samples:
                decisions[trial_id] = CONTINUE
                continue
            others.sort()
            mid = len(others) // 2
            median = (others[mid] if len(others) % 2
                      else 0.5 * (others[mid - 1] + others[mid]))
            best = max(mine)
            decisions[trial_id] = CONTINUE if best >= median else STOP
        return decisions
