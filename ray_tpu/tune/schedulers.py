"""Trial schedulers: FIFO and ASHA early stopping.

Parity target: reference python/ray/tune/schedulers/async_hyperband.py
(ASHAScheduler) — asynchronous successive halving: at each rung
(iteration r, r*eta, r*eta^2, ...) a trial survives only if its metric is
in the top 1/eta of results recorded AT that rung so far.
"""

from __future__ import annotations

from typing import Any, Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, iteration: int,
                  metrics: Dict[str, Any]) -> str:
        return CONTINUE

    def on_batch(self, results) -> Dict[str, str]:
        return {trial_id: CONTINUE for trial_id, _i, _m in results}


class ASHAScheduler:
    def __init__(self, metric: str, mode: str = "max",
                 grace_period: int = 1, reduction_factor: int = 3,
                 max_t: int = 100):
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.eta = reduction_factor
        self.max_t = max_t
        # rung iteration -> list of recorded metric values
        self._rungs: Dict[int, List[float]] = {}
        r = grace_period
        self._rung_levels = []
        while r < max_t:
            self._rung_levels.append(r)
            r *= reduction_factor

    def _score(self, metrics: Dict[str, Any]) -> float:
        v = float(metrics[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, iteration: int,
                  metrics: Dict[str, Any]) -> str:
        return self.on_batch([(trial_id, iteration, metrics)])[trial_id]

    def on_batch(self, results) -> Dict[str, str]:
        """Batch-synchronous halving: record EVERY result of the round at
        its rung first, then judge each against the updated cutoff — a
        lockstep tuner feeding results one-by-one would otherwise prune by
        arrival order, not by score."""
        decisions: Dict[str, str] = {}
        judge = []
        for trial_id, iteration, metrics in results:
            if iteration >= self.max_t:
                decisions[trial_id] = STOP
                continue
            if iteration not in self._rung_levels:
                decisions[trial_id] = CONTINUE
                continue
            score = self._score(metrics)
            rung = self._rungs.setdefault(iteration, [])
            rung.append(score)
            judge.append((trial_id, iteration, score))
        for trial_id, iteration, score in judge:
            rung = sorted(self._rungs[iteration], reverse=True)
            # Top 1/eta of everything recorded at this rung survives
            # (ceil: a 2-entry rung at eta=2 keeps 1, a 4-entry keeps 2).
            k = max(1, -(-len(rung) // self.eta))
            decisions[trial_id] = (CONTINUE if score >= rung[k - 1]
                                   else STOP)
        return decisions


class PopulationBasedTraining:
    """PBT (reference: python/ray/tune/schedulers/pbt.py:221
    PopulationBasedTraining): at each perturbation interval, bottom-
    quantile trials EXPLOIT a top-quantile trial (clone its checkpoint +
    config) and EXPLORE (mutate hyperparameters). Decisions come back as
    {"action": "clone", "source": trial_id, "config": {...}} entries the
    Tuner applies by restarting the trial from the source's checkpoint.
    """

    def __init__(self, metric: str, mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Dict[str, Any] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: int = 0):
        import random as _random

        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        if not hyperparam_mutations:
            raise ValueError("hyperparam_mutations must be non-empty")
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self._rng = _random.Random(seed)
        self._configs: Dict[str, Dict[str, Any]] = {}
        self._latest: Dict[str, float] = {}

    # The Tuner registers configs so explore() can mutate them.
    def register(self, trial_id: str, config: Dict[str, Any]) -> None:
        self._configs[trial_id] = dict(config)

    def _score(self, metrics: Dict[str, Any]) -> float:
        v = float(metrics[self.metric])
        return v if self.mode == "max" else -v

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """Mutate each listed hyperparameter: resample with probability
        resample_probability, else perturb x1.2 / x0.8 (numeric) or step
        to a neighboring option (categorical) — reference explore()."""
        out = dict(config)
        for key, spec in self.mutations.items():
            cur = out.get(key)
            if self._rng.random() < self.resample_p or cur is None:
                if callable(spec):
                    out[key] = spec()
                elif isinstance(spec, (list, tuple)):
                    out[key] = self._rng.choice(list(spec))
                elif hasattr(spec, "sample"):
                    out[key] = spec.sample(self._rng)
                continue
            if isinstance(spec, (list, tuple)) and cur in spec:
                idx = list(spec).index(cur)
                step = self._rng.choice([-1, 1])
                out[key] = list(spec)[max(0, min(len(spec) - 1, idx + step))]
            elif isinstance(cur, (int, float)):
                factor = self._rng.choice([0.8, 1.2])
                out[key] = type(cur)(cur * factor)
        return out

    def on_batch(self, results) -> Dict[str, Any]:
        decisions: Dict[str, Any] = {}
        for trial_id, _it, metrics in results:
            if self.metric in metrics:
                self._latest[trial_id] = self._score(metrics)
            decisions[trial_id] = CONTINUE
        ranked = sorted(self._latest.items(), key=lambda kv: kv[1])
        n = len(ranked)
        if n < 2:
            return decisions
        k = max(1, int(n * self.quantile))
        bottom = {tid for tid, _s in ranked[:k]}
        top = [tid for tid, _s in ranked[-k:]]
        for trial_id, iteration, _metrics in results:
            if (trial_id in bottom and iteration > 0
                    and iteration % self.interval == 0):
                source = self._rng.choice(top)
                if source == trial_id:
                    continue
                new_config = self._explore(self._configs.get(source, {}))
                self._configs[trial_id] = new_config
                decisions[trial_id] = {"action": "clone", "source": source,
                                       "config": new_config}
        return decisions


class PB2(PopulationBasedTraining):
    """Population-Based Bandits (Parker-Holder et al. 2020; reference:
    python/ray/tune/schedulers/pb2.py): PBT's exploit step, but explore
    picks the next hyperparameters by a GP-UCB bandit over observed
    (config -> score-improvement) data instead of random perturbation —
    far more sample-efficient for small populations.

    ``hyperparam_bounds`` maps each tuned key to [low, high]; explore
    proposes within those bounds. The GP is a small exact RBF regressor
    over normalized configs with UCB acquisition (kappa sqrt-growth in
    data size, matching the time-varying bandit schedule's spirit)."""

    def __init__(self, metric: str, mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_bounds: Dict[str, Any] = None,
                 quantile_fraction: float = 0.25, seed: int = 0,
                 n_candidates: int = 64):
        if not hyperparam_bounds:
            raise ValueError("hyperparam_bounds must be non-empty")
        for k, b in hyperparam_bounds.items():
            if (not isinstance(b, (list, tuple)) or len(b) != 2
                    or not float(b[0]) < float(b[1])):
                raise ValueError(f"bounds for {k!r} must be [low, high]")
        self.bounds = {k: (float(b[0]), float(b[1]))
                       for k, b in hyperparam_bounds.items()}
        # The base class's mutations/resample machinery never runs — PB2
        # replaces _explore wholesale — but its constructor requires a
        # non-empty mutations dict; pass an inert marker per tuned key.
        super().__init__(metric, mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={k: "pb2-gp"
                                               for k in self.bounds},
                         quantile_fraction=quantile_fraction, seed=seed)
        self.n_candidates = n_candidates
        #: (normalized config vector, score delta) observations
        self._gp_data: List[tuple] = []
        self._prev_score: Dict[str, float] = {}

    # -------------------------------------------------------------- data

    def _vec(self, config: Dict[str, Any]) -> List[float]:
        out = []
        for k, (lo, hi) in sorted(self.bounds.items()):
            v = float(config.get(k, lo))
            out.append((v - lo) / (hi - lo))
        return out

    def on_batch(self, results) -> Dict[str, Any]:
        # Record per-trial score improvements BEFORE the base class
        # updates _latest (the GP models "what config change helped").
        for trial_id, _it, metrics in results:
            if self.metric not in metrics:
                continue
            score = self._score(metrics)
            prev = self._prev_score.get(trial_id)
            if prev is not None:
                cfg = self._configs.get(trial_id)
                if cfg is not None:
                    self._gp_data.append((self._vec(cfg), score - prev))
                    if len(self._gp_data) > 100:
                        self._gp_data.pop(0)
            self._prev_score[trial_id] = score
        return super().on_batch(results)

    # ------------------------------------------------------------ explore

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        import numpy as np

        out = dict(config)
        if len(self._gp_data) < 4:
            for k, (lo, hi) in self.bounds.items():
                out[k] = self._rng.uniform(lo, hi)
            return out
        X = np.asarray([d[0] for d in self._gp_data])
        y = np.asarray([d[1] for d in self._gp_data])
        y_std = y.std() or 1.0
        yn = (y - y.mean()) / y_std
        ell, noise = 0.3, 1e-2
        d2 = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        K = np.exp(-0.5 * d2 / ell**2) + noise * np.eye(len(X))
        Kinv = np.linalg.inv(K)
        alpha = Kinv @ yn

        cand = np.asarray([
            [self._rng.random() for _ in self.bounds]
            for _ in range(self.n_candidates)
        ])
        cd2 = ((cand[:, None, :] - X[None, :, :]) ** 2).sum(-1)
        Kc = np.exp(-0.5 * cd2 / ell**2)
        mu = Kc @ alpha
        var = np.maximum(1.0 - np.einsum("ij,jk,ik->i", Kc, Kinv, Kc), 1e-9)
        kappa = 0.5 * np.sqrt(np.log(len(X) + 1.0))
        best = int(np.argmax(mu + kappa * np.sqrt(var)))
        for i, (k, (lo, hi)) in enumerate(sorted(self.bounds.items())):
            out[k] = lo + float(cand[best, i]) * (hi - lo)
        return out


class MedianStoppingRule:
    """Median stopping (reference: python/ray/tune/schedulers/
    median_stopping_rule.py): a trial stops when its best metric so far
    falls below the MEDIAN of other trials' running-average metric at the
    same iteration — a gentle prune that needs no rung schedule.

    Guards: no stops before `min_samples_required` trials have reported
    at an iteration, nor before `grace_period` iterations of the trial
    itself (fresh trials get time to warm up)."""

    def __init__(self, metric: str, mode: str = "max",
                 grace_period: int = 2, min_samples_required: int = 3):
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        # trial_id -> list of scores per reported iteration
        self._scores: Dict[str, List[float]] = {}

    def _score(self, metrics: Dict[str, Any]) -> float:
        v = float(metrics[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, iteration: int,
                  metrics: Dict[str, Any]) -> str:
        return self.on_batch([(trial_id, iteration, metrics)])[trial_id]

    def on_batch(self, results) -> Dict[str, str]:
        decisions: Dict[str, str] = {}
        for trial_id, _iteration, metrics in results:
            self._scores.setdefault(trial_id, []).append(
                self._score(metrics))
        for trial_id, iteration, _metrics in results:
            mine = self._scores[trial_id]
            if iteration < self.grace:
                decisions[trial_id] = CONTINUE
                continue
            t = len(mine)
            # Other trials' RUNNING AVERAGE over their first t reports.
            others = [sum(s[:t]) / min(t, len(s))
                      for tid, s in self._scores.items()
                      if tid != trial_id and s]
            if len(others) < self.min_samples:
                decisions[trial_id] = CONTINUE
                continue
            others.sort()
            mid = len(others) // 2
            median = (others[mid] if len(others) % 2
                      else 0.5 * (others[mid - 1] + others[mid]))
            best = max(mine)
            decisions[trial_id] = CONTINUE if best >= median else STOP
        return decisions


class HyperBandScheduler:
    """Synchronous HyperBand (reference: python/ray/tune/schedulers/
    hyperband.py): trials are dealt round-robin into brackets with
    different exploration/exploitation trade-offs; bracket s halves its
    cohort every ``R / eta^(s-k)`` iterations, so aggressive brackets
    stop most trials early while conservative ones let everything run
    long. A rung is judged ONCE, when every live cohort member has
    reached it (terminal trials are dropped from readiness via
    on_trial_complete, so a dead peer can never block its bracket);
    losers are stopped wherever they are — including trials that passed
    the rung in earlier rounds (the tuner applies decisions to any
    trial, not just the round's reporters).

    NOTE: synchronous halving prunes BELOW max_t only when a bracket's
    cohort runs concurrently (the tuner's lockstep rounds provide this
    when max_concurrent_trials >= the trial count; the reference gets it
    by pausing trials at rungs). With fewer slots, early trials finish
    before their peers arrive and only the stragglers get pruned —
    prefer ASHAScheduler for heavily queued experiments."""

    def __init__(self, metric: str, mode: str = "max", max_t: int = 81,
                 reduction_factor: int = 3):
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode
        self.eta = reduction_factor
        self.max_t = max_t
        # Integer bracket count: float log loses a bracket on exact
        # powers (log(243, 3) == 4.999...).
        s = 0
        while self.eta ** (s + 1) <= max_t:
            s += 1
        self._s_max = s
        #: bracket s -> rung iterations (ascending), e.g. R=81, eta=3,
        #: s=2 -> [9, 27, 81]
        self._bracket_rungs = {
            b: [max(1, int(max_t / (reduction_factor ** k)))
                for k in range(b, -1, -1)]
            for b in range(self._s_max + 1)
        }
        self._next_bracket = 0
        self._trial_bracket: Dict[str, int] = {}
        #: trial -> score per iteration
        self._scores: Dict[str, Dict[int, float]] = {}
        self._stopped: set = set()
        self._finished: set = set()
        self._judged: set = set()  # (bracket, rung) pairs already halved

    def register(self, trial_id: str, config) -> None:
        self._trial_bracket[trial_id] = self._next_bracket
        self._next_bracket = (self._next_bracket + 1) % (self._s_max + 1)

    def on_trial_complete(self, trial_id: str) -> None:
        """Terminal (finished/errored) trials leave their cohort — their
        absence must not stall readiness forever."""
        self._finished.add(trial_id)

    def _score(self, metrics: Dict[str, Any]) -> float:
        v = float(metrics[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, iteration: int,
                  metrics: Dict[str, Any]) -> str:
        """One-trial-at-a-time protocol: halving decisions that target
        OTHER trials (stragglers judged when this report completed a rung)
        are delivered on each loser's NEXT report via _stopped — on_batch
        marks them stopped, and any report from a stopped trial returns
        STOP below, so no decision is lost."""
        return self.on_batch([(trial_id, iteration, metrics)])[trial_id]

    def on_batch(self, results) -> Dict[str, str]:
        decisions: Dict[str, str] = {}
        touched: set = set()
        for trial_id, iteration, metrics in results:
            self._trial_bracket.setdefault(trial_id, 0)
            self._scores.setdefault(trial_id, {})[iteration] = \
                self._score(metrics)
            bracket = self._trial_bracket[trial_id]
            if trial_id in self._stopped or iteration >= self.max_t:
                # Already judged out in an earlier round (its STOP may have
                # been addressed to a batch it wasn't part of) — or done.
                decisions[trial_id] = STOP
                self._stopped.add(trial_id)
            else:
                decisions[trial_id] = CONTINUE
            touched.add(bracket)
        # Judge every unjudged non-final rung whose cohort is complete —
        # decisions may target trials OUTSIDE this batch (stragglers that
        # passed the rung earlier).
        for bracket in touched:
            rungs = self._bracket_rungs[bracket]
            # Cohort for RANKING includes terminal trials whose rung
            # score was recorded (they just can't be stopped again);
            # readiness requires every non-terminal member at the rung.
            members = [t for t, b in self._trial_bracket.items()
                       if b == bracket and t not in self._stopped]
            if len(members) < 2:
                continue
            for rung in rungs[:-1]:
                if (bracket, rung) in self._judged:
                    continue
                live = [t for t in members if t not in self._finished]
                if not all(rung in self._scores.get(t, {})
                           for t in live):
                    break  # live cohort still climbing toward this rung
                scored = [t for t in members
                          if rung in self._scores.get(t, {})]
                if len(scored) < 2:
                    break
                self._judged.add((bracket, rung))
                ranked = sorted(scored,
                                key=lambda t: -self._scores[t][rung])
                keep = max(1, len(ranked) // self.eta)
                for loser in ranked[keep:]:
                    decisions[loser] = STOP
                    self._stopped.add(loser)
                members = [t for t in members
                           if t not in self._stopped]
        return decisions
