"""Trial schedulers: FIFO and ASHA early stopping.

Parity target: reference python/ray/tune/schedulers/async_hyperband.py
(ASHAScheduler) — asynchronous successive halving: at each rung
(iteration r, r*eta, r*eta^2, ...) a trial survives only if its metric is
in the top 1/eta of results recorded AT that rung so far.
"""

from __future__ import annotations

from typing import Any, Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, iteration: int,
                  metrics: Dict[str, Any]) -> str:
        return CONTINUE

    def on_batch(self, results) -> Dict[str, str]:
        return {trial_id: CONTINUE for trial_id, _i, _m in results}


class ASHAScheduler:
    def __init__(self, metric: str, mode: str = "max",
                 grace_period: int = 1, reduction_factor: int = 3,
                 max_t: int = 100):
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.eta = reduction_factor
        self.max_t = max_t
        # rung iteration -> list of recorded metric values
        self._rungs: Dict[int, List[float]] = {}
        r = grace_period
        self._rung_levels = []
        while r < max_t:
            self._rung_levels.append(r)
            r *= reduction_factor

    def _score(self, metrics: Dict[str, Any]) -> float:
        v = float(metrics[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, iteration: int,
                  metrics: Dict[str, Any]) -> str:
        return self.on_batch([(trial_id, iteration, metrics)])[trial_id]

    def on_batch(self, results) -> Dict[str, str]:
        """Batch-synchronous halving: record EVERY result of the round at
        its rung first, then judge each against the updated cutoff — a
        lockstep tuner feeding results one-by-one would otherwise prune by
        arrival order, not by score."""
        decisions: Dict[str, str] = {}
        judge = []
        for trial_id, iteration, metrics in results:
            if iteration >= self.max_t:
                decisions[trial_id] = STOP
                continue
            if iteration not in self._rung_levels:
                decisions[trial_id] = CONTINUE
                continue
            score = self._score(metrics)
            rung = self._rungs.setdefault(iteration, [])
            rung.append(score)
            judge.append((trial_id, iteration, score))
        for trial_id, iteration, score in judge:
            rung = sorted(self._rungs[iteration], reverse=True)
            # Top 1/eta of everything recorded at this rung survives
            # (ceil: a 2-entry rung at eta=2 keeps 1, a 4-entry keeps 2).
            k = max(1, -(-len(rung) // self.eta))
            decisions[trial_id] = (CONTINUE if score >= rung[k - 1]
                                   else STOP)
        return decisions
