"""Demand-driven autoscaler.

Parity target: the reference autoscaler v2
(reference: python/ray/autoscaler/v2/autoscaler.py:42 Autoscaler.update,
v2/scheduler.py bin-packing over demand, _private/autoscaler.py:171 v1
loop): poll the head for UNMET resource demand + node views, bin-pack the
demand onto the smallest-fitting node types (clamped by max_nodes), and
reap nodes that sat fully idle past idle_timeout. Scale-down drains via
the head so the scheduler stops routing to the node before termination.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.util import metrics as _m

logger = logging.getLogger(__name__)

STEP_FAILURES = _m.Counter(
    "rtpu_autoscaler_step_failures_total",
    "autoscaler reconcile passes that raised (loop backs off and retries)")


@dataclasses.dataclass
class AutoscalerConfig:
    max_nodes: int = 8
    min_nodes: int = 0
    idle_timeout_s: float = 30.0
    poll_interval_s: float = 2.0
    demand_window_s: float = 20.0
    # Scale-up batches are capped per step (reference upscaling_speed).
    max_launch_per_step: int = 4


class Autoscaler:
    """Drives one provider against one cluster head."""

    def __init__(self, cluster_runtime, provider: NodeProvider,
                 config: Optional[AutoscalerConfig] = None):
        self._rt = cluster_runtime
        self._provider = provider
        self.config = config or AutoscalerConfig()
        self._idle_since: Dict[str, float] = {}
        # Drained from the head but the provider terminate failed: the
        # node is gone from the cluster state, so the main reap loop can
        # never see it again — retried explicitly each pass until the
        # provider call succeeds (else the VM leaks and bills forever).
        self._pending_terminate: set = set()
        self._launched = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # provider ids we created, mapped to cluster node ids once known
        self._managed: Dict[str, Optional[str]] = {}

    # ---------------------------------------------------------------- API

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        # Join the loop (bounded): the Event wakes the wait immediately,
        # so only an in-flight step() holds the thread — letting a live
        # reconcile pass race interpreter teardown is how half-drained
        # nodes leak.
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=10.0)

    def step(self) -> Dict[str, Any]:
        """One reconcile pass; returns what it did (tested directly)."""
        state = self._rt.head.retrying_call(
            "get_demand", self.config.demand_window_s, timeout=10)
        # Snapshot the provider's node map ONCE per step: slice providers
        # back cluster_node_ids by a cloud list call, and per-pid lookups
        # would be O(slices) API calls per pass.
        mapper = getattr(self._provider, "cluster_node_map", None)
        self._node_map = mapper() if mapper is not None else None
        launched = self._scale_up(state)
        reaped = self._scale_down(state)
        return {"launched": launched, "reaped": reaped}

    # ------------------------------------------------------------- scaling

    def _fits(self, demand: Dict[str, float],
              resources: Dict[str, float]) -> bool:
        return all(resources.get(k, 0.0) >= v
                   for k, v in demand.items()
                   if k != "_labels" and v > 0)

    @staticmethod
    def _labels_match(demand: Dict[str, Any],
                      labels: Dict[str, str]) -> bool:
        """A label-constrained demand (see head node_label picks) only
        counts against capacity that CARRIES those labels — scaling up
        unlabeled nodes for it would loop forever."""
        need = demand.get("_labels") or {}
        return all(labels.get(k) == v for k, v in dict(need).items())

    def _scale_up(self, state) -> List[str]:
        demands = state["unmet"]
        if not demands:
            return []
        # Provider nodes self-register with the head, so each appears both
        # in non_terminated_nodes() and in state["nodes"] once up. Count
        # alive cluster nodes plus provider nodes none of whose hosts are
        # alive in the cluster view (booting, or dead-but-still-billed
        # VMs) — double-counting understates the launch budget; skipping
        # dead VMs overshoots it.
        alive_ids = {n["node_id"] for n in state["nodes"] if n["alive"]}
        n_current = len(alive_ids) + len(
            [pid for pid in self._provider.non_terminated_nodes()
             if not any(cid in alive_ids
                        for cid in self._cluster_ids_of(pid))])
        launched: List[str] = []
        # Bin-pack: demands first absorb EXISTING free capacity, then the
        # smallest node type that fits; one node absorbs several demands.
        # Non-numeric node-type entries (e.g. a slice provider's
        # accelerator_type) are config, not capacity.
        types = sorted(
            ((name, {k: float(v) for k, v in res.items()
                     if isinstance(v, (int, float))},
              dict(res.get("_labels", {})))
             for name, res in self._provider.node_types.items()),
            key=lambda kv: sum(kv[1].values()))
        pending_capacity: List[tuple] = [
            (dict(n["available"]), dict(n.get("labels") or {}))
            for n in state["nodes"] if n["alive"]]
        for demand in demands:
            placed = False
            for cap, labels in pending_capacity:
                if self._fits(demand, cap) and self._labels_match(demand,
                                                                  labels):
                    for k, v in demand.items():
                        if k == "_labels":
                            continue
                        cap[k] = cap.get(k, 0.0) - v
                    placed = True
                    break
            if placed:
                continue
            for _name, res, type_labels in types:
                if self._fits(demand, res) and self._labels_match(
                        demand, type_labels):
                    cap = dict(res)
                    for k, v in demand.items():
                        if k == "_labels":
                            continue
                        cap[k] = cap.get(k, 0.0) - v
                    pending_capacity.append((cap, type_labels))
                    launched.append(_name)
                    break
        # max_nodes is a HOST cap and n_current counts hosts: charge each
        # launch its host count (a v5p-8 slice = 2 hosts), else multi-host
        # slices overshoot the cap by their host factor.
        host_counter = getattr(self._provider, "node_type_hosts", None)
        host_budget = max(0, self.config.max_nodes - n_current)
        taken: List[str] = []
        hosts_used = 0
        for node_type in launched:
            if len(taken) >= self.config.max_launch_per_step:
                break
            hosts = (host_counter(node_type)
                     if host_counter is not None else 1)
            if hosts_used + hosts > host_budget:
                break
            taken.append(node_type)
            hosts_used += hosts
        for node_type in taken:
            try:
                pid = self._provider.create_node(node_type)
                self._managed[pid] = None
                self._launched += 1
            except Exception as e:
                logger.warning("create_node(%s) failed (rest of this "
                               "step's launches skipped): %r",
                               node_type, e)
                break
        return taken

    def _cluster_ids_of(self, pid: str) -> List[str]:
        """Cluster node ids behind one provider node. LocalNodeProvider
        ids ARE cluster node ids; slice providers (GCE TPU) map one
        provider id to every host of the slice (via the per-step
        cluster_node_map snapshot)."""
        node_map = getattr(self, "_node_map", None)
        if node_map is not None:
            return node_map.get(pid, [])
        mapper = getattr(self._provider, "cluster_node_ids", None)
        if mapper is not None:
            return mapper(pid)
        return [pid]

    def _scale_down(self, state) -> List[str]:
        now = time.monotonic()
        reaped: List[str] = []
        reaped_hosts = 0
        by_cluster_id = {n["node_id"]: n for n in state["nodes"]}
        for pid in list(self._pending_terminate):
            # A drained-but-unterminated node's heartbeat re-registers it
            # with the head (the head acked False after the drain), so it
            # may be alive again with fresh work routed to it — re-drain
            # before the terminate retry, never terminate a routable node.
            for cid in self._cluster_ids_of(pid):
                if cid in by_cluster_id and by_cluster_id[cid]["alive"]:
                    try:
                        self._rt.head.retrying_call(
                            "drain_node", cid, timeout=10)
                    except Exception as e:
                        logger.warning("re-drain of %s before terminate "
                                       "retry failed: %r", cid, e)
            try:
                self._provider.terminate_node(pid)
            except Exception:
                continue
            self._pending_terminate.discard(pid)
            self._managed.pop(pid, None)
            self._idle_since.pop(pid, None)
            reaped.append(pid)
            # A re-registered node was alive in THIS snapshot: charge its
            # hosts against the min_nodes floor or the main loop below
            # could reap another node and undershoot min_nodes.
            reaped_hosts += len(
                [cid for cid in self._cluster_ids_of(pid)
                 if cid in by_cluster_id and by_cluster_id[cid]["alive"]])
        alive_total = len([n for n in state["nodes"] if n["alive"]])
        for pid in list(self._managed):
            if pid in self._pending_terminate:
                continue
            nodes = [by_cluster_id.get(cid)
                     for cid in self._cluster_ids_of(pid)]
            nodes = [n for n in nodes if n is not None and n["alive"]]
            if not nodes:
                continue
            # A slice reaps only when EVERY host sat idle (TPU slices
            # terminate whole, never host-by-host).
            idle = all(
                all(abs(n["available"].get(k, 0.0) - v) < 1e-9
                    for k, v in n["resources"].items())
                for n in nodes)
            if not idle:
                self._idle_since.pop(pid, None)
                continue
            t0 = self._idle_since.setdefault(pid, now)
            # min_nodes is a HOST floor: a multi-host slice removes all
            # its hosts at once, so count hosts, not provider ids.
            if (now - t0 >= self.config.idle_timeout_s
                    and alive_total - reaped_hosts - len(nodes)
                    >= self.config.min_nodes):
                for n in nodes:
                    try:
                        self._rt.head.retrying_call(
                            "drain_node", n["node_id"], timeout=10)
                    except Exception as e:
                        logger.warning("drain of idle node %s failed "
                                       "(terminating anyway): %r",
                                       n["node_id"], e)
                # Only report the node reaped once the provider actually
                # dropped it. Drain removes the node from the head's
                # state, so a failed terminate afterwards moves the pid to
                # _pending_terminate (retried above) rather than relying
                # on this loop ever seeing the node again.
                try:
                    self._provider.terminate_node(pid)
                except Exception:
                    self._pending_terminate.add(pid)
                    reaped_hosts += len(nodes)
                    continue
                self._managed.pop(pid, None)
                self._idle_since.pop(pid, None)
                reaped.append(pid)
                reaped_hosts += len(nodes)
        return reaped

    # ---------------------------------------------------------------- loop

    def _loop(self) -> None:
        failures = 0
        while not self._stop.wait(
                self.config.poll_interval_s * min(2 ** failures, 16)):
            try:
                self.step()
                failures = 0
            except Exception as e:
                # A dead head or a cloud-API outage must not kill the
                # loop, but it must not be silent either: count it,
                # log it, and back the poll off (up to 16x) so a down
                # head isn't hammered every interval.
                failures += 1
                STEP_FAILURES.inc()
                logger.warning(
                    "autoscaler step failed (%d consecutive, next try "
                    "in %.1fs): %r", failures,
                    self.config.poll_interval_s * min(2 ** failures, 16),
                    e)
