"""Demand-driven autoscaling over pluggable node providers."""

from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig
from ray_tpu.autoscaler.gce import (FakeGceApi, GceTpuApi,
                                    GceTpuNodeProvider, RestGceTpuApi)
from ray_tpu.autoscaler.node_provider import (GkeTpuSliceNodeProvider,
                                              LocalNodeProvider,
                                              NodeProvider)

__all__ = ["Autoscaler", "AutoscalerConfig", "FakeGceApi", "GceTpuApi",
           "GceTpuNodeProvider", "GkeTpuSliceNodeProvider",
           "LocalNodeProvider", "NodeProvider", "RestGceTpuApi"]
