"""Node providers: how the autoscaler adds/removes machines.

Parity target: the reference's NodeProvider abstraction
(reference: python/ray/autoscaler/node_provider.py:23 — create_node /
terminate_node / non_terminated_nodes over cloud APIs), trimmed to what a
TPU-first deployment needs: homogeneous-or-typed node creation and
termination. The GKE provider below is the TPU-native analog of the
reference's KubeRay/GCP providers: one "node" = one TPU slice host pool
member, created by scaling a GKE node pool.
"""

from __future__ import annotations

import threading
import queue as _queue
from typing import Any, Dict, List, Optional


class NodeProvider:
    """ABC: the autoscaler talks to providers only through this surface."""

    #: name -> resources dict one node of that type contributes
    node_types: Dict[str, Dict[str, float]] = {}

    def create_node(self, node_type: str) -> str:
        """Provision one node of `node_type`; returns a provider node id.
        The node is expected to self-register with the cluster head."""
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """In-process provider for tests/dev: nodes are node-manager
    subprocesses on this host (cluster.add_node). Spawns run on a
    DEDICATED long-lived thread: PDEATHSIG is delivered when the spawning
    thread exits, so provisioning from short-lived callers would kill the
    node (same discipline as the node manager's worker spawner)."""

    def __init__(self, cluster_runtime,
                 node_types: Optional[Dict[str, Dict[str, float]]] = None):
        self._rt = cluster_runtime
        self.node_types = node_types or {"cpu": {"CPU": 4.0}}
        self._nodes: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._requests: "_queue.Queue" = _queue.Queue()
        self._results: "_queue.Queue" = _queue.Queue()
        self._spawner = threading.Thread(target=self._spawn_loop,
                                         daemon=True,
                                         name="autoscaler-provider")
        self._spawner.start()

    def _spawn_loop(self) -> None:
        while True:
            node_type = self._requests.get()
            if node_type is None:
                return
            try:
                res = dict(self.node_types[node_type])
                cpus = res.pop("CPU", 0.0)
                # "_labels" is node METADATA, not capacity: apply as node
                # labels (label-constrained demands match against them).
                labels = dict(res.pop("_labels", {}))
                node = self._rt.add_node(num_cpus=cpus,
                                         resources=res or None,
                                         labels=labels or None)
                self._results.put(("ok", node))
            except BaseException as e:  # noqa: BLE001
                self._results.put(("err", e))

    def create_node(self, node_type: str) -> str:
        if node_type not in self.node_types:
            raise KeyError(f"unknown node type {node_type!r}")
        self._requests.put(node_type)
        kind, val = self._results.get(timeout=120)
        if kind == "err":
            raise val
        with self._lock:
            self._nodes[val.node_id] = val
        return val.node_id

    def terminate_node(self, provider_node_id: str) -> None:
        with self._lock:
            node = self._nodes.pop(provider_node_id, None)
        if node is not None:
            try:
                node.proc.terminate()
            except Exception:
                pass

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return [nid for nid, n in self._nodes.items()
                    if n.proc.poll() is None]


class GkeTpuSliceNodeProvider(NodeProvider):
    """GKE TPU-slice provider SKETCH (the cloud-API calls are stubbed —
    this image has zero egress; the shape is what matters).

    A node type maps to a GKE node pool whose machines carry a TPU slice
    topology (reference analog: python/ray/autoscaler/_private/gcp/ +
    _private/kuberay/, and the TPU pod scheduling notes in
    python/ray/_private/accelerators/tpu.py). create_node scales the pool
    by +1; the new host's startup script runs `ray_tpu node join
    --head <addr>`, which self-registers exactly like LocalNodeProvider's
    subprocess nodes. TPU-slice atomicity: multi-host slice pools scale
    in whole-slice quanta, so `slice_hosts` nodes are requested together
    (one v5p-16 slice = 2 hosts, etc.)."""

    def __init__(self, project: str, zone: str, cluster: str,
                 node_types: Optional[Dict[str, Dict[str, Any]]] = None):
        self.project, self.zone, self.cluster = project, zone, cluster
        self.node_types = node_types or {
            "tpu-v5p-8": {"CPU": 208.0, "TPU": 4.0, "_pool": "v5p-8-pool",
                          "_slice_hosts": 1},
        }

    def _gcloud(self, *args) -> None:  # pragma: no cover - requires cloud
        raise NotImplementedError(
            "GKE provider requires cloud credentials; this environment has "
            "no egress. Shape: gcloud container clusters resize "
            f"{self.cluster} --node-pool <pool> --num-nodes <n>")

    def create_node(self, node_type: str) -> str:  # pragma: no cover
        spec = self.node_types[node_type]
        self._gcloud("container", "clusters", "resize", self.cluster,
                     "--node-pool", spec["_pool"], "--num-nodes", "+1")
        return f"{spec['_pool']}/pending"

    def terminate_node(self, provider_node_id: str) -> None:  # pragma: no cover
        pool = provider_node_id.split("/")[0]
        self._gcloud("container", "clusters", "resize", self.cluster,
                     "--node-pool", pool, "--num-nodes", "-1")

    def non_terminated_nodes(self) -> List[str]:  # pragma: no cover
        return []
