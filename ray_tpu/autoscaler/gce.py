"""GCE TPU node provider: the autoscaler's cloud arm.

Parity target: the reference's GCP provider + TPU pod support
(reference: python/ray/autoscaler/_private/gcp/node_provider.py and the
TPU-VM creation path in _private/gcp/node.py; slice/pod shapes from
python/ray/_private/accelerators/tpu.py). Design:

- ``GceTpuApi`` is the narrow surface of the GCE TPU API actually used
  (create/list/delete TPU VM slices). Production binds ``RestGceTpuApi``
  (stubbed here: zero-egress image); tests bind ``FakeGceApi`` — an
  in-memory cloud whose "VMs" are real local node processes that
  self-register with the head carrying the slice's TPU resources, so
  autoscaler tests exercise the REAL end-to-end loop (demand -> provider
  -> node joins -> demand met) exactly like the reference's
  fake_multinode provider tests (tests/test_autoscaler_fake_multinode.py).
- One ``create_node`` call provisions ONE WHOLE SLICE (all its hosts):
  TPU slices are atomic units in the cloud API — there is no such thing
  as half a v5p-16.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.core.accelerators import parse_slice_shape, slice_node_resources
from ray_tpu.autoscaler.node_provider import NodeProvider


class GceTpuApi:
    """The GCE TPU-VM API surface the provider consumes."""

    def create_tpu_slice(self, name: str, accelerator_type: str) -> None:
        """Provision a slice; its hosts boot and self-register."""
        raise NotImplementedError

    def list_tpu_slices(self) -> List[Dict[str, Any]]:
        """[{"name", "accelerator_type", "state", "node_ids": [...]}]"""
        raise NotImplementedError

    def delete_tpu_slice(self, name: str) -> None:
        raise NotImplementedError


class RestGceTpuApi(GceTpuApi):  # pragma: no cover — requires cloud creds
    """Real API shape (tpu.googleapis.com v2 TPU-VM REST calls — the
    reference drives the same endpoints through googleapiclient in
    autoscaler/_private/gcp/node.py). Unusable in this zero-egress image;
    kept as the production binding point."""

    def __init__(self, project: str, zone: str, runtime_version: str,
                 startup_script: str):
        self.project, self.zone = project, zone
        self.runtime_version = runtime_version
        self.startup_script = startup_script

    def _call(self, method: str, path: str, body=None):
        raise NotImplementedError(
            "no egress: POST https://tpu.googleapis.com/v2/projects/"
            f"{self.project}/locations/{self.zone}/nodes ...")

    def create_tpu_slice(self, name, accelerator_type):
        self._call("POST", f"nodes?nodeId={name}", {
            "acceleratorType": accelerator_type,
            "runtimeVersion": self.runtime_version,
            "metadata": {"startup-script": self.startup_script},
        })

    def list_tpu_slices(self):
        return self._call("GET", "nodes")

    def delete_tpu_slice(self, name):
        self._call("DELETE", f"nodes/{name}")


class FakeGceApi(GceTpuApi):
    """In-memory GCE: slice hosts are local node-manager processes with
    mocked TPU resources (the reference's mocked-accelerator test pattern:
    tests/accelerators/test_tpu.py fakes GCE metadata the same way)."""

    def __init__(self, cluster_runtime):
        self._rt = cluster_runtime
        self._slices: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()

    def create_tpu_slice(self, name: str, accelerator_type: str,
                         extra_labels=None) -> None:
        _gen, _chips, hosts = parse_slice_shape(accelerator_type)
        # Record CREATING before hosts boot (like the real API: the node
        # resource exists immediately, state flips to READY when all hosts
        # are up) — a lister mid-boot must see the slice, not nothing.
        with self._lock:
            self._slices[name] = {
                "name": name, "accelerator_type": accelerator_type,
                "state": "CREATING", "nodes": [], "node_ids": [],
            }
        nodes = []
        for worker_id in range(hosts):
            res, labels = slice_node_resources(accelerator_type, worker_id)
            node = self._rt.add_node(
                num_cpus=8.0, resources=res,
                labels={**labels, **(extra_labels or {}),
                        "tpu-slice": name})
            nodes.append(node)
        with self._lock:
            s = self._slices.get(name)
            if s is None:
                # Deleted mid-create: tear the hosts back down.
                for n in nodes:
                    try:
                        n.proc.terminate()
                    except Exception:
                        pass
                return
            s.update(state="READY", nodes=nodes,
                     node_ids=[n.node_id for n in nodes])

    def list_tpu_slices(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = []
            for s in self._slices.values():
                if s["state"] == "CREATING":
                    out.append({"name": s["name"],
                                "accelerator_type": s["accelerator_type"],
                                "state": "CREATING", "node_ids": []})
                    continue
                alive = [n for n in s["nodes"] if n.proc.poll() is None]
                out.append({"name": s["name"],
                            "accelerator_type": s["accelerator_type"],
                            "state": "READY" if alive else "TERMINATED",
                            "node_ids": [n.node_id for n in alive]})
            return out

    def delete_tpu_slice(self, name: str) -> None:
        with self._lock:
            s = self._slices.pop(name, None)
        if s is None:
            return
        for n in s["nodes"]:
            try:
                n.proc.terminate()
            except Exception:
                pass


class GceTpuNodeProvider(NodeProvider):
    """NodeProvider over the GCE TPU API. A provider "node" is one SLICE
    (all hosts provision/terminate together); ``cluster_node_ids`` maps a
    slice to the cluster nodes its hosts registered as, which the
    autoscaler uses for idleness and drain decisions."""

    def __init__(self, api: GceTpuApi,
                 node_types: Optional[Dict[str, Dict[str, Any]]] = None):
        self._api = api
        #: name -> {"accelerator_type": ..., plus the resources one slice
        #: HEAD host advertises (what the bin-packer matches demands to)}
        self.node_types = node_types or {
            "tpu-v5p-8": {"CPU": 8.0, "TPU": 4.0, "TPU-v5p-8-head": 1.0,
                          "accelerator_type": "v5p-8"},
        }

    def _resources_of(self, node_type: str) -> Dict[str, float]:
        spec = self.node_types[node_type]
        return {k: float(v) for k, v in spec.items()
                if k not in ("accelerator_type", "_labels")}

    def create_node(self, node_type: str) -> str:
        import inspect

        spec = self.node_types[node_type]
        name = f"{node_type}-{uuid.uuid4().hex[:8]}"
        # Signature probe, not try/except TypeError: catching the live
        # call would mask real TypeErrors AND silently drop labels (an
        # unlabeled slice can never satisfy a label demand — launch loop).
        params = inspect.signature(self._api.create_tpu_slice).parameters
        if "extra_labels" in params:
            self._api.create_tpu_slice(name, spec["accelerator_type"],
                                       dict(spec.get("_labels", {})))
        else:
            self._api.create_tpu_slice(name, spec["accelerator_type"])
        return name

    def terminate_node(self, provider_node_id: str) -> None:
        self._api.delete_tpu_slice(provider_node_id)

    def non_terminated_nodes(self) -> List[str]:
        return [s["name"] for s in self._api.list_tpu_slices()
                if s["state"] != "TERMINATED"]

    def node_type_hosts(self, node_type: str) -> int:
        """Hosts one create_node of this type adds to the cluster."""
        spec = self.node_types[node_type]
        _gen, _chips, hosts = parse_slice_shape(spec["accelerator_type"])
        return hosts

    def cluster_node_ids(self, provider_node_id: str) -> List[str]:
        return self.cluster_node_map().get(provider_node_id, [])

    def cluster_node_map(self) -> Dict[str, List[str]]:
        """One cloud list call covering every slice — the autoscaler
        snapshots this once per reconcile pass."""
        return {s["name"]: list(s["node_ids"])
                for s in self._api.list_tpu_slices()}
