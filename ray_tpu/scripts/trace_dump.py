"""Merged cluster-observability export: one chrome://tracing JSON.

Parity target: `ray timeline` (chrome-trace export of task events)
extended across the observability plane this runtime actually has:

- the head's TRACE RING (distributed spans: serve request lifecycles,
  task submit/lease/dispatch/execute/seal chains, pull fetches);
- every process's FLIGHT-RECORDER ring (rpc dispatches, heartbeats,
  lease churn, store seal/evict, engine ticks), fetched live over
  ``rpc_dump_flight`` from the head and every alive node — plus any
  offline dump FILES (SIGUSR2 / chaos-kill / worker-death dumps) passed
  via ``--flight``;
- the head's cluster task-event ring (``list_task_events``) as the
  timeline rows.

Clock alignment: wall clocks differ across hosts. Every node manager
keeps a heartbeat-RTT-estimated offset to the head's clock
(``clock_offset_s`` in its flight dump: head_time - node_time); spans
carry the node id of their emitting process, so each span/event is
shifted onto the HEAD's clock before export. The script also probes the
head once itself (same RTT estimate) to place its own clock.

Usage::

    python -m ray_tpu.scripts.trace_dump --address HOST:PORT \
        [--out trace.json] [--trace-id ID] [--limit N] \
        [--flight 'dumpdir/flight-*.json']

Open the output at chrome://tracing or https://ui.perfetto.dev.
"""

from __future__ import annotations

import argparse
import glob
import json
import sys
import time
from typing import Any, Dict, List, Optional


def _probe_offset(client) -> float:
    """Remote clock minus local clock, RTT-corrected (median of 3).
    RTT measured on the MONOTONIC clock: a wall-clock step mid-probe
    (the very skew this tool corrects) must not corrupt the estimate."""
    samples = []
    for _ in range(3):
        t0 = time.time()
        m0 = time.monotonic()
        remote_t = client.call("clock_probe", timeout=5)
        rtt = time.monotonic() - m0
        samples.append(float(remote_t) - (t0 + rtt / 2.0))
    samples.sort()
    return samples[len(samples) // 2]


def _span_events(spans: List[dict], node_offsets: Dict[str, float]
                 ) -> List[dict]:
    """Spans -> chrome-trace 'X' events on the head clock. Rows group by
    (node, pid); the tid is the span name's subsystem prefix so one
    request's phases stack readably."""
    events = []
    for s in spans:
        off = node_offsets.get(s.get("node") or "", 0.0)
        start = s["start"] + off
        end = (s["end"] if s["end"] is not None else s["start"]) + off
        events.append({
            "name": s["name"], "ph": "X",
            "pid": f"spans:{(s.get('node') or 'head')[:12]}",
            "tid": s["name"].split(":")[0].split(".")[0],
            "ts": start * 1e6,
            "dur": max((end - start) * 1e6, 1),
            "args": dict(s.get("attrs") or {},
                         trace_id=s.get("trace_id"),
                         span_id=s.get("span_id"),
                         parent=s.get("parent_id"),
                         ok=s.get("ok", True)),
        })
    return events


def _flight_events(dump: dict, node_offsets: Dict[str, float]
                   ) -> List[dict]:
    """One flight dump -> chrome-trace instant events."""
    off = dump.get("clock_offset_s") or 0.0
    node = dump.get("node_id")
    if node and node in node_offsets:
        off = node_offsets[node]
    row = f"flight:{dump.get('role', 'proc')}:{dump.get('pid', 0)}"
    events = []
    for ev in dump.get("events", ()):
        try:
            ts, kind, fields = ev
        except (TypeError, ValueError):
            continue
        events.append({
            "name": kind, "ph": "i", "s": "t",
            "pid": row, "tid": kind,
            "ts": (ts + off) * 1e6,
            "args": dict(fields or {}),
        })
    return events


def _task_events(rows: List[dict]) -> List[dict]:
    """Head task-event ring (cluster-wide completions: task_id, name,
    duration_s, end_ts, owner) -> timeline 'X' rows. Owner-clock; owners
    run on node hosts whose offsets we don't know per-event — close
    enough for the task-duration view."""
    events = []
    for e in rows:
        end = e.get("end_ts")
        dur = e.get("duration_s")
        if end is None or dur is None:
            continue
        events.append({
            "name": e.get("name", "task"), "ph": "X",
            "pid": "tasks", "tid": e.get("owner", "?"),
            "ts": (end - dur) * 1e6, "dur": max(dur * 1e6, 1),
            "args": {"task_id": e.get("task_id", ""),
                     "status": e.get("status", "")},
        })
    return events


def collect(address: str, trace_id: Optional[str] = None,
            limit: int = 20000,
            flight_globs: Optional[List[str]] = None) -> Dict[str, Any]:
    """Gather spans + flight rings + task events from a live cluster and
    merge them (head-clock-aligned) into one chrome-trace dict."""
    from ray_tpu.cluster.protocol import RpcClient

    head = RpcClient(address)
    try:
        head_off = _probe_offset(head)  # head clock - local clock
        if trace_id:
            spans = head.call("get_trace", trace_id, timeout=10)
        else:
            spans = head.call("trace_tail", limit, timeout=10)
        nodes = head.call("list_nodes", timeout=10)
        task_rows = head.call("list_task_events", limit, timeout=10)
        head_flight = head.call("dump_flight", timeout=10)

        # Per-node clock offsets TO THE HEAD: prefer a fresh local
        # probe (script -> node, combined with the script -> head
        # probe); fall back to the node's own heartbeat-RTT estimate.
        node_offsets: Dict[str, float] = {}
        flight_dumps = [head_flight]
        for n in nodes:
            if not n.get("alive", True):
                continue
            try:
                nc = RpcClient(n["address"])
            except OSError:
                continue
            try:
                dump = nc.call("dump_flight", timeout=10)
                try:
                    node_off = _probe_offset(nc)  # node clock - local
                    # node ts + offset == head-clock ts
                    node_offsets[n["node_id"]] = head_off - node_off
                except Exception:  # noqa: BLE001 — fall back to the
                    # node's own heartbeat-RTT estimate
                    node_offsets[n["node_id"]] = \
                        dump.get("clock_offset_s") or 0.0
                dump.setdefault("node_id", n["node_id"])
                flight_dumps.append(dump)
            except Exception as e:  # noqa: BLE001 — best-effort census
                print(f"trace_dump: node {n['node_id'][:12]} "
                      f"unreachable: {e!r}", file=sys.stderr)
            finally:
                nc.close()
    finally:
        head.close()

    for path in (p for g in (flight_globs or ()) for p in glob.glob(g)):
        try:
            with open(path) as f:
                flight_dumps.append(json.load(f))
        except (OSError, ValueError) as e:
            print(f"trace_dump: skipping {path}: {e}", file=sys.stderr)

    events: List[dict] = []
    events.extend(_span_events(spans, node_offsets))
    for dump in flight_dumps:
        events.extend(_flight_events(dump, node_offsets))
    events.extend(_task_events(task_rows))
    return {
        "traceEvents": events,
        "otherData": {
            "spans": len(spans),
            "flight_dumps": len(flight_dumps),
            "task_events": len(task_rows),
            "node_clock_offsets_s": {k[:12]: round(v, 6)
                                     for k, v in node_offsets.items()},
        },
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m ray_tpu.scripts.trace_dump", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--address", required=True,
                   help="head address (HOST:PORT)")
    p.add_argument("--out", default="trace_dump.json")
    p.add_argument("--trace-id", default=None,
                   help="export one trace instead of the whole tail")
    p.add_argument("--limit", type=int, default=20000,
                   help="span/task-event tail size")
    p.add_argument("--flight", action="append", default=[],
                   help="glob of offline flight-dump files to merge "
                        "(repeatable)")
    args = p.parse_args(argv)
    out = collect(args.address, trace_id=args.trace_id, limit=args.limit,
                  flight_globs=args.flight)
    with open(args.out, "w") as f:
        json.dump(out, f)
    meta = out["otherData"]
    print(f"trace_dump: {len(out['traceEvents'])} events "
          f"({meta['spans']} spans, {meta['flight_dumps']} flight dumps, "
          f"{meta['task_events']} task events) -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
