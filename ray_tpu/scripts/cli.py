"""ray-tpu CLI: start/join/status/submit/logs/jobs/down.

Parity target: the reference's `ray` CLI
(reference: python/ray/scripts/scripts.py — start :654, status :1682,
`ray job submit` via python/ray/dashboard/modules/job/cli.py), trimmed to
the operations a TPU pod deployment needs. Run as:

    python -m ray_tpu.scripts.cli start --head [--port P] [--num-cpus N]
    python -m ray_tpu.scripts.cli start --address HOST:PORT [--num-cpus N]
    python -m ray_tpu.scripts.cli status --address HOST:PORT
    python -m ray_tpu.scripts.cli submit --address HOST:PORT -- CMD...
    python -m ray_tpu.scripts.cli jobs --address HOST:PORT
    python -m ray_tpu.scripts.cli logs --address HOST:PORT JOB_ID
    python -m ray_tpu.scripts.cli down --address HOST:PORT
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _cmd_start(args) -> int:
    if args.head:
        # Foreground head + one node (the reference's `ray start --head`
        # daemonizes; staying foreground suits containers/systemd).
        from ray_tpu.cluster.head import HeadServer
        from ray_tpu.cluster.node_manager import NodeManager

        persist = args.persist or os.path.join(
            "/tmp/ray_tpu", f"head_state_{args.port or 0}.db")
        head = HeadServer("0.0.0.0" if args.public else "127.0.0.1",
                          args.port or 0, persist_path=persist)
        print(f"ray_tpu head listening at {head.address}", flush=True)
        resources = {"CPU": float(args.num_cpus or (os.cpu_count() or 1))}
        if args.num_tpus:
            resources["TPU"] = float(args.num_tpus)
        node = NodeManager(head.address, _new_node_id(), resources, {},
                           args.object_store_memory)
        print(f"node {node.node_id[:12]} joined with {resources}",
              flush=True)
        print(f"Connect drivers with ray_tpu.init(address="
              f"{head.address!r}) or RTPU_ADDRESS={head.address}",
              flush=True)
        return _block_forever(head, node)
    # Worker node joining an existing head.
    from ray_tpu.cluster.node_manager import NodeManager

    resources = {"CPU": float(args.num_cpus or (os.cpu_count() or 1))}
    if args.num_tpus:
        resources["TPU"] = float(args.num_tpus)
    node = NodeManager(args.address, _new_node_id(), resources, {},
                       args.object_store_memory)
    print(f"node {node.node_id[:12]} joined {args.address} "
          f"with {resources}", flush=True)
    return _block_forever(None, node)


def _new_node_id() -> str:
    import uuid

    return uuid.uuid4().hex


def _block_forever(head, node) -> int:
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        if node is not None:
            node.shutdown()
        if head is not None:
            head.shutdown()
        return 0


def _connect(address: str):
    import ray_tpu

    return ray_tpu.init(address=address, ignore_reinit_error=True)


def _cmd_status(args) -> int:
    rt = _connect(args.address)
    total, avail = rt.head.retrying_call("cluster_resources", timeout=10)
    nodes = rt.head.retrying_call("list_nodes", timeout=10)
    demand = rt.head.retrying_call("get_demand", 30.0, timeout=10)
    print(f"Nodes: {len([n for n in nodes if n['alive']])} alive "
          f"/ {len(nodes)} total")
    for n in nodes:
        state = "ALIVE" if n["alive"] else "DEAD"
        print(f"  {n['node_id'][:12]} {state:5s} {n['address']:21s} "
              f"avail={n['available']} total={n['resources']}")
    print(f"Resources: total={total} available={avail}")
    if demand["unmet"]:
        print(f"Pending demand: {len(demand['unmet'])} unmet requests "
              f"(e.g. {demand['unmet'][0]})")
    return 0


def _cmd_submit(args) -> int:
    from ray_tpu.jobs import JobSubmissionClient

    _connect(args.address)
    client = JobSubmissionClient()
    import shlex

    entrypoint = shlex.join(args.entrypoint)
    runtime_env = json.loads(args.runtime_env) if args.runtime_env else None
    job_id = client.submit_job(entrypoint=entrypoint,
                               runtime_env=runtime_env,
                               submission_id=args.submission_id)
    print(f"submitted {job_id}: {entrypoint!r}")
    if args.no_wait:
        return 0
    status = client.wait_until_finish(job_id, timeout=args.timeout)
    sys.stdout.write(client.get_job_logs(job_id))
    print(f"job {job_id} -> {status.value}")
    return 0 if status.value == "SUCCEEDED" else 1


def _cmd_jobs(args) -> int:
    from ray_tpu.jobs import JobSubmissionClient

    _connect(args.address)
    for info in JobSubmissionClient().list_jobs():
        dur = (info.end_time or time.time()) - info.start_time
        print(f"{info.submission_id:28s} {info.status:9s} {dur:7.1f}s "
              f"{info.entrypoint!r}"
              + (f"  ({info.message})" if info.message else ""))
    return 0


def _cmd_logs(args) -> int:
    from ray_tpu.jobs import JobSubmissionClient

    _connect(args.address)
    sys.stdout.write(JobSubmissionClient().get_job_logs(args.job_id))
    return 0


def _cmd_serve_deploy(args) -> int:
    import json as _json

    from ray_tpu import serve

    _connect(args.address)
    handles = serve.deploy_config(args.config)
    print(_json.dumps({"deployed": sorted(handles),
                       "status": serve.status()}, indent=1, default=str))
    return 0


def _cmd_down(args) -> int:
    rt = _connect(args.address)
    nodes = rt.head.retrying_call("list_nodes", timeout=10)
    for n in nodes:
        try:
            rt.head.retrying_call("drain_node", n["node_id"], timeout=10)
        except Exception:
            pass
    print(f"drained {len(nodes)} node(s); head remains for re-attach")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head or join a node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", help="head address to join (node mode)")
    sp.add_argument("--port", type=int, default=None)
    sp.add_argument("--public", action="store_true",
                    help="bind 0.0.0.0 instead of loopback")
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--num-tpus", type=float, default=None)
    sp.add_argument("--object-store-memory", type=int, default=2 << 30)
    sp.add_argument("--persist", default=None,
                    help="head state sqlite path (head mode)")
    sp.set_defaults(fn=_cmd_start)

    for name, fn in (("status", _cmd_status), ("jobs", _cmd_jobs),
                     ("down", _cmd_down)):
        s2 = sub.add_parser(name)
        s2.add_argument("--address", required=True)
        s2.set_defaults(fn=fn)

    s3 = sub.add_parser("submit", help="run an entrypoint as a cluster job")
    s3.add_argument("--address", required=True)
    s3.add_argument("--runtime-env", default=None,
                    help='JSON, e.g. \'{"env_vars": {"K": "V"}}\'')
    s3.add_argument("--submission-id", default=None)
    s3.add_argument("--no-wait", action="store_true")
    s3.add_argument("--timeout", type=float, default=3600.0)
    s3.add_argument("entrypoint", nargs=argparse.REMAINDER)
    s3.set_defaults(fn=_cmd_submit)

    s4 = sub.add_parser("logs")
    s4.add_argument("--address", required=True)
    s4.add_argument("job_id")
    s4.set_defaults(fn=_cmd_logs)

    s5 = sub.add_parser(
        "serve-deploy",
        help="deploy serve applications from a YAML config "
             "(reference: `serve deploy`)")
    s5.add_argument("--address", required=True)
    s5.add_argument("config", help="path to the serve YAML")
    s5.set_defaults(fn=_cmd_serve_deploy)

    args = p.parse_args(argv)
    if args.cmd == "start" and not args.head and not args.address:
        p.error("start requires --head or --address")
    if args.cmd == "submit":
        args.entrypoint = [a for a in args.entrypoint if a != "--"]
        if not args.entrypoint:
            p.error("submit requires an entrypoint after --")
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
