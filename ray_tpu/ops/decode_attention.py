"""Pallas TPU kernel: single-token decode attention over a KV cache.

The serving engine's hot op (serve/llm.py decodes one token per slot per
step): q is ONE query position per sequence attending to a long cache.
The training-shaped flash kernel (ops/attention.py dispatches to the tuned
jax.experimental.pallas.ops.tpu kernel) wants big q blocks; decode has
q_len == 1, so its arithmetic is pure KV streaming — this kernel keeps the
MXU busy by folding the GQA query-head group into the q-block rows and
streams the cache in lane-aligned blocks with the online-softmax carry in
VMEM scratch (the canonical flash pattern from the Pallas guide:
sequential innermost grid dimension + revisited output block).

Layout (grid = (B, KH, S/block_s), innermost sequential on one core):
  q    [B, KH, G, D]   one block (1,1,G,D) per (b,kh)
  k,v  [B, KH, S, D]   one block (1,1,block_s,D) per (b,kh,s)
  len  [B]             int32, SMEM scalar-prefetch (masks cache tail)
  out  [B, KH, G, D]   written on the LAST s-block

Falls back to a pure-jnp reference implementation off-TPU (and under
``interpret=True`` for the CPU test suite, which checks the kernel against
that reference exactly).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def decode_attention_reference(q, k, v, lengths):
    """Pure-jnp reference: q [B,H,D], k/v [B,S,KH,D], lengths [B] ->
    [B,H,D]. GQA via head-group repetition; masked softmax over the
    valid cache prefix."""
    b, h, d = q.shape
    s, kh = k.shape[1], k.shape[2]
    rep = h // kh
    qg = q.reshape(b, kh, rep, d)
    kk = k.transpose(0, 2, 1, 3)  # [B,KH,S,D]
    vv = v.transpose(0, 2, 1, 3)
    logits = jnp.einsum("bkgd,bksd->bkgs", qg, kk,
                        preferred_element_type=jnp.float32)
    logits = logits * (d ** -0.5)
    mask = jnp.arange(s)[None, :] < lengths[:, None]  # [B,S]
    logits = jnp.where(mask[:, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgs,bksd->bkgd", probs.astype(vv.dtype), vv)
    return out.reshape(b, h, d)


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, block_s: int, scale: float):
    import jax.experimental.pallas as pl

    b = pl.program_id(0)
    s_idx = pl.program_id(2)
    n_s = pl.num_programs(2)

    @pl.when(s_idx == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Inputs stay in their storage dtype (bf16 on the serving path): the
    # MXU takes bf16 operands with f32 accumulation via
    # preferred_element_type, and the f32 upcasts cost ~1.8x end-to-end
    # (measured 1563us -> 873us on v5e at B8/H32/KH8/S4096/D128).
    q = q_ref[0, 0]                              # [G, D]
    k = k_ref[0, 0]                              # [block_s, D]
    v = v_ref[0, 0]
    length = len_ref[b]

    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # [G, block_s] f32
    positions = s_idx * block_s + jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, 1)
    logits = jnp.where(positions < length, logits, NEG_INF)

    m_prev = m_ref[...]                          # [G, 1] carried max
    m_cur = jnp.max(logits, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    correction = jnp.exp(m_prev - m_new)
    p = jnp.exp(logits - m_new)                  # [G, block_s] f32
    # Fully-masked block (length == 0 slot): every logit == m_new ==
    # NEG_INF and exp(0) would attend UNIFORMLY to padding — clamp to 0
    # (the standard flash guard; output for an empty slot is then 0/eps).
    p = jnp.where(m_new == NEG_INF, 0.0, p)
    l_ref[...] = l_ref[...] * correction + jnp.sum(p, -1, keepdims=True)
    acc_ref[...] = acc_ref[...] * correction + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[...] = m_new

    @pl.when(s_idx == n_s - 1)
    def _finish():
        o_ref[0, 0] = (acc_ref[...]
                       / jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("block_s", "interpret", "layout"))
def decode_attention(q, k, v, lengths, *, block_s: int = 2048,
                     interpret: Optional[bool] = None,
                     layout: str = "bskd"):
    """q [B,H,D], lengths [B] int32 -> [B,H,D]. Uses the Pallas kernel on
    TPU (or interpret mode when forced); pure-jnp reference elsewhere.

    ``layout`` names the cache layout: "bskd" = [B,S,KH,D] (the training
    convention; transposed on entry — a full HBM round trip) or "bksd" =
    [B,KH,S,D] (the engine-native layout this kernel streams directly —
    store the cache this way for decode-bound serving)."""
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None:
        interpret = False
    if not on_tpu and not interpret:
        if layout == "bksd":
            k = k.transpose(0, 2, 1, 3)
            v = v.transpose(0, 2, 1, 3)
        return decode_attention_reference(q, k, v, lengths)

    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, d = q.shape
    if layout == "bskd":
        kk = k.transpose(0, 2, 1, 3)  # [B,KH,S,D]
        vv = v.transpose(0, 2, 1, 3)
    else:
        kk, vv = k, v
    kh, s = kk.shape[1], kk.shape[2]
    rep = h // kh
    if s % block_s:
        pad = block_s - s % block_s
        kk = jnp.pad(kk, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vv = jnp.pad(vv, ((0, 0), (0, 0), (0, pad), (0, 0)))
        s += pad
    qg = q.reshape(b, kh, rep, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, kh, s // block_s),
        in_specs=[
            pl.BlockSpec((1, 1, rep, d), lambda bi, ki, si, lens: (bi, ki, 0, 0)),
            pl.BlockSpec((1, 1, block_s, d),
                         lambda bi, ki, si, lens: (bi, ki, si, 0)),
            pl.BlockSpec((1, 1, block_s, d),
                         lambda bi, ki, si, lens: (bi, ki, si, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, rep, d),
                               lambda bi, ki, si, lens: (bi, ki, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, 1), jnp.float32),   # running max
            pltpu.VMEM((rep, 1), jnp.float32),   # running denom
            pltpu.VMEM((rep, d), jnp.float32),   # running numerator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, block_s=block_s,
                          scale=d ** -0.5),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kh, rep, d), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), qg, kk, vv)
    return out.reshape(b, h, d)
