"""Normalization ops (RMSNorm) — fused-friendly formulations for XLA.

Computation kept in fp32 regardless of input dtype (matches standard Llama
practice); XLA fuses the normalize+scale into neighboring elementwise work.
"""

from __future__ import annotations

from jax import lax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * lax.rsqrt(var + eps)
    return (y * (1.0 + scale.astype(jnp.float32))).astype(dtype)
