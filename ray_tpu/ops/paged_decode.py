"""Pallas TPU kernel: paged single-token decode attention.

PagedAttention-style (Kwon et al. 2023) counterpart to
``decode_attention.py``: instead of attending over one contiguous
``[B, KH, S, D]`` cache row per sequence, the kernel reads a
block-granular KV cache IN PLACE through a **block table** — sequence
``b``'s logical page ``p`` lives wherever ``block_table[b, p]`` says,
anywhere in the cache pool. No gather, no copy: the table drives the
kernel's BlockSpec index map, so each page is DMA'd straight from its
resident location, and pages past ``ceil(length/page)`` are never
streamed (the index map parks them on the last valid page, which Pallas'
revisited-block elision turns into zero extra traffic).

Page-id convention: the pool is the engine's own cache array
``[B_pool, KH, S, D]`` viewed as ``B_pool * S/page`` pages in row-major
(pool row, then page-within-row) order — page ``t`` is rows
``[(t % np_row) * page, ...)`` of pool row ``t // np_row``. The serving
engine's table is slot-identity today (``kv_manager`` keeps prefixes
slot-affine), which makes the paged read bit-equal to the contiguous
one; the table indirection is the seam that lets future cross-slot
paging / disaggregated-prefill KV shipping land without touching the
kernel.

Falls back to a pure-jnp gather reference off-TPU (and checks the
kernel against it exactly under ``interpret=True`` — the
``decode_attention.py``/``fused.py`` test idiom).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def paged_decode_attention_reference(q, k, v, block_table, lengths,
                                     page_size: int):
    """Pure-jnp reference: q [B,H,D], k/v [Bp,KH,S,D] page pools,
    block_table [B,NP] int32 flat page ids, lengths [B] -> [B,H,D].

    Gathers the table's pages into a contiguous per-sequence cache and
    runs the masked-softmax reference — the exact computation the
    in-place kernel must reproduce (and exactly what the kernel
    replaces: this gather is the HBM round trip the paged read avoids).
    """
    b, h, d = q.shape
    bp, kh, s, _ = k.shape
    np_row = s // page_size
    n_pages = block_table.shape[1]
    # Page t = rows [(t % np_row) * page, ...) of pool row t // np_row:
    # split S into pages FIRST, then flatten (pool row, page-in-row).
    kp = jnp.moveaxis(k.reshape(bp, kh, np_row, page_size, d),
                      2, 1).reshape(bp * np_row, kh, page_size, d)
    vp = jnp.moveaxis(v.reshape(bp, kh, np_row, page_size, d),
                      2, 1).reshape(bp * np_row, kh, page_size, d)
    # [B, NP, KH, page, D] -> [B, KH, NP*page, D]
    kk = jnp.moveaxis(kp[block_table], 2, 1).reshape(
        b, kh, n_pages * page_size, d)
    vv = jnp.moveaxis(vp[block_table], 2, 1).reshape(
        b, kh, n_pages * page_size, d)
    rep = h // kh
    qg = q.reshape(b, kh, rep, d)
    logits = jnp.einsum("bkgd,bksd->bkgs", qg, kk,
                        preferred_element_type=jnp.float32) * (d ** -0.5)
    mask = (jnp.arange(n_pages * page_size)[None, :]
            < lengths[:, None])  # [B, NP*page]
    logits = jnp.where(mask[:, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    # A zero-length slot's row is fully masked: uniform softmax over
    # NEG_INF would attend to garbage — zero it like the kernel does.
    probs = jnp.where(mask[:, None, None, :], probs, 0.0)
    out = jnp.einsum("bkgs,bksd->bkgd", probs.astype(vv.dtype), vv)
    return out.reshape(b, h, d)


def _paged_kernel(table_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  m_ref, l_ref, acc_ref, *, page_size: int, scale: float):
    import jax.experimental.pallas as pl

    b = pl.program_id(0)
    p = pl.program_id(2)
    n_p = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    length = len_ref[b]

    # Pages at or past ceil(length/page) were remapped by the index map
    # onto the last valid page (no fresh DMA); skip their compute too.
    @pl.when(p * page_size < length)
    def _accumulate():
        q = q_ref[0, 0]                          # [G, D]
        k = k_ref[0, 0]                          # [page, D]
        v = v_ref[0, 0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [G, page] f32
        positions = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 1)
        logits = jnp.where(positions < length, logits, NEG_INF)
        m_prev = m_ref[...]                      # [G, 1] carried max
        m_new = jnp.maximum(m_prev,
                            jnp.max(logits, axis=-1, keepdims=True))
        correction = jnp.exp(m_prev - m_new)
        probs = jnp.exp(logits - m_new)          # [G, page] f32
        probs = jnp.where(m_new == NEG_INF, 0.0, probs)
        l_ref[...] = (l_ref[...] * correction
                      + jnp.sum(probs, -1, keepdims=True))
        acc_ref[...] = acc_ref[...] * correction + jax.lax.dot_general(
            probs.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(p == n_p - 1)
    def _finish():
        o_ref[0, 0] = (acc_ref[...]
                       / jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("page_size", "interpret"))
def paged_decode_attention(q, k, v, block_table, lengths, *,
                           page_size: int,
                           interpret: Optional[bool] = None):
    """q [B,H,D], k/v [Bp,KH,S,D] page pools (S a multiple of
    ``page_size``), block_table [B,NP] int32 flat page ids, lengths [B]
    int32 -> [B,H,D]. Pallas kernel on TPU (or under ``interpret``);
    pure-jnp gather reference elsewhere."""
    bp, kh, s, d = k.shape
    if s % page_size:
        raise ValueError(f"cache rows {s} not a multiple of the "
                         f"{page_size}-row page (pad the allocation)")
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None:
        interpret = False
    if not on_tpu and not interpret:
        return paged_decode_attention_reference(q, k, v, block_table,
                                                lengths, page_size)

    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, _ = q.shape
    np_row = s // page_size
    n_pages = block_table.shape[1]
    rep = h // kh
    qg = q.reshape(b, kh, rep, d)

    def _kv_index(bi, ki, pi, table, lens):
        """Physical block of logical page ``pi`` of sequence ``bi`` —
        pages past ceil(length/page) park on the last valid one, so the
        revisited block needs no fresh copy."""
        valid = jax.lax.div(lens[bi] + page_size - 1, page_size)
        p_eff = jnp.minimum(pi, jnp.maximum(valid - 1, 0))
        t = table[bi, p_eff]
        return jax.lax.div(t, np_row), ki, jax.lax.rem(t, np_row), 0

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kh, n_pages),
        in_specs=[
            pl.BlockSpec((1, 1, rep, d),
                         lambda bi, ki, pi, table, lens: (bi, ki, 0, 0)),
            pl.BlockSpec((1, 1, page_size, d), _kv_index),
            pl.BlockSpec((1, 1, page_size, d), _kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, rep, d),
            lambda bi, ki, pi, table, lens: (bi, ki, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, 1), jnp.float32),   # running max
            pltpu.VMEM((rep, 1), jnp.float32),   # running denom
            pltpu.VMEM((rep, d), jnp.float32),   # running numerator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_kernel, page_size=page_size,
                          scale=d ** -0.5),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kh, rep, d), q.dtype),
        interpret=interpret,
    )(block_table.astype(jnp.int32), lengths.astype(jnp.int32),
      qg, k, v)
    return out.reshape(b, h, d)
