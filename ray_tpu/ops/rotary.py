"""Rotary position embeddings (RoPE), Llama-3 style (half-dim rotation)."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float = 500000.0) -> jnp.ndarray:
    """Inverse frequencies for each pair of rotated dims: [head_dim // 2]."""
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta ** exponents)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray,
               theta: float = 500000.0) -> jnp.ndarray:
    """Rotate ``x`` [..., seq, heads, head_dim] by per-position angles.

    ``positions``: integer array broadcastable to [..., seq] — passing explicit
    positions (rather than arange) keeps the same code path correct for
    sequence-sharded (ring attention) and KV-cache decode cases.
    """
    dtype = x.dtype
    freqs = rope_frequencies(x.shape[-1], theta)                # [hd/2]
    angles = positions[..., None].astype(jnp.float32) * freqs   # [..., seq, hd/2]
    angles = angles[..., None, :]                               # [..., seq, 1, hd/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(dtype)
