"""Fused Pallas TPU kernels for the per-layer model-path glue.

The transformer block's non-matmul work — RMSNorm, rotary embedding,
SwiGLU — is memory-bound elementwise/reduction glue between matmuls.
Left to XLA it becomes several HBM round trips per block (norm reads x,
rope reads q and k separately and recomputes cos/sin twice, the silu
and multiply each materialize a [B,S,F] temp). Each op here makes ONE
pass over its operands in VMEM:

- ``fused_rms_norm``          — fp32 normalize + scale in one pass.
- ``fused_rms_norm_residual`` — residual add folded into the next norm:
  returns ``(normed, summed)`` so the block's ``x = x + attn; h =
  rms_norm(x)`` pair reads/writes ``x`` once.
- ``fused_qk_rope``           — one kernel rotates BOTH the q and k
  projection outputs, computing the cos/sin tables once per position
  (the unfused path recomputes them per tensor).
- ``fused_swiglu``            — ``silu(gate) * up`` in fp32 without a
  materialized intermediate.

Each op follows the ``ops/decode_attention.py`` idiom: a pure-jnp
reference (the exact pre-fusion formulation), a Pallas kernel, and a
dispatcher that runs the kernel on TPU (or under ``interpret=True`` on
CPU — the test suite checks kernel-vs-reference equivalence that way)
and the reference elsewhere. Every op carries a custom VJP (backward in
plain jnp, checked against autodiff of the reference) so the TRAINING
path can use the fused forward under ``jax.checkpoint``; models opt in
via ``LlamaConfig.fused_ops``.

Kernel-body discipline (now ENFORCED by jax-lint's
``pallas-shape-rules`` — ``python -m ray_tpu.devtools.lint --family
jax``): every intermediate stays >= 2D (reductions carry
``keepdims=True``), iota is ``lax.broadcasted_iota`` (never a 1D
``jnp.arange``), and no reshape happens inside a kernel body —
relayouts belong to the host-side wrappers and BlockSpecs. These are
the classic Mosaic lowering failures this file originally worked
around by hand; the linter keeps the next kernel from rediscovering
them.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ray_tpu.ops.norms import rms_norm as rms_norm_reference
from ray_tpu.ops.rotary import apply_rope as apply_rope_reference

_ROW_BLOCKS = (128, 64, 32, 16, 8, 4, 2, 1)
_COL_BLOCKS = (1024, 512, 256, 128)


def _row_block(n: int) -> int:
    return next(c for c in _ROW_BLOCKS if n % c == 0)


def _col_block(n: int) -> int:
    for c in _COL_BLOCKS:
        if n % c == 0:
            return c
    return n  # small/ragged feature dim: one block spans it


def _use_kernel(interpret: bool) -> bool:
    return interpret or jax.default_backend() == "tpu"


# ---------------------------------------------------------------- RMSNorm

def _rms_kernel(x_ref, s_ref, o_ref, *, eps: float):
    xf = x_ref[...].astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * lax.rsqrt(var + eps)
    o_ref[...] = (y * (1.0 + s_ref[...].astype(jnp.float32))
                  ).astype(o_ref.dtype)


def _rms_res_kernel(x_ref, r_ref, s_ref, o_ref, sum_ref, *, eps: float):
    # The residual add happens in the STORAGE dtype (matching the
    # unfused ``x = x + attn`` it replaces), then the norm upcasts.
    u = x_ref[...] + r_ref[...]
    sum_ref[...] = u
    uf = u.astype(jnp.float32)
    var = jnp.mean(uf * uf, axis=-1, keepdims=True)
    y = uf * lax.rsqrt(var + eps)
    o_ref[...] = (y * (1.0 + s_ref[...].astype(jnp.float32))
                  ).astype(o_ref.dtype)


def _rms_impl(x, scale, eps, interpret, residual=None):
    import jax.experimental.pallas as pl

    shape = x.shape
    d = shape[-1]
    x2 = x.reshape(-1, d)
    n = x2.shape[0]
    bn = _row_block(n)
    s2 = scale.reshape(1, d)
    row_spec = pl.BlockSpec((bn, d), lambda i: (i, 0))
    scale_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    if residual is None:
        out = pl.pallas_call(
            functools.partial(_rms_kernel, eps=eps),
            grid=(n // bn,),
            in_specs=[row_spec, scale_spec],
            out_specs=row_spec,
            out_shape=jax.ShapeDtypeStruct((n, d), x.dtype),
            interpret=interpret,
        )(x2, s2)
        return out.reshape(shape)
    r2 = residual.reshape(-1, d)
    out, summed = pl.pallas_call(
        functools.partial(_rms_res_kernel, eps=eps),
        grid=(n // bn,),
        in_specs=[row_spec, row_spec, scale_spec],
        out_specs=[row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct((n, d), x.dtype),
                   jax.ShapeDtypeStruct((n, d), residual.dtype)],
        interpret=interpret,
    )(x2, r2, s2)
    return out.reshape(shape), summed.reshape(shape)


def _rms_bwd_math(u, scale, gy, eps):
    """Backward of y = rms_norm(u) * (1 + scale) w.r.t. (u, scale)."""
    uf = u.astype(jnp.float32)
    gf = gy.astype(jnp.float32)
    r = lax.rsqrt(jnp.mean(uf * uf, axis=-1, keepdims=True) + eps)
    n_ = uf * r
    dn = gf * (1.0 + scale.astype(jnp.float32))
    du = r * (dn - n_ * jnp.mean(dn * n_, axis=-1, keepdims=True))
    ds = jnp.sum(gf * n_, axis=tuple(range(u.ndim - 1)))
    return du, ds


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms_p(x, scale, eps, interpret):
    if not _use_kernel(interpret):
        return rms_norm_reference(x, scale, eps)
    return _rms_impl(x, scale, eps, interpret)


def _rms_fwd(x, scale, eps, interpret):
    return _rms_p(x, scale, eps, interpret), (x, scale)


def _rms_bwd(eps, interpret, res, gy):
    x, scale = res
    du, ds = _rms_bwd_math(x, scale, gy, eps)
    return du.astype(x.dtype), ds.astype(scale.dtype)


_rms_p.defvjp(_rms_fwd, _rms_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _rms_res_p(x, residual, scale, eps, interpret):
    if not _use_kernel(interpret):
        u = x + residual
        return rms_norm_reference(u, scale, eps), u
    return _rms_impl(x, scale, eps, interpret, residual=residual)


def _rms_res_fwd(x, residual, scale, eps, interpret):
    y, u = _rms_res_p(x, residual, scale, eps, interpret)
    return (y, u), (u, scale)


def _rms_res_bwd(eps, interpret, res, gs):
    u, scale = res
    gy, gsum = gs
    du, ds = _rms_bwd_math(u, scale, gy, eps)
    du = du + gsum.astype(jnp.float32)
    return (du.astype(u.dtype), du.astype(u.dtype), ds.astype(scale.dtype))


_rms_res_p.defvjp(_rms_res_fwd, _rms_res_bwd)


def fused_rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-5,
                   *, interpret: bool = False) -> jnp.ndarray:
    """One-pass RMSNorm (fp32 compute): Pallas kernel on TPU / under
    ``interpret``; the exact ``ops.norms.rms_norm`` reference elsewhere.
    Differentiable (custom VJP) either way."""
    return _rms_p(x, scale, float(eps), bool(interpret))


def fused_rms_norm_residual(x: jnp.ndarray, residual: jnp.ndarray,
                            scale: jnp.ndarray, eps: float = 1e-5,
                            *, interpret: bool = False):
    """Residual add folded into the norm: returns ``(normed, x +
    residual)`` in one pass over the operands."""
    return _rms_res_p(x, residual, scale, float(eps), bool(interpret))


# ------------------------------------------------------------------ RoPE

def _rope_kernel(pos_ref, q_ref, k_ref, oq_ref, ok_ref, *, theta: float):
    # All intermediates stay >= 2D and no cross-lane reshapes happen
    # (1D vectors and (1,N)->(N,1) relayouts are the classic Mosaic
    # lowering failures); broadcasting inserts the unit axes instead.
    d = q_ref.shape[-1]
    half = d // 2
    # Same formulation as ops.rotary.rope_frequencies: 1 / theta^(2i/d).
    expo = lax.broadcasted_iota(jnp.float32, (1, 1, half), 2) * (2.0 / d)
    inv = 1.0 / (theta ** expo)                            # [1, 1, half]
    ang = pos_ref[...].astype(jnp.float32)[..., None] * inv  # [1,bs,half]
    cos = jnp.cos(ang)[:, :, None, :]                    # [1,bs,1,half]
    sin = jnp.sin(ang)[:, :, None, :]
    for ref, out in ((q_ref, oq_ref), (k_ref, ok_ref)):
        x = ref[...].astype(jnp.float32)                 # [1,bs,H,D]
        x1, x2 = x[..., :half], x[..., half:]
        out[...] = jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin],
            axis=-1).astype(out.dtype)


def _rope_impl(q, k, positions, theta, interpret):
    import jax.experimental.pallas as pl

    b, s, h, d = q.shape
    kh = k.shape[2]
    bs = _row_block(s)
    qspec = pl.BlockSpec((1, bs, h, d), lambda bi, si: (bi, si, 0, 0))
    kspec = pl.BlockSpec((1, bs, kh, d), lambda bi, si: (bi, si, 0, 0))
    pspec = pl.BlockSpec((1, bs), lambda bi, si: (bi, si))
    return pl.pallas_call(
        functools.partial(_rope_kernel, theta=theta),
        grid=(b, s // bs),
        in_specs=[pspec, qspec, kspec],
        out_specs=[qspec, kspec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                   jax.ShapeDtypeStruct(k.shape, k.dtype)],
        interpret=interpret,
    )(positions, q, k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _rope_qk_p(q, k, positions, theta, interpret):
    if not _use_kernel(interpret):
        return (apply_rope_reference(q, positions, theta),
                apply_rope_reference(k, positions, theta))
    return _rope_impl(q, k, positions, theta, interpret)


def _rope_qk_fwd(q, k, positions, theta, interpret):
    return _rope_qk_p(q, k, positions, theta, interpret), (positions,)


def _rope_qk_bwd(theta, interpret, res, gs):
    # Rotation is orthogonal: the VJP rotates the cotangents by -angle,
    # i.e. the same kernel with negated positions.
    (positions,) = res
    gq, gk = gs
    dq, dk = _rope_qk_p(gq, gk, -positions, theta, interpret)
    dpos = np.zeros(positions.shape, jax.dtypes.float0)
    return dq, dk, dpos


_rope_qk_p.defvjp(_rope_qk_fwd, _rope_qk_bwd)


def fused_qk_rope(q: jnp.ndarray, k: jnp.ndarray, positions: jnp.ndarray,
                  theta: float = 500000.0, *, interpret: bool = False):
    """Rotate the q AND k projection outputs in one kernel: q [B,S,H,D],
    k [B,S,KH,D], positions [B,S] int. The cos/sin tables are computed
    once per position (the unfused path recomputes them per tensor).
    Returns ``(q_rot, k_rot)``; matches two ``ops.rotary.apply_rope``
    calls."""
    return _rope_qk_p(q, k, positions, float(theta), bool(interpret))


# ---------------------------------------------------------------- SwiGLU

def swiglu_reference(gate: jnp.ndarray, up: jnp.ndarray) -> jnp.ndarray:
    """The unfused formulation from the block: ``silu(gate) * up``
    computed in fp32 (kernel and reference share the upcast)."""
    out = jax.nn.silu(gate.astype(jnp.float32)) * up.astype(jnp.float32)
    return out.astype(gate.dtype)


def _swiglu_kernel(g_ref, u_ref, o_ref):
    gf = g_ref[...].astype(jnp.float32)
    uf = u_ref[...].astype(jnp.float32)
    o_ref[...] = (gf * jax.nn.sigmoid(gf) * uf).astype(o_ref.dtype)


def _swiglu_impl(gate, up, interpret):
    import jax.experimental.pallas as pl

    shape = gate.shape
    f = shape[-1]
    g2 = gate.reshape(-1, f)
    n = g2.shape[0]
    bn = _row_block(n)
    bf = _col_block(f)
    spec = pl.BlockSpec((bn, bf), lambda i, j: (i, j))
    out = pl.pallas_call(
        _swiglu_kernel,
        grid=(n // bn, f // bf),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((n, f), gate.dtype),
        interpret=interpret,
    )(g2, up.reshape(-1, f))
    return out.reshape(shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _swiglu_p(gate, up, interpret):
    if not _use_kernel(interpret):
        return swiglu_reference(gate, up)
    return _swiglu_impl(gate, up, interpret)


def _swiglu_fwd(gate, up, interpret):
    return _swiglu_p(gate, up, interpret), (gate, up)


def _swiglu_bwd(interpret, res, g):
    gate, up = res
    gf = gate.astype(jnp.float32)
    uf = up.astype(jnp.float32)
    cot = g.astype(jnp.float32)
    sig = jax.nn.sigmoid(gf)
    dgate = cot * uf * sig * (1.0 + gf * (1.0 - sig))
    dup = cot * gf * sig
    return dgate.astype(gate.dtype), dup.astype(up.dtype)


_swiglu_p.defvjp(_swiglu_fwd, _swiglu_bwd)


def fused_swiglu(gate: jnp.ndarray, up: jnp.ndarray,
                 *, interpret: bool = False) -> jnp.ndarray:
    """``silu(gate) * up`` in one pass (fp32 compute, no materialized
    silu intermediate)."""
    return _swiglu_p(gate, up, bool(interpret))
