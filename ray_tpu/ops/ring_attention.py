"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

The reference has NO sequence parallelism (SURVEY.md §2.4: grep over the Ray
tree finds no ring-attention/Ulysses implementation — long context is deferred
to vLLM/torch). Here it is a first-class op: the sequence dimension is sharded
over the ``sp`` mesh axis, and K/V blocks rotate around the ring via
`lax.ppermute` (one ICI hop per step) while each device accumulates its local
queries' attention with a numerically-stable online softmax (flash-attention
style m/l running stats).

Causality is enforced by *global position* comparison, so the blocks never
need re-ordering: a device holding queries at positions [2048:4096) simply
masks out rotated K/V positions above its own.

Used by `models/llama.py` whenever the mesh has sp > 1; compute per step stays
a large [B, Sq/sp, Sk/sp] matmul that tiles onto the MXU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from jax import lax
import jax.numpy as jnp
try:  # jax >= 0.5 exports shard_map at top level
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax in CI images
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import NEG_INF, online_softmax_update


def _ring_attention_local(q, k, v, q_pos, k_pos, *, axis_name: str,
                          scale: Optional[float] = None):
    """Per-shard body (runs inside shard_map). Shapes are the LOCAL shard:
    q [B, Sq, H, D], k/v [B, Sk, KH, D], q_pos/k_pos [B, S*].

    K/V rotate around the ring UN-repeated ([…,KH,D]); GQA expansion to the
    full query-head count happens inside `online_softmax_update`, after the
    ppermute — so each ICI hop carries only KH/H of the naive bytes.
    """
    n = lax.psum(1, axis_name)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b, sq, heads, d = q.shape

    # Build the accumulators FROM q so they carry exactly q's varying-axes
    # type (sp plus any dp/fsdp/tp axes the caller sharded over) — required
    # for a well-typed fori_loop carry under shard_map's vma tracking.
    qz = jnp.transpose(q.astype(jnp.float32), (0, 2, 1, 3)) * 0.0  # [B,H,Sq,D]
    m0 = qz[..., 0] + NEG_INF
    l0 = qz[..., 0]
    o0 = qz
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(_, carry):
        m, l, o, kc, vc, kpc = carry
        m, l, o = online_softmax_update(q, kc, vc, q_pos, kpc, m, l, o, scale)
        # Rotate K/V (and their global positions) one hop around the ring.
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        kpc = lax.ppermute(kpc, axis_name, perm)
        return m, l, o, kc, vc, kpc

    m, l, o, _, _, _ = lax.fori_loop(0, n, step, (m0, l0, o0, k, v, k_pos))
    o = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(o, (0, 2, 1, 3)).astype(q.dtype)   # [B,Sq,H,D]


def ring_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    q_positions: jnp.ndarray, kv_positions: jnp.ndarray,
    *, mesh: Mesh, sp_axis: str = "sp",
    batch_spec=("dp", "fsdp"), heads_axis: str = "tp",
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Sequence-parallel causal attention over ``mesh[sp_axis]``.

    Inputs are GLOBAL arrays (inside jit); shard_map splits seq over sp.
    q/k/v: [B, S, H|KH, D]; positions: [B, S] global token positions.
    """
    qkv_spec = P(batch_spec, sp_axis, heads_axis, None)
    pos_spec = P(batch_spec, sp_axis)
    fn = functools.partial(_ring_attention_local, axis_name=sp_axis, scale=scale)
    return shard_map(
        fn, mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, pos_spec, pos_spec),
        out_specs=qkv_spec,
    )(q, k, v, q_positions, kv_positions)
