"""Public kernel API for the model path.

Every op pairs a portable jnp reference with a TPU-tuned fast path and a
dispatcher that picks between them; callers import from THIS package,
not the submodules. Dispatch conditions:

=========================  ===============================  =========================================
op                         TPU fast path                    dispatch condition
=========================  ===============================  =========================================
full_causal_attention      Pallas flash kernel (fwd+bwd)    ``use_fused_kernel``: standard arange
                                                            positions, seq >= 256 and % 128 == 0,
                                                            head_dim <= 128 or % 128 == 0; else
                                                            blockwise scan (seq >= 1024) / dense
causal_attention           (portable dense reference)       always available; position-based masks
blockwise_attention        (portable online-softmax scan)   seq a multiple of ``block_k``
decode_attention           Pallas single-query kernel       on TPU, or ``interpret=True`` off-TPU;
                                                            jnp reference elsewhere
paged_decode_attention     Pallas block-table kernel:       ``LlamaConfig.paged_decode`` (engine knob
                           reads the paged KV cache IN      ``paged_decode=True``): kernel on TPU or
                           PLACE through the table's        under ``interpret``; jnp gather reference
                           index map, streaming only        elsewhere. Cache rows must be a multiple
                           ceil(len/page) pages/seq         of ``decode_page`` (engine pads). Greedy
                                                            output token-identical to the unpaged
                                                            paths (identity table == contiguous read)
ring_attention             shard_map ppermute ring          mesh ``sp`` axis > 1 (the ONLY module
                                                            allowed to import shard_map — rtpu-lint
                                                            banned-API rule)
rms_norm                   (fp32 jnp reference)             always; the fused ops' exactness anchor
apply_rope                 (fp32 jnp reference)             always
fused_rms_norm             Pallas one-pass norm kernel      ``LlamaConfig.fused_ops``: kernel on TPU
fused_rms_norm_residual    + residual-add fold              or under ``interpret``; reference impl
fused_qk_rope              one kernel for q AND k           elsewhere (same custom VJP both ways,
fused_swiglu               silu(gate)*up, no temp           so the train path may fuse too)
=========================  ===============================  =========================================
"""

from ray_tpu.ops.attention import (
    blockwise_attention,
    causal_attention,
    full_causal_attention,
    online_softmax_update,
    repeat_kv,
    use_fused_kernel,
)
from ray_tpu.ops.decode_attention import (
    decode_attention,
    decode_attention_reference,
)
from ray_tpu.ops.fused import (
    fused_qk_rope,
    fused_rms_norm,
    fused_rms_norm_residual,
    fused_swiglu,
    swiglu_reference,
)
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.paged_decode import (
    paged_decode_attention,
    paged_decode_attention_reference,
)
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.ops.rotary import apply_rope, rope_frequencies

__all__ = [
    "apply_rope",
    "blockwise_attention",
    "causal_attention",
    "decode_attention",
    "decode_attention_reference",
    "full_causal_attention",
    "fused_qk_rope",
    "fused_rms_norm",
    "fused_rms_norm_residual",
    "fused_swiglu",
    "online_softmax_update",
    "paged_decode_attention",
    "paged_decode_attention_reference",
    "repeat_kv",
    "ring_attention",
    "rms_norm",
    "rope_frequencies",
    "swiglu_reference",
    "use_fused_kernel",
]
