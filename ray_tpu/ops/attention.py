"""Causal grouped-query attention: dispatcher + portable paths.

`full_causal_attention` dispatches to the fused Pallas TPU flash kernel
(jax.experimental.pallas.ops.tpu.flash_attention, with block sizes tuned
for Llama shapes — see `use_fused_kernel`); the blockwise online-softmax
scan below is the portable path (CPU tests, ragged shapes), and
`ops/ring_attention.py` covers sequence parallelism over the ``sp`` axis.

Shapes follow [batch, seq, heads, head_dim] throughout ("BSHD").
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

NEG_INF = -1e30


def repeat_kv(k: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """Expand KV heads to match query heads for GQA: [B,S,K,D] -> [B,S,K*n,D]."""
    if n_rep == 1:
        return k
    b, s, kh, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, kh, n_rep, d)).reshape(
        b, s, kh * n_rep, d)


def causal_attention(
    q: jnp.ndarray,                 # [B, Sq, H, D]
    k: jnp.ndarray,                 # [B, Sk, KH, D]
    v: jnp.ndarray,                 # [B, Sk, KH, D]
    *,
    q_positions: Optional[jnp.ndarray] = None,   # [B, Sq] global positions
    kv_positions: Optional[jnp.ndarray] = None,  # [B, Sk]
    kv_mask: Optional[jnp.ndarray] = None,       # [B, Sk] valid-kv mask (decode)
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Softmax(QK^T)V with causal masking by *global position*.

    Position-based masking (not index-based) makes the same function serve
    full prefill, chunked prefill, and single-token decode against a KV cache.
    """
    b, sq, h, d = q.shape
    kh = k.shape[2]
    if h != kh:
        rep = h // kh
        k = repeat_kv(k, rep)
        v = repeat_kv(v, rep)
    if scale is None:
        scale = d ** -0.5
    if q_positions is None:
        q_positions = jnp.broadcast_to(jnp.arange(sq), (b, sq))
    if kv_positions is None:
        kv_positions = jnp.broadcast_to(jnp.arange(k.shape[1]), (b, k.shape[1]))

    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    causal = q_positions[:, None, :, None] >= kv_positions[:, None, None, :]
    if kv_mask is not None:
        causal = jnp.logical_and(causal, kv_mask[:, None, None, :])
    logits = jnp.where(causal, logits, NEG_INF)
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out


def online_softmax_update(q, k, v, q_pos, k_pos, m, l, o, scale):
    """One flash-style online-softmax accumulation step against a K/V block.

    The single shared implementation for the blockwise scan (below) and the
    ring-attention ppermute loop (`ops/ring_attention.py`). GQA-aware: k/v may
    have fewer heads ([B,Sk,KH,D]); they are expanded here, AFTER any
    inter-chip transfer, so ring hops move only the un-repeated KV bytes.

    q: [B,Sq,H,D]; accumulators m,l: [B,H,Sq] fp32, o: [B,H,Sq,D] fp32.
    """
    h, kh = q.shape[2], k.shape[2]
    if h != kh:
        k = repeat_kv(k, h // kh)
        v = repeat_kv(v, h // kh)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    mask = q_pos[:, None, :, None] >= k_pos[:, None, None, :]
    s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.where(mask, jnp.exp(s - m_new[..., None]), 0.0)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return m_new, l_new, o_new


def blockwise_attention(
    q: jnp.ndarray,                 # [B, Sq, H, D]
    k: jnp.ndarray,                 # [B, Sk, KH, D]
    v: jnp.ndarray,                 # [B, Sk, KH, D]
    *,
    q_positions: jnp.ndarray,       # [B, Sq]
    kv_positions: jnp.ndarray,      # [B, Sk]
    scale: Optional[float] = None,
    block_k: int = 512,
) -> jnp.ndarray:
    """Flash-style online-softmax attention, scanning KV in blocks.

    Never materializes the [Sq, Sk] score matrix: peak temp is
    [B, H, Sq, block_k]. Portable (CPU tests, TPU fallback when the Pallas
    kernel does not apply); numerics match `causal_attention`.
    """
    import jax
    from jax import lax

    b, sq, h, d = q.shape
    sk, kh = k.shape[1], k.shape[2]
    if scale is None:
        scale = d ** -0.5
    if sk % block_k or sk < block_k:
        # Ragged tail: fall back to the dense path.
        return causal_attention(q, k, v, q_positions=q_positions,
                                kv_positions=kv_positions, scale=scale)
    n_blocks = sk // block_k
    kb = k.reshape(b, n_blocks, block_k, kh, d).swapaxes(0, 1)
    vb = v.reshape(b, n_blocks, block_k, kh, d).swapaxes(0, 1)
    pb = kv_positions.reshape(b, n_blocks, block_k).swapaxes(0, 1)

    m0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o0 = jnp.zeros((b, h, sq, d), jnp.float32)

    def step(carry, blk):
        m, l, o = carry
        kc, vc, kp = blk
        m, l, o = online_softmax_update(q, kc, vc, q_positions, kp,
                                        m, l, o, scale)
        return (m, l, o), None

    (m, l, o), _ = lax.scan(
        jax.checkpoint(step), (m0, l0, o0), (kb, vb, pb))
    o = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(o, (0, 2, 1, 3)).astype(q.dtype)


def _default_positions(q_positions, kv_positions, b, sq, sk) -> bool:
    """True iff positions are the standard full-sequence arange (the only
    pattern the fused TPU kernel's `causal=True` flag encodes)."""
    if q_positions is None and kv_positions is None:
        return sq == sk
    return False


def use_fused_kernel(on_tpu: bool, standard: bool, sq: int, d: int) -> bool:
    """The fused-flash dispatch gate, exposed for tests: the kernel accepts
    any head_dim <= 128 (lane-padded internally) or an exact multiple of
    128 — Llama-class head_dim=64/128 both qualify."""
    return (on_tpu and standard and sq >= 256 and sq % 128 == 0
            and (d <= 128 or d % 128 == 0))


def full_causal_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    *,
    q_positions: Optional[jnp.ndarray] = None,
    kv_positions: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Training-path attention dispatcher (full sequence, causal).

    TPU: fused Pallas flash kernel (jax.experimental.pallas.ops.tpu) — no
    [Sq,Sk] materialization, fwd+bwd kernels. Elsewhere / ragged shapes:
    blockwise online-softmax scan, then dense for short sequences.
    """
    import jax

    b, sq, h, d = q.shape
    sk = k.shape[1]
    if scale is None:
        scale = d ** -0.5
    on_tpu = jax.devices()[0].platform == "tpu"
    standard = _default_positions(q_positions, kv_positions, b, sq, sk)
    if use_fused_kernel(on_tpu, standard, sq, d):
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            BlockSizes,
            flash_attention as _tpu_flash,
        )

        kh = k.shape[2]
        if h != kh:
            k = repeat_kv(k, h // kh)
            v = repeat_kv(v, h // kh)
        qt = jnp.transpose(q, (0, 2, 1, 3))
        kt = jnp.transpose(k, (0, 2, 1, 3))
        vt = jnp.transpose(v, (0, 2, 1, 3))
        # The library defaults (block_k_major=128) leave the MXU idle between
        # tiny grid steps — measured 4x slower than 1024-blocks at Llama
        # shapes on v5e. Use the largest block <=1024 that divides seq.
        blk = next(c for c in (1024, 512, 256, 128) if sq % c == 0)
        bq = bk = min(blk, sq)
        bs = BlockSizes(
            block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
            block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
            block_q_dkv=bq, block_k_major_dq=bk, block_k_dq=bk,
            block_q_dq=bq,
        )
        out = _tpu_flash(qt, kt, vt, causal=True, sm_scale=scale,
                         block_sizes=bs)
        return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)
    if q_positions is None:
        q_positions = jnp.broadcast_to(jnp.arange(sq), (b, sq))
    if kv_positions is None:
        kv_positions = jnp.broadcast_to(jnp.arange(sk), (b, sk))
    if sk >= 1024:
        return blockwise_attention(q, k, v, q_positions=q_positions,
                                   kv_positions=kv_positions, scale=scale)
    return causal_attention(q, k, v, q_positions=q_positions,
                            kv_positions=kv_positions, scale=scale)
