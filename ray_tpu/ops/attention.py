"""Causal grouped-query attention — XLA reference path.

This is the portable implementation (CPU tests + TPU fallback). The hot TPU
paths are `ops/pallas/flash_attention.py` (fused kernel) and
`ops/ring_attention.py` (sequence-parallel over the ``sp`` mesh axis).

Shapes follow [batch, seq, heads, head_dim] throughout ("BSHD").
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

NEG_INF = -1e30


def repeat_kv(k: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """Expand KV heads to match query heads for GQA: [B,S,K,D] -> [B,S,K*n,D]."""
    if n_rep == 1:
        return k
    b, s, kh, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, kh, n_rep, d)).reshape(
        b, s, kh * n_rep, d)


def causal_attention(
    q: jnp.ndarray,                 # [B, Sq, H, D]
    k: jnp.ndarray,                 # [B, Sk, KH, D]
    v: jnp.ndarray,                 # [B, Sk, KH, D]
    *,
    q_positions: Optional[jnp.ndarray] = None,   # [B, Sq] global positions
    kv_positions: Optional[jnp.ndarray] = None,  # [B, Sk]
    kv_mask: Optional[jnp.ndarray] = None,       # [B, Sk] valid-kv mask (decode)
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Softmax(QK^T)V with causal masking by *global position*.

    Position-based masking (not index-based) makes the same function serve
    full prefill, chunked prefill, and single-token decode against a KV cache.
    """
    b, sq, h, d = q.shape
    kh = k.shape[2]
    if h != kh:
        rep = h // kh
        k = repeat_kv(k, rep)
        v = repeat_kv(v, rep)
    if scale is None:
        scale = d ** -0.5
    if q_positions is None:
        q_positions = jnp.broadcast_to(jnp.arange(sq), (b, sq))
    if kv_positions is None:
        kv_positions = jnp.broadcast_to(jnp.arange(k.shape[1]), (b, k.shape[1]))

    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    causal = q_positions[:, None, :, None] >= kv_positions[:, None, None, :]
    if kv_mask is not None:
        causal = jnp.logical_and(causal, kv_mask[:, None, None, :])
    logits = jnp.where(causal, logits, NEG_INF)
    probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out
